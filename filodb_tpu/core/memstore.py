"""TimeSeriesMemStore: per-dataset shards wiring ingest -> part-key index -> HBM store.

Reference: core/.../memstore/TimeSeriesMemStore.scala (shard map, ingestStream),
TimeSeriesShard.scala (the heart: partition set, Lucene index, ingest loop
:459/:1183, flush pipeline :771-:1048, recovery, eviction).

TPU-native shape of the same responsibilities:
  - partition lookup: host dict part-key-bytes -> part_id, resolved once per
    *distinct label set per container* (not per sample; the container's part_idx
    indirection makes sample->part_id a single vectorized numpy gather)
  - ingest: host staging buffers -> batched device scatter when the staging
    threshold is reached (one XLA call per flush, not per record)
  - flush groups & offset watermarks: group = part_id % num_groups; the group
    watermark advances when the group's staged samples land on device (and, once a
    ChunkSink is attached, when they are durably flushed) — recovery replays the
    bus from min(watermark), skipping below-watermark rows per group (ref:
    TimeSeriesShard.scala:180-184, doc/ingestion.md "Recovery and Persistence")
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from dataclasses import dataclass, field

import numpy as np

log = logging.getLogger(__name__)

# epoch-log sentinel: this visibility bump may have affected data at ANY
# timestamp (destructive mutations — purge, eviction, retention compaction,
# durable age-out). Fragments validated against a log containing it
# invalidate whole (query/incremental.py stable_before).
EPOCH_AFFECTS_ALL = -(1 << 62)

# The declared visibility surface (filolint epochcheck — analysis/
# epochcheck.py reads this dict from the AST; keep it a pure literal).
# Every function where query-visible store state changes must be named
# here, with the affected-timestamp class its bump records:
#   "batch_min_ts"      — the bump logs the minimum data timestamp the
#                         mutation touched (staged flush, recovery chunk
#                         load, purge end-time marks); per-step fragment
#                         validity survives for steps before it
#   "EPOCH_AFFECTS_ALL" — destructive: rows at arbitrary timestamps
#                         vanished (release/eviction, retention compaction,
#                         durable age-out); caches invalidate whole
#   "admit"             — series admission only: a partition with zero
#                         visible samples changes no query result, so no
#                         bump is required until the first staged flush
#                         lands its data (which bumps)
# ``visible_calls`` are the field-sensitive mutator shapes the checker
# hunts (self.<attr>.<method> / a local alias of self.<attr>): mutations
# of the arrays the query read path scans. ``admit_calls``/``admit_maps``
# are the admission-only shapes (declaration required, bump not).
# Undeclared mutation sites, bumps outside the shard lock, and
# EPOCH_AFFECTS_ALL bumps where a batch minimum is in scope are tier-1
# failures — see ANALYSIS.md "Epoch & visibility contracts".
EPOCH_SPEC = {
    "class": "TimeSeriesShard",
    "bump": "_bump_epoch_locked",
    "lock": "lock",
    "visible_calls": {
        "store": ("append", "compact", "free_rows"),
        "index": ("remove_part_keys", "update_end_time"),
        "sink": ("age_out", "age_out_commit"),
    },
    "admit_calls": {
        "index": ("add_part_key", "add_part_keys_bulk",
                  "add_part_keys_columnar"),
    },
    "admit_maps": ("_part_key_of_id", "_part_key_to_id"),
    "sites": {
        "staged_flush": {
            "fn": "TimeSeriesShard._flush_staged_locked",
            "affects": "batch_min_ts"},
        "partition_release": {
            "fn": "TimeSeriesShard._release_partitions_locked",
            "affects": "EPOCH_AFFECTS_ALL"},
        "purge_mark_ended": {
            "fn": "TimeSeriesShard.purge_expired_partitions",
            "affects": "batch_min_ts"},
        "compaction": {
            "fn": "TimeSeriesShard.flush",
            "affects": "EPOCH_AFFECTS_ALL"},
        "age_out": {
            "fn": "TimeSeriesShard.age_out_durable",
            "affects": "EPOCH_AFFECTS_ALL"},
        "recovery_chunk_load": {
            "fn": "TimeSeriesShard._recover_inner",
            "affects": "batch_min_ts"},
        "series_admit": {
            "fn": "TimeSeriesShard._create_series_locked",
            "affects": "admit"},
        "series_admit_bulk": {
            "fn": "TimeSeriesShard._bulk_create_locked",
            "affects": "admit"},
    },
}

from .chunkstore import SeriesStore
from .eviction import BloomFilter, CapacityEvictionPolicy, EvictionPolicy
from .filters import Filter
from .partkey_index import PartKeyIndex
from .record import RecordContainer
from .schemas import Schema, Schemas, part_key_bytes, part_key_of
from .store import (INDEX_FLAG_UNPARSEABLE, INDEX_GENESIS_BUCKET,
                    INDEX_RETIRE_BUCKET, INDEX_TOMBSTONE_BUCKET,
                    ChunkSetRecord, ChunkSink, encode_index_bucket,
                    labels_from_blob)
from ..utils.diagnostics import TimedRLock, assert_owned
from ..utils.metrics import (FILODB_INDEX_PERSISTED_BUCKETS,
                             FILODB_INDEX_RECOVER_MS,
                             FILODB_RETENTION_AGED_OUT_ROWS,
                             FILODB_RETENTION_ODP_ROWS,
                             FILODB_STORE_RESIDENCY_FALLBACK, registry)
from ..utils.tracing import SPAN_ODP_DURABLE, span

# _create_series_locked outcome distinct from "blocked, stage prefix first"
# (None): the tenant's cardinality quota shed this NEW series — the caller
# skips its samples (existing series are never affected)
SHED_PID = -2

# default granularity of persisted index time buckets (index.time_bucket)
DEFAULT_INDEX_BUCKET_MS = 6 * 3600 * 1000

# dense live runs at least this long load via ONE columnar bulk add at
# recovery; shorter runs stay per-key (bulk setup costs more than it saves)
RECOVER_BULK_MIN = 256


@dataclass
class StoreConfig:
    """Per-dataset store tuning (ref: core/.../store/IngestionConfig.scala + the
    store {} block of conf/timeseries-dev-source.conf)."""
    max_series_per_shard: int = 1 << 20
    samples_per_series: int = 1024          # device row capacity (ring via compaction)
    flush_batch_size: int = 65536           # staged samples triggering a device flush
    groups_per_shard: int = 16
    retention_ms: int = 3 * 3600 * 1000
    dtype: str = "float32"
    # maintain an i16 quantized mirror of f32 value columns (ops/narrow.py):
    # halves the HBM bytes the fused query path streams (bit-exact for
    # integer-valued series; raw-f32 fallback per row otherwise). OFF by
    # default: on this TPU generation the fused kernel is MXU-bound (band
    # matmuls), so fewer HBM bytes measured ~1.5ms/query SLOWER at 1M
    # series — enable on deployments where the value stream, not the MXU,
    # is the measured bottleneck
    narrow_mirror: bool = False
    # narrow-RESIDENT: after each flush the f32 value block compresses to
    # i16 (q, vmin, scale) + a raw-f32 cohort pool for non-quantizable rows
    # and the f32 array is FREED — ~2x value-retention per HBM byte. Appends
    # rehydrate (write buffers stay raw, like the reference's); the fused
    # query path streams the i16 state directly; general paths decode a
    # transient. Scalar f32 single-column stores only. (Back-compat alias
    # for compressed_residency="gauge".)
    narrow_resident: bool = False
    # which store shapes adopt the compressed-resident form after flush
    # (server knob: config.py store.compressed_residency):
    #   "off"   — raw f32/i64 blocks stay resident
    #   "gauge" — scalar f32 single-column stores: the narrowest decode
    #             variant carrying the data bit-exactly (ops/decodereg.py:
    #             delta8 anchor+i8 deltas for counters, quant16, delta16)
    #             + ts elision
    #   "all"   — gauge AND [S, C, B] histogram stores (i8/i16 2D-delta bucket
    #             blocks — the reference keeps ALL in-memory data compressed,
    #             histograms most of all: doc/compression.md "Histograms")
    compressed_residency: str = "off"
    # cohort-pool gate for compressed residency: the fraction of live rows
    # allowed to fail the bit-exactness contract (kept raw in the cohort
    # pool) before the store declines compression — beyond it, raw f32 is
    # the cheaper residency and the decline counts a
    # filodb_store_residency_fallback
    narrow_cohort_gate: float = 0.25

    def __post_init__(self):
        if self.compressed_residency not in ("off", "gauge", "all"):
            raise ValueError(
                f"compressed_residency must be off|gauge|all, "
                f"got {self.compressed_residency!r}")
        if not 0.0 <= self.narrow_cohort_gate <= 1.0:
            raise ValueError(
                f"narrow_cohort_gate must be in [0, 1], "
                f"got {self.narrow_cohort_gate!r}")

    def residency_mode(self) -> str:
        """Effective residency mode ("off" | "gauge" | "all"), folding the
        legacy narrow_resident flag in."""
        if self.compressed_residency != "off":
            return self.compressed_residency
        return "gauge" if self.narrow_resident else "off"


@dataclass
class ShardStats:
    rows_ingested: int = 0
    series_created: int = 0
    unknown_schema_dropped: int = 0
    partitions_purged: int = 0
    partitions_evicted: int = 0
    evicted_part_key_reingests: int = 0
    # NEW series births shed by the per-tenant cardinality limiter (their
    # samples dropped WITH the birth; existing-series samples always land)
    series_quota_shed: int = 0


class TimeSeriesShard:
    """All state for one shard of one dataset."""

    def __init__(self, dataset: str, schema: Schema, shard_num: int, config: StoreConfig,
                 device=None, sink: ChunkSink | None = None,
                 eviction_policy: EvictionPolicy | None = None):
        import jax.numpy as jnp
        self.dataset = dataset
        self.schema = schema
        self.shard_num = shard_num
        self.config = config
        self.index = PartKeyIndex()
        self._part_key_to_id: dict[bytes, int] = {}
        self._part_key_of_id: dict[int, bytes] = {}
        # native open-addressing part-key table (ref: PartitionSet.scala) —
        # batch-probed once per container on the ingest hot path; the dicts
        # above remain the source of truth (and the fallback when no
        # toolchain). Mirrored on create/release/recover.
        from . import native as _native
        # native inserts for NEW series are deferred and batched: one ctypes
        # call per container instead of one per key (see _flush_native_locked)
        self._pending_native: list = []
        self._native_ps = (_native.NativePartSet(config.max_series_per_shard)
                           if _native.available() else None)
        # hash each pid was INSERTED under (container-supplied for ingest):
        # removal must use the same value — recomputing could diverge from a
        # frame whose trailer hash mismatches its key bytes, stranding a
        # stale native entry that resolves to a freed slot
        self._pid_hash = (np.zeros(config.max_series_per_shard, np.uint64)
                          if self._native_ps is not None else None)
        # bumped on every partition release: invalidates batch-resolved pids
        self._release_epoch = 0
        # O(1) data-time lead: the max sample timestamp ever staged or
        # recovered into this shard. The retention router consults it per
        # query — a full last_ts scan there would cost O(max_series) on
        # every query's hot path (monotonic: purge/compact never lower it)
        self.lead_ms = 0
        # ingest/mutation watermark: bumped (under the shard lock) whenever
        # query-visible data changes — rows staged, partitions released,
        # retention compaction. The query result cache records the cluster
        # vector of these counters per entry; a vector mismatch means a
        # cached result could diverge from re-execution (query/engine.py
        # QueryResultCache). Served over /api/v1/epochs for peer probes.
        self.data_epoch = 0
        # per-bump provenance for INCREMENTAL serving (query/incremental.py):
        # each data_epoch bump appends (new epoch, min affected data ts) —
        # an append-type bump records the minimum timestamp that became
        # visible, a destructive bump (purge/eviction/compaction/age-out)
        # records EPOCH_AFFECTS_ALL. A cached per-step fragment recorded at
        # epoch e stays provably valid for steps t < min(min_ts of every
        # bump after e): only data at timestamps <= t can influence step t
        # (windows and lookback reach strictly backward). Bounded ring; a
        # gap (too many bumps since e) reads as "unknown" and the fragment
        # fully invalidates — never a stale serve.
        self._epoch_log: deque[tuple[int, int]] = deque(maxlen=256)
        self._stage_min_ts: int | None = None
        self._stage_max_ts = 0
        # QUERY-VISIBLE data-time lead: advances when staged rows actually
        # land on the device store (or recovery loads chunks), unlike
        # lead_ms which advances at STAGE time. Streaming subscriptions
        # chase this one — an increment cut at the staged (not yet
        # visible) lead would serve a step without its samples and never
        # re-deliver it (the cursor only moves forward)
        self.visible_lead_ms = 0
        # True while recover() is rebuilding this shard (queries are
        # admitted during recovery, but an empty selection seen in the
        # window must not be CACHED as proof of emptiness — the negative
        # cache consults this; ref: RecoveryInProgress status)
        self.recovering = False
        # purged slots available for reuse + membership filter of evicted keys
        # (ref: TimeSeriesShard evictedPartKeys bloom :93-96, checked on ingest :1092)
        self._free_pids: list[int] = []
        self._evicted_keys = BloomFilter()
        # memoized RangeVectorKey per queried pid: the dict-encoded index
        # reconstructs labels on demand, so query leaves cache the key object
        # (built once per series lifetime, dropped on purge)
        self._rv_keys: dict[int, object] = {}
        self.eviction_policy = eviction_policy or CapacityEvictionPolicy()
        # guards the donating device append vs concurrent query dispatch: the
        # scatter invalidates (donates) the old store buffers, so query leaves
        # must capture arrays AND dispatch their kernels under this lock
        # (ref analog: per-shard single ingest thread + ChunkMap read locks)
        self.lock = TimedRLock(f"shard-{shard_num}-lock", order_class="shard",
                               order_index=shard_num)
        # per-slot release counters (purge/eviction): lazily materialized
        # query artifacts (LazyKeys) snapshot the epochs of THEIR pids and
        # detect slot reuse without being invalidated by unrelated releases
        self.slot_epoch = np.zeros(config.max_series_per_shard, np.uint32)
        self._device = device
        self._dtype = jnp.float64 if config.dtype == "float64" else jnp.float32
        self.bucket_les: np.ndarray | None = None
        if schema.is_histogram:
            # histogram stores are created lazily: the bucket scheme arrives with
            # the first container (ref: BinaryHistogram carries its bucket scheme)
            self.store = None
        else:
            self.store = self._make_store()
            self.store.owner_lock = self.lock
        # staging buffers (host)
        self._stage_pid: list[np.ndarray] = []
        self._stage_ts: list[np.ndarray] = []
        self._stage_val: list[np.ndarray] = []
        self._staged = 0
        # per-group ingest offset watermarks (ref: checkpoint per flush group)
        self.group_watermarks = np.full(config.groups_per_shard, -1, np.int64)
        self._pending_offset = -1
        # persistence (ref: doFlushSteps — encode + sink write + checkpoint commit)
        self.sink = sink
        G = config.groups_per_shard
        self._pending_chunks: list[list] = [[] for _ in range(G)]   # per group (pids, ts, vals)
        self._pending_group_offset = np.full(G, -1, np.int64)
        # pids of chunk snapshots currently being written by a flush_group
        # call (token -> unique pids). While a snapshot is outside
        # _pending_chunks its pids are invisible to the release-time scrubs,
        # so eviction/purge must not release them: a release during the sink
        # write would persist a dead pid's samples after its tombstone and,
        # after slot reuse, attribute them to the slot's next owner on
        # recovery. Protected here; scrub-on-requeue stays as defense.
        self._inflight_flush: dict[object, np.ndarray] = {}
        # one flush at a time per group (ref: createFlushTask — a group's
        # flush task is singular). Beyond exactly-once, this gives callers a
        # happens-after guarantee: when flush_group(g) returns, any in-flight
        # flush of g that had already snapshotted the pending chunks has
        # finished its sink write AND its inline-downsample publish — without
        # it, a caller could see an empty pending list, return immediately,
        # and read the sink before the concurrent flusher published.
        # Ordered TimedRLocks (not bare threading.Lock): the global order
        # group_flush < sink < shard is asserted under FILODB_LOCK_DEBUG=1
        # (diagnostics.LOCK_ORDER) and checked statically by filolint.
        self._group_flush_locks = [
            TimedRLock(f"shard-{shard_num}-group-{g}-flush",
                       order_class="group_flush", order_index=g)
            for g in range(G)]
        # ordered part-key event log awaiting durable persist: creations
        # (pid, labels, start) and release tombstones (pid, {}, -1) in event
        # order, so recovery's last-entry-wins resolves slot reuse correctly
        # regardless of which thread drains the log
        self._partkey_log: list[tuple[int, dict, int]] = []
        # serializes drain+write batches (ordered: sink < shard)
        self._sink_lock = TimedRLock(f"shard-{shard_num}-sink-lock",
                                     order_class="sink",
                                     order_index=shard_num)
        self._meta_written = False
        # inline downsampling at flush (ref: ShardDownsampler + DownsamplePublisher):
        # (resolution_ms, callback(shard, {agg: (pids, ts, vals)}))
        self.downsample: tuple | None = None
        # ingest cardinality governance (core/cardinality.py): per-tenant
        # active-series accounting + birth limiter, shared per dataset; the
        # shard consults it under its own lock at every series creation
        self.governor = None
        # durable index time buckets (index.time_bucket; 0 disables): the
        # part-key log drain also appends columnar index frames so a
        # restarted shard recovers the index from the ring instead of
        # rebuilding per key (ref: persisted Lucene time-bucket blobs)
        self.index_bucket_ms = DEFAULT_INDEX_BUCKET_MS
        # True once index.log carries a GENESIS snapshot covering this
        # shard's full history (written at the first drain of a fresh
        # shard, or after a recovery that had to fall back to
        # partkeys.log) — recovery only trusts the log from its last
        # genesis marker, so an upgraded/toggled shard never loses series
        self._index_log_seeded = False
        self.stats = ShardStats()

    # -- partition resolution ----------------------------------------------

    def _resolve_segment_locked(self, container, mapping, first_ts, start) -> int:
        """Resolve label sets from ``start`` onward to dense part ids, creating
        new partitions (and index entries) as needed. Under slot pressure, evict
        least-recently-active partitions to make room (ref: TimeSeriesShard
        ``ensureFreeSpace``, :1315). Returns the index one past the last label
        set resolved: when a new slot is needed but every eviction candidate is
        a series resolved earlier in this same container (its samples not yet
        staged), resolution stops there so the caller can stage the prefix —
        which makes those series evictable — and re-enter.

        Hot path: the whole container probes the native part-key table in ONE
        call (ref: PartitionSet zero-alloc probes under
        getOrAddPartitionAndIngest, TimeSeriesShard.scala:1183); only misses
        (new series) take the per-set creation path. A release during the
        loop (eviction making room) invalidates the batch snapshot, so the
        remaining tail re-probes."""
        n_sets = len(container.label_sets)
        keys, hashes = container.resolved_keys()
        protected: set[int] = set()
        i = start
        while i < n_sets:
            if self._native_ps is not None:
                self._flush_native_locked()   # re-probes must see this batch
                pids = self._native_ps.resolve_batch(hashes[i:], keys[i:])
            else:
                g = self._part_key_to_id.get
                pids = np.fromiter((g(k, -1) for k in keys[i:]), np.int32,
                                   count=n_sets - i)
            if i == start and self._bulk_create_locked(container, mapping,
                                                       pids, i, first_ts):
                return n_sets
            epoch0 = self._release_epoch
            seg = i
            for j in range(seg, n_sets):
                pid = int(pids[j - seg])
                if pid < 0:
                    pid = self._create_series_locked(
                        container.label_sets[j], keys[j], int(hashes[j]),
                        first_ts, protected)
                    if pid is None:
                        return j   # blocked on this container's own series
                mapping[j] = pid
                if pid >= 0:       # SHED_PID: quota-shed birth, no slot
                    protected.add(pid)
                i = j + 1
                if self._release_epoch != epoch0 and i < n_sets:
                    break          # eviction ran: re-probe the tail
        return n_sets

    BULK_CREATE_MIN = 512      # below this, per-key creation wins

    def _bulk_create_locked(self, container, mapping, probe_pids,
                            seg: int, first_ts) -> bool:
        """Registration fast path: admit ALL of a probe's misses in one bulk
        pass — dense pid assignment, bulk index add from the container's
        canonical key bytes, one dict update for the key maps (ref:
        TimeSeriesShard.scala:1183 getOrAddPartitionAndIngest +
        PartKeyLuceneIndex.addPartKey; jmh IngestionBenchmark is the bar).

        Only when nothing per-key can happen: enough free capacity for every
        miss without eviction (and none ever evicted — the bloom re-ingest
        accounting stays exact), no reusable slots (dense append only), and
        no ignored shard-key tags (the index stores ALL labels; key bytes
        drop ignored ones). Returns False untouched otherwise."""
        miss = np.nonzero(probe_pids < 0)[0]
        if len(miss) < self.BULK_CREATE_MIN:
            return False
        if (self._free_pids or self.stats.partitions_evicted
                or self.schema.options.ignore_shard_key_tags
                or len(self.index) + len(miss) > self.config.max_series_per_shard):
            return False
        keys, hashes = container.resolved_keys()
        label_sets = container.label_sets
        n_sets = len(label_sets)
        base = len(self.index)
        new_pids = np.arange(base, base + len(miss), dtype=np.int64)
        new_keys = [keys[seg + j] for j in miss.tolist()]
        # builder interning makes label sets unique, but hand-built containers
        # may repeat a key — the per-key path dedups those; bulk cannot
        if len(set(new_keys)) != len(new_keys):
            return False
        gov_tenant = None
        if self.governor is not None and self.governor.limit is not None:
            # all-or-nothing block reservation; mixed-tenant batches (or a
            # batch that does not fit) take the per-key path, which sheds
            # series-precisely
            tenants = {self.governor.tenant_of(label_sets[seg + int(j)])
                       for j in miss}
            if len(tenants) != 1:
                return False
            gov_tenant = tenants.pop()
            if not self.governor.admit_block(gov_tenant, len(miss)):
                return False
        # columnar fast path: the builder's per-label columns skip pair-bytes
        # parsing entirely (one dict probe per value); only valid when the
        # whole container is new series (columns align 1:1 with the misses)
        added = False
        if (container.label_columns is not None and seg == 0
                and len(miss) == n_sets):
            fixed, vary, cols = container.label_columns
            added = self.index.add_part_keys_columnar(
                new_pids, fixed, vary, cols, first_ts)
        if not added:
            counts_hint = np.fromiter((len(label_sets[seg + j])
                                       for j in miss.tolist()), np.int64,
                                      count=len(miss))
            if not self.index.add_part_keys_bulk(new_pids, new_keys, first_ts,
                                                 counts_hint=counts_hint):
                if gov_tenant is not None:   # reservation rolls back with us
                    self.governor.retire(gov_tenant, len(miss))
                return False
        pid_list = new_pids.tolist()
        self._part_key_to_id.update(zip(new_keys, pid_list))
        self._part_key_of_id.update(zip(pid_list, new_keys))
        if self._native_ps is not None:
            # straight to the native table (array form, no per-entry tuples);
            # deferred inserts must land FIRST to keep insertion order sane
            hs = hashes[seg + miss]
            self._flush_native_locked()
            self._native_ps.insert_arrays(hs, new_keys, new_pids.astype(np.int32))
            self._pid_hash[new_pids] = hs
        if self.sink is not None:
            # 4-tuple form: labels stay a (sequence, index) reference so the
            # dicts build at flush time OUTSIDE the shard lock — a 1M-series
            # batch must not pay n dict builds in the locked ingest path
            self._partkey_log.extend(
                (pid, label_sets, seg + j, first_ts)
                for pid, j in zip(pid_list, miss.tolist()))
        self.stats.series_created += len(miss)
        seg_map = mapping[seg:seg + (n_sets - seg)]
        hit = probe_pids >= 0
        seg_map[hit] = probe_pids[hit]
        seg_map[miss] = new_pids
        return True

    def _flush_native_locked(self) -> None:
        """Land deferred part-key inserts in one native call. Must run
        before any native probe or removal: within a container, creations are
        visible through _part_key_to_id; across operations the native table
        is the source of truth."""
        if self._pending_native:
            self._native_ps.insert_batch(self._pending_native)
            self._pending_native.clear()

    def _create_series_locked(self, labels, pk: bytes, ph: int, first_ts,
                              protected) -> int | None:
        """Admit a new series: assign a slot (evicting under pressure), index
        it, and mirror the key into the native table. None when every
        eviction candidate is protected (caller stages its prefix first)."""
        S = self.config.max_series_per_shard
        # distinct label sets can share one part key (ignore_shard_key_tags):
        # an earlier creation in this same batch snapshot must win, not be
        # double-created (the batch probe predates it)
        pid = self._part_key_to_id.get(pk)
        if pid is not None:
            return pid
        gov_tenant = None
        if self.governor is not None:
            # series-birth limiter: a tenant at quota sheds the NEW part key
            # (and only it) — samples for existing series are unaffected,
            # which is the whole multi-tenant point (a noisy tenant's label
            # explosion must not evict everyone else's series). Checked
            # BEFORE eviction work so an over-quota birth never evicts
            # someone else's series to make room it will not use.
            gov_tenant = self.governor.tenant_of(labels)
            if not self.governor.admit(gov_tenant):
                self.stats.series_quota_shed += 1
                self.governor.count_shed("shard", gov_tenant)
                return SHED_PID
        if not self._free_pids and len(self.index) >= S:
            if not self._ensure_free_space_locked(protected):
                # creation BLOCKED (caller stages its prefix and retries,
                # re-admitting then): the reservation must roll back or
                # every blocked attempt permanently inflates the tenant's
                # active count
                if gov_tenant is not None:
                    self.governor.retire(gov_tenant)
                return None
        if pk in self._evicted_keys:
            self.stats.evicted_part_key_reingests += 1
        pid = self._free_pids.pop() if self._free_pids else len(self.index)
        self._part_key_to_id[pk] = pid
        self._part_key_of_id[pid] = pk
        if self._native_ps is not None:
            self._pending_native.append((ph, pk, pid))
            self._pid_hash[pid] = ph
        self.index.add_part_key(pid, labels, start_time=first_ts)
        if self.sink is not None:
            self._partkey_log.append((pid, labels, first_ts))
        self.stats.series_created += 1
        return pid

    def _ensure_free_space_locked(self, protected: set[int]) -> bool:
        """Evict the least-recently-active partitions so a new series can be
        admitted instead of erroring (ref: TimeSeriesShard.ensureFreeSpace
        :1315 + evictedPartKeys bloom :93-96). Eviction frees the HBM rows,
        tombstones the index entries, and records the part keys so a returning
        series is detected. Returns False when every occupied slot belongs to
        ``protected`` (series whose samples are still pending in the caller's
        container) and nothing can move."""
        self._flush_staged_locked()   # staged rows must land before slots move
        occupied = np.fromiter(self._part_key_of_id.keys(), np.int64,
                               count=len(self._part_key_of_id))
        if protected:
            occupied = occupied[~np.isin(
                occupied, np.fromiter(protected, np.int64, count=len(protected)))]
        if self._inflight_flush:
            # snapshots mid-write (see _inflight_flush): releasing these pids
            # would persist dead samples after their tombstone
            inflight = np.unique(np.concatenate(list(self._inflight_flush.values())))
            occupied = occupied[~np.isin(occupied, inflight)]
        if occupied.size == 0:
            return False
        # amortize: evict a small batch, least-recently-active first
        k = min(occupied.size, max(1, self.config.max_series_per_shard // 16))
        last = self.store.last_ts[occupied]
        victims = (occupied[np.argpartition(last, k - 1)[:k]]
                   if k < occupied.size else occupied)
        self._release_partitions_locked(victims.astype(np.int32))
        self.stats.partitions_evicted += int(victims.size)
        return True

    def _bump_epoch_locked(self, min_affected_ms: int) -> None:
        """Advance the visibility watermark (caller holds the shard lock),
        recording the minimum data timestamp the mutation can have touched
        — ``EPOCH_AFFECTS_ALL`` for destructive changes. EVERY data_epoch
        bump must route through here: the incremental-serving validity rule
        requires one log entry per bump (a gap reads as full
        invalidation)."""
        self.data_epoch += 1
        self._epoch_log.append((self.data_epoch, int(min_affected_ms)))

    def epoch_state(self) -> tuple[int, list[tuple[int, int]]]:
        """``(data_epoch, recent (epoch, min affected ts) entries)`` read
        coherently under the shard lock — the substrate of per-step
        fragment validity (local probes read this directly; peers serve it
        over ``/api/v1/epochs?log=1``)."""
        with self.lock:
            return self.data_epoch, list(self._epoch_log)

    def _release_partitions_locked(self, pids: np.ndarray) -> None:
        """Shared teardown for purge and eviction: drop id maps (recording the
        keys in the evicted-keys filter), tombstone index entries, free HBM
        rows, and make the slots reusable. Durable tombstones (queued here,
        written outside the lock by the next drain point) ensure recovery
        neither resurrects the series nor attributes its persisted chunks to a
        later owner of the reused slot."""
        pid_list = pids.tolist()
        self.slot_epoch[pids] += 1
        self._release_epoch += 1
        # result-cache watermark: data gone (destructive — a released
        # series held samples at arbitrary timestamps)
        self._bump_epoch_locked(EPOCH_AFFECTS_ALL)
        if self.governor is not None:
            # labels still resolve here (the index tombstones below):
            # churned-out series release their tenant's quota slots
            for pid in pid_list:
                self.governor.retire(
                    self.governor.tenant_of(self.index.labels_of(pid)))
        for pid in pid_list:
            pk = self._part_key_of_id.pop(pid, None)
            if pk is not None:
                del self._part_key_to_id[pk]
                self._evicted_keys.add(pk)
                if self._native_ps is not None:
                    self._flush_native_locked()
                    # remove under the hash it was INSERTED with (see
                    # _pid_hash) — never a recomputed one
                    self._native_ps.remove(int(self._pid_hash[pid]), pk)
        self.index.remove_part_keys(pids)
        self.store.free_rows(pids)
        for pid in pid_list:
            self._rv_keys.pop(pid, None)
        self._free_pids.extend(pid_list)
        # open downsample buckets of released partitions must never emit: the
        # slot's next owner would be attributed the dead series' data
        if self.downsample is not None and hasattr(self.downsample[1], "drop_pids"):
            self.downsample[1].drop_pids(pid_list)
        if self.sink is not None:
            # unpersisted samples of a released partition must never reach the
            # sink: a later flush_group would write them under a pid whose slot
            # may belong to a new owner by recovery time (the purge path avoids
            # this by refusing to purge pids with pending chunks; eviction
            # cannot refuse, so it scrubs them instead)
            gone_arr = np.asarray(pid_list, np.int32)
            for g, pending in enumerate(self._pending_chunks):
                if not pending:
                    continue
                kept = []
                for pids_, ts_, vals_ in pending:
                    m = ~np.isin(pids_, gone_arr)
                    if m.all():
                        kept.append((pids_, ts_, vals_))
                    elif m.any():
                        kept.append((pids_[m], ts_[m], vals_[m]))
                self._pending_chunks[g] = kept
            self._partkey_log.extend((pid, {}, -1) for pid in pid_list)

    def _flush_partkey_log(self) -> None:
        """Persist queued part-key events. The drain and the sink write happen
        inside one critical section (``_sink_lock``, NOT the shard lock — sink
        I/O must not stall ingest/query threads): two concurrent drains could
        otherwise write their batches out of event order, letting a released
        slot's tombstone land after its new owner's key and erase that series
        on recovery."""
        if self.sink is None:
            return
        with self._sink_lock:
            with self.lock:
                log, self._partkey_log = self._partkey_log, []
            if not log:
                return
            try:
                # rows are (pid, labels, start) or the bulk path's deferred
                # (pid, labels_seq, idx, start) — materialized here, off the
                # shard lock
                rows = []
                for e in log:
                    if len(e) == 3:
                        pid, labels, start = e
                    else:
                        pid, seq, i, start = e
                        labels = seq[i]
                    rows.append((int(pid), labels, int(start)))
                # index time buckets FIRST, then the JSON part-key log: a
                # crash between the two leaves index.log AHEAD (extra events
                # replay idempotently, latest-per-pid wins), never behind —
                # so recovery may trust the columnar log whenever present.
                # A failed write requeues the whole batch; the retry's
                # duplicate frames dedup the same way.
                self._persist_index_buckets(rows)
                self.sink.write_part_keys(self.dataset, self.shard_num, rows)
            except Exception:
                # transient sink failure: the events must survive for retry —
                # prepend (they predate anything queued meanwhile)
                with self.lock:
                    self._partkey_log = log + self._partkey_log
                raise

    @staticmethod
    def _index_entry(pid: int, labels: dict, start: int) -> tuple:
        """(pid, start, blob, flags) for one index.log entry. Labels the
        pair encoding cannot represent (NUL in a name/value, the pair
        separator in a name) get the UNPARSEABLE flag — recovery then
        refuses the whole frames path instead of loading split garbage."""
        for k, v in labels.items():
            if "\x00" in k or "\x00" in v or "\x01" in k:
                return (pid, start, b"", INDEX_FLAG_UNPARSEABLE)
        return (pid, start, part_key_bytes(sorted(labels.items()), ()), 0)

    def _write_index_genesis(self) -> None:
        """Append a GENESIS frame: a complete live-series snapshot, the
        trust anchor recovery applies the log from. Written once per shard
        lifetime — at the first drain of a fresh shard, or right after a
        recovery that had to rebuild from partkeys.log (upgraded shard,
        persistence toggled back on). Caller holds ``_sink_lock`` or is
        single-threaded recovery; takes the shard lock for the snapshot
        (sink < shard is the declared order)."""
        with self.lock:
            snapshot = [self._index_entry(pid, self.index.labels_of(pid),
                                          self.index.start_time(pid))
                        for pid in sorted(self._part_key_of_id)]
        self.sink.write_index_bucket(
            self.dataset, self.shard_num,
            encode_index_bucket(INDEX_GENESIS_BUCKET, snapshot))
        self._index_log_seeded = True

    def _persist_index_buckets(self, rows) -> None:
        """Append columnar index frames for one part-key drain batch,
        grouped into CONSECUTIVE same-bucket runs (dict-grouping could
        reorder a tombstone past a slot-reusing re-creation inside one
        batch — event order is what last-entry-wins recovery relies on).
        Creations bucket by their start time; tombstones ride the
        dedicated tombstone pseudo-bucket."""
        if not self.index_bucket_ms \
                or not hasattr(self.sink, "write_index_bucket"):
            return
        if not self._index_log_seeded:
            self._write_index_genesis()
        frames: list[bytes] = []
        cur_bucket: int | None = None
        cur: list[tuple] = []
        for pid, labels, start in rows:
            if labels:
                entry = self._index_entry(pid, labels, start)
                bucket = (start // self.index_bucket_ms) \
                    * self.index_bucket_ms
            else:
                entry = (pid, start, b"", 0)
                bucket = INDEX_TOMBSTONE_BUCKET
            if bucket != cur_bucket and cur:
                frames.append(encode_index_bucket(cur_bucket, cur))
                cur = []
            cur_bucket = bucket
            cur.append(entry)
        if cur:
            frames.append(encode_index_bucket(cur_bucket, cur))
        for frame in frames:
            self.sink.write_index_bucket(self.dataset, self.shard_num, frame)
        if frames:
            registry.counter(FILODB_INDEX_PERSISTED_BUCKETS,
                             {"dataset": self.dataset,
                              "shard": str(self.shard_num)}) \
                .increment(len(frames))

    # -- ingest -------------------------------------------------------------

    def _make_store(self, width_hint: int = 0) -> SeriesStore:
        """Device store shaped by the schema: multi-value-column schemas get
        one array per data column sharing ts/n (Schema.col_layout); legacy
        single-column schemas keep the flat scalar/histogram layout
        (``width_hint``: bucket count of a les-less 2-D container)."""
        nb = len(self.bucket_les) if self.bucket_les is not None else 0
        if not nb and not self.schema.is_multi_column:
            nb = width_hint
        layout = (self.schema.col_layout(nb)
                  if self.schema.is_multi_column else None)
        store = SeriesStore(self.config.max_series_per_shard,
                            self.config.samples_per_series,
                            dtype=self._dtype, device=self._device,
                            nbuckets=nb, layout=layout,
                            default_col=self.schema.value_column)
        store.cohort_gate = self.config.narrow_cohort_gate
        return store

    def ingest(self, container: RecordContainer, offset: int = -1,
               recovery_watermarks: np.ndarray | None = None) -> None:
        """Ingest one container. During recovery replay, rows whose flush group
        already persisted past ``offset`` are skipped (ref: TimeSeriesShard
        recovery skips rows below the group watermark, :180-184)."""
        if container.schema.schema_id != self.schema.schema_id:
            with self.lock:   # stats are shard state: writers race otherwise
                self.stats.unknown_schema_dropped += len(container)
            return
        if self.store is None:
            # double-checked under the shard lock: two writer threads racing
            # the first container would each build a store and one's would be
            # silently dropped (with its bucket scheme)
            with self.lock:
                if self.store is None:
                    self.bucket_les = (np.asarray(container.bucket_les)
                                       if container.bucket_les is not None
                                       else None)
                    width = (container.values.shape[1]
                             if container.values.ndim == 2 else 0)
                    self.store = self._make_store(width_hint=width)
                    self.store.owner_lock = self.lock
        n_sets = len(container.label_sets)
        if n_sets == 0 or len(container) == 0:
            return
        mapping = np.empty(n_sets, np.int32)
        first_ts = int(container.ts.min())
        # resolution + staging share the shard lock: HTTP writers / gateways may
        # ingest from several threads, and query paths call flush(). Resolution
        # is segmented: when slot pressure forces eviction but every candidate
        # is a series from this very container, the resolved prefix is staged
        # and landed on device first so those series become evictable.
        with self.lock:
            start = 0
            while start < n_sets:
                done = self._resolve_segment_locked(container, mapping,
                                                    first_ts, start)
                self._stage_segment_locked(container, mapping, start, done,
                                           offset, recovery_watermarks)
                if done < n_sets:
                    self._flush_staged_locked()
                start = done
        if self._staged >= self.config.flush_batch_size:
            self.flush()

    def _stage_segment_locked(self, container, mapping, start, done, offset,
                              recovery_watermarks) -> None:
        """Stage the samples of label sets ``[start, done)`` (the common case —
        the whole container — avoids the mask)."""
        if start == 0 and done == len(container.label_sets):
            pids = mapping[container.part_idx]
            ts, vals = container.ts, container.values
        else:
            sel = (container.part_idx >= start) & (container.part_idx < done)
            pids = mapping[container.part_idx[sel]]
            ts, vals = container.ts[sel], container.values[sel]
        if len(pids) and pids.min() < 0:
            # quota-shed births (SHED_PID): drop exactly their samples —
            # every other series in the container lands normally
            keep = pids >= 0
            pids, ts, vals = pids[keep], ts[keep], vals[keep]
        if recovery_watermarks is not None:
            keep = recovery_watermarks[pids % self.config.groups_per_shard] < offset
            if not keep.all():
                pids, ts, vals = pids[keep], ts[keep], vals[keep]
        if len(pids) == 0:
            return
        self._stage_pid.append(pids)
        self._stage_ts.append(ts)
        self._stage_val.append(vals)
        # min staged ts feeds the epoch log at the flush visibility point:
        # steps older than it stay provably cacheable across the bump
        batch_min = int(ts.min())
        if self._stage_min_ts is None or batch_min < self._stage_min_ts:
            self._stage_min_ts = batch_min
        lead = int(ts.max())
        if lead > self._stage_max_ts:
            self._stage_max_ts = lead
        if lead > self.lead_ms:
            self.lead_ms = lead
        self._staged += len(ts)
        self._pending_offset = max(self._pending_offset, offset)
        self.stats.rows_ingested += len(ts)
        if self.sink is not None:
            # one stable argsort + split instead of a full-array mask per
            # group: the staging path runs per container on the ingest hot
            # loop, and G masks are G passes over the batch
            groups = pids % self.config.groups_per_shard
            order = np.argsort(groups, kind="stable")
            gs = groups[order]
            for idx in np.split(order, np.flatnonzero(np.diff(gs)) + 1):
                if not len(idx):
                    continue
                g = int(groups[idx[0]])
                self._pending_chunks[g].append((pids[idx], ts[idx], vals[idx]))
                self._pending_group_offset[g] = max(self._pending_group_offset[g], offset)

    def _flush_staged_locked(self) -> int:
        """Land staged samples on the device store (caller holds the lock)."""
        if not self._staged:
            return 0
        # result-cache watermark bumps at the VISIBILITY point: staged rows
        # are host-side until this scatter, so bumping at stage time would
        # let a query cached in the stage->flush window validate against a
        # vector that already includes the not-yet-visible rows — a stale
        # hit after the flush (review finding, PR 8)
        self._bump_epoch_locked(self._stage_min_ts
                                if self._stage_min_ts is not None
                                else EPOCH_AFFECTS_ALL)
        self._stage_min_ts = None
        # the staged rows become query-visible with this scatter
        if self._stage_max_ts > self.visible_lead_ms:
            self.visible_lead_ms = self._stage_max_ts
        pids = np.concatenate(self._stage_pid)
        ts = np.concatenate(self._stage_ts)
        vals = np.concatenate(self._stage_val, axis=0)
        self._stage_pid.clear(); self._stage_ts.clear(); self._stage_val.clear()
        self._staged = 0
        return self.store.append(pids, ts, vals)

    def flush(self) -> int:
        """Push staged samples to the device store; advance group watermarks.
        Applies device backpressure OUTSIDE the lock (SeriesStore.throttle):
        a hot ingest loop must run at the device's retirement rate, or its
        dispatch backlog starves concurrent query fetches."""
        with self.lock:
            staged = bool(self._staged)
            written = self._flush_staged_locked() if staged else 0
        residency = self.config.residency_mode()
        if not staged:
            # nothing new — but a purge/compact since the last flush may have
            # rehydrated a compressed-resident store; re-adopt, else the
            # quiesced shard silently sits at raw 12B/sample residency
            if residency != "off":
                self._compress_resident_two_phase(residency)
            return 0
        self.store.throttle()
        if self.config.narrow_mirror and residency == "off":
            # flush-time rebuild, outside the lock: the build streams the
            # whole store and fetches the ok flags — queries only CONSULT.
            # (Pointless alongside compressed residency — the i16 state IS
            # the store there, and refresh would read the freed f32 block.)
            self.store.narrow.refresh(self.store)
        if self.sink is None and self._pending_offset >= 0:
            # without a durable sink, device residency is the only watermark
            with self.lock:
                self.group_watermarks[:] = self._pending_offset
        # capacity pressure -> compact out data older than retention
        # (policy pluggable; ref: PartitionEvictionPolicy.scala)
        if self.eviction_policy.should_evict(self.store, self.config):
            cutoff = int(self.store.last_ts.max(initial=0)) - self.config.retention_ms
            with self.lock:
                self.store.compact(cutoff)
                # result-cache watermark: rows aged out (destructive)
                self._bump_epoch_locked(EPOCH_AFFECTS_ALL)
        if residency != "off":
            # adopt/refresh the compressed-resident state AFTER any compact
            # (compact rehydrates — compressing first would be discarded
            # work). Two-phase: the streaming build + host fetches run
            # OUTSIDE the shard lock; only the swap takes it.
            self._compress_resident_two_phase(residency)
        return written

    def _compress_resident_two_phase(self, mode: str = "gauge") -> None:
        """Build the compressed-resident state without the shard lock, then
        swap under it iff nothing mutated meanwhile (a racing append donates
        the very buffers the build streams — detected and retried next
        flush; ref: the NarrowMirror outside-the-lock rule). ``mode`` gates
        which store shapes compress (histograms only under "all")."""
        st = self.store
        if st is None:
            return
        if st.nbuckets and mode != "all":
            return
        epoch0 = st.mutation_epoch()
        # idempotence: fully compressed already, or nothing mutated since the
        # last (possibly declined) attempt — a declined 25%-gate store must
        # not re-run the full-store build on every empty flush tick
        if st._val_compressed and (st._ts_elided
                                   or st.grid_info() is None):
            return
        if getattr(self, "_last_compress_epoch", None) == epoch0:
            return
        self._last_compress_epoch = epoch0
        try:
            prep = st.compress_prepare(hist=mode == "all")
        except RuntimeError:
            return                 # racing donation invalidated the build
        if prep is None:
            if st.residency_decline is not None:
                # the store WANTED compression and the data refused the
                # ok-contract: "tried and fell back" must be a visible
                # signal, not a silent raw-residency downgrade
                registry.counter(FILODB_STORE_RESIDENCY_FALLBACK,
                                 {"reason": st.residency_decline}).increment()
            return
        with self.lock:
            if st.mutation_epoch() == epoch0:
                st.compress_commit(prep)

    # -- persistence flush pipeline (ref: TimeSeriesShard.doFlushSteps :814) --

    def flush_group(self, group: int) -> int:
        """Encode and persist one flush group's pending samples, then commit its
        checkpoint atomically after the write (ref: :989 writeChunks ->
        :1048 commitCheckpoint). Serialized per group — see
        ``_group_flush_locks``. Returns chunkset record count."""
        if self.sink is None:
            return 0
        with self._group_flush_locks[group]:
            return self._flush_group_serialized(group)

    def _flush_group_serialized(self, group: int) -> int:
        self.flush()                      # device state first
        token = object()
        with self.lock:
            pending = self._pending_chunks[group]
            self._pending_chunks[group] = []
            # per-sample-batch slot epochs: if the persist below fails and a
            # release ran meanwhile, the requeue scrubs exactly the released
            # (possibly reused) slots' samples
            pend_epochs = [self.slot_epoch[p].copy() for (p, _, _) in pending]
            if pending:
                self._inflight_flush[token] = np.unique(
                    np.concatenate([p for (p, _, _) in pending]))
        try:
            # part-key events (creations + tombstones, in order) land before
            # the chunks that reference them. Order matters: the chunk
            # snapshot is taken FIRST — every pid in it was resolved (and so
            # logged) before its samples were staged, hence this drain
            # necessarily covers it. A drain before the snapshot would let a
            # concurrently-created series slip its chunks into this flush
            # with its key still queued.
            self._flush_partkey_log()
            if not pending:
                return 0
            pids = np.concatenate([p for p, _, _ in pending])
            ts = np.concatenate([t for _, t, _ in pending])
            vals = np.concatenate([v for _, _, v in pending])
            order = np.argsort(pids, kind="stable")
            pids, ts, vals = pids[order], ts[order], vals[order]
            bounds = np.concatenate([[0], np.nonzero(np.diff(pids))[0] + 1,
                                     [len(pids)]])
            layout = None
            if self.schema.is_multi_column:
                nb = len(self.bucket_les) if self.bucket_les is not None else 0
                layout = tuple(self.schema.col_layout(nb))
            records = [
                ChunkSetRecord(int(pids[bounds[i]]), ts[bounds[i]:bounds[i + 1]],
                               vals[bounds[i]:bounds[i + 1]], layout)
                for i in range(len(bounds) - 1)
            ]
            if self.bucket_les is not None and not self._meta_written:
                if hasattr(self.sink, "write_meta"):
                    self.sink.write_meta(self.dataset, self.shard_num,
                                         {"bucket_les": list(map(float, self.bucket_les))})
                self._meta_written = True
            self.sink.write_chunkset(self.dataset, self.shard_num, group, records)
        except Exception:
            # transient sink failure must not lose the snapshot: requeue it
            # for the next flush attempt. A fully-written duplicate frame from
            # a partially-completed attempt is deduped at recovery replay by
            # the store's out-of-order drop; a torn tail frame is skipped by
            # the sink reader (WAL semantics). The requeue puts the pids back
            # in _pending_chunks where the release-time scrubs see them, so
            # the inflight token can be dropped with the snapshot re-queued.
            with self.lock:
                self._requeue_pending_locked(group, pending, pend_epochs)
                self._inflight_flush.pop(token, None)
            raise
        try:
            # inline downsample runs after the chunks are durably written; a
            # failure here must not kill the ingest thread — the streaming
            # downsampler retains its accumulators and retries next flush.
            # Still under the inflight token: a release between the sink
            # write and this add would otherwise let the dead pid's samples
            # rebuild an open bucket AFTER drop_pids scrubbed it, and the
            # claim-generation check cannot poison a claim taken later
            if self.downsample is not None and vals.ndim == 1:
                res_ms, target = self.downsample
                try:
                    if hasattr(target, "add"):    # streaming InlineDownsampler
                        target.add(self, pids, ts, vals)
                    else:                         # plain callback (tests)
                        from .downsample import downsample_records
                        target(self, downsample_records(pids, ts, vals, res_ms))
                except Exception:
                    log.exception("inline downsample publish failed; will retry")
        finally:
            with self.lock:
                self._inflight_flush.pop(token, None)
        off = int(self._pending_group_offset[group])
        if off >= 0:
            # a checkpoint failure does NOT requeue: the chunks are durable,
            # the watermark merely lags and recommits on the next flush
            self.sink.write_checkpoint(self.dataset, self.shard_num, group, off)
            with self.lock:
                self.group_watermarks[group] = off
        return len(records)

    def _requeue_pending_locked(self, group, pending, pend_epochs) -> None:
        """Return a failed flush's chunk snapshot to the pending queue (at the
        front, preserving order), scrubbing samples whose partition was
        released while the snapshot was outside ``_pending_chunks`` — the
        release-time scrub could not see them there. Caller holds the lock."""
        kept = []
        for (pids_, ts_, vals_), eps in zip(pending, pend_epochs):
            m = self.slot_epoch[pids_] == eps
            if m.all():
                kept.append((pids_, ts_, vals_))
            elif m.any():
                kept.append((pids_[m], ts_[m], vals_[m]))
        self._pending_chunks[group] = kept + self._pending_chunks[group]

    def flush_all_groups(self) -> None:
        for g in range(self.config.groups_per_shard):
            self.flush_group(g)

    def recover(self, bus=None, schemas: Schemas | None = None,
                on_chunks_loaded=None, accept=None) -> int:
        """Restore shard state from the sink + replay the bus from the minimum
        checkpointed offset (ref: TimeSeriesShard.recoverIndex :483 +
        TimeSeriesMemStore.recoverStream :148). Returns rows replayed.
        ``accept(container)`` filters replayed containers when several
        shards share one broker partition (IngestionConsumer demux)."""
        assert self.sink is not None and len(self.index) == 0
        # queries admitted mid-recovery see a PARTIAL shard: flagged so the
        # serving layer never caches an in-window empty selection as proof
        # of emptiness (the TTL negative cache would otherwise mask the
        # recovered data for its whole TTL — a restart-then-404 incident)
        self.recovering = True
        try:
            return self._recover_inner(bus, schemas, on_chunks_loaded, accept)
        finally:
            self.recovering = False

    def _recover_inner(self, bus, schemas, on_chunks_loaded, accept) -> int:
        if self.store is None and (self.schema.is_histogram
                                   or self.schema.is_multi_column):
            meta = self.sink.read_meta(self.dataset, self.shard_num) \
                if hasattr(self.sink, "read_meta") else {}
            # create early only when the bucket count is knowable: a
            # histogram schema without persisted les (crash before first
            # flush) must stay None so bus replay recreates it with the
            # bucket scheme its first container carries
            if meta.get("bucket_les") or not self.schema.is_histogram:
                # under the shard lock: queries are admitted while recovery
                # streams in, and they read self.store
                with self.lock:
                    self.bucket_les = (np.asarray(meta["bucket_les"])
                                       if meta.get("bucket_les") else None)
                    self.store = self._make_store()
                    self.store.owner_lock = self.lock
        # 1. part keys -> index (ids dense in creation order; a purged slot may
        #    have been re-persisted under a new series — the last entry wins).
        #    The durable index time buckets (index.log) are the FAST path:
        #    columnar frames load back through bulk array adds; partkeys.log
        #    (per-key JSON) stays the fallback for sinks/logs without them.
        #    Either way the duration lands in filodb_index_recover_ms.
        import time as _time
        t0_index = _time.perf_counter()
        # pid -> (labels | None, label blob | None, start); blobs parse
        # lazily — the bulk load consumes them as canonical key bytes
        latest: dict[int, tuple[dict | None, bytes | None, int]] = {}
        last_live: dict[int, tuple[dict | None, bytes | None]] = {}
        frames_reader = getattr(self.sink, "read_index_frames", None)
        used_frames = False
        if frames_reader is not None and self.index_bucket_ms:
            try:
                frames = list(frames_reader(self.dataset,
                                            self.shard_num) or ())
                # trust window: the log is authoritative only from its
                # LAST genesis snapshot, and only when no RETIRE marker
                # (a persistence-off recovery ran since) supersedes it —
                # an upgraded or toggled shard whose log misses history
                # must fall back, never silently lose series
                gen_at = retire_at = -1
                for fi, fr in enumerate(frames):
                    if fr[0] == INDEX_GENESIS_BUCKET:
                        gen_at = fi
                    elif fr[0] == INDEX_RETIRE_BUCKET:
                        retire_at = fi
                trusted = gen_at >= 0 and gen_at > retire_at
                for fr in (frames[gen_at:] if trusted else ()):
                    _bucket, fpids, fstarts, fblobs, fflags = fr
                    if len(fflags) \
                            and (fflags & INDEX_FLAG_UNPARSEABLE).any():
                        trusted = False     # placeholder entries: the pair
                        break               # encoding could not hold them
                    for pid, start, blob in zip(fpids.tolist(),
                                                fstarts.tolist(), fblobs):
                        latest[pid] = (None, blob, start)
                        if blob:
                            last_live[pid] = (None, blob)
                if trusted and latest:
                    used_frames = True
                    self._index_log_seeded = True
                else:
                    latest.clear()
                    last_live.clear()
            except Exception:
                log.warning("index.log recovery failed; rebuilding from "
                            "partkeys.log", exc_info=True)
                latest.clear()
                last_live.clear()
        if not used_frames:
            for pid, labels, start in self.sink.read_part_keys(
                    self.dataset, self.shard_num) or ():
                latest[pid] = (labels, None, start)
                if labels:
                    last_live[pid] = (labels, None)
        opts = self.schema.options

        def _pk_and_labels(labels, blob):
            if labels is None:
                labels = labels_from_blob(blob)
            if blob and not opts.ignore_shard_key_tags:
                return blob, labels      # full-label blob IS the part key
            return part_key_of(labels, opts), labels

        # queries are admitted while recovery streams in (the reference serves
        # partial data during RecoveryInProgress), so index and store
        # mutations take the shard lock like any ingest would — an unlocked
        # store.append would donate (delete) array buffers a concurrent query
        # has already captured
        with self.lock:
            recovered_keys: list[tuple[int, bytes]] = []
            items = [(pid,) + latest[pid] for pid in sorted(latest)]
            # bulk-loadable only when the blob doubles as the canonical key
            # (no ignored tags: add_part_keys_bulk derives index labels FROM
            # the key bytes, which must then carry every label)
            can_bulk = used_frames and not opts.ignore_shard_key_tags
            i = 0
            while i < len(items):
                pid, labels, blob, start = items[i]
                while len(self.index) < pid:   # gap: entry lost; free hole
                    hole = len(self.index)
                    self.index.add_part_key(hole, {}, 0, end_time=-1)
                    self._free_pids.append(hole)
                if not labels and not blob:    # tombstone won: slot is free
                    self.index.add_part_key(pid, {}, 0, end_time=-1)
                    self._free_pids.append(pid)
                    prev = last_live.get(pid)
                    if prev is not None:       # returning-series detection
                        self._evicted_keys.add(_pk_and_labels(*prev)[0])
                    i += 1
                    continue
                # dense live run -> ONE columnar bulk add (the recover-ms
                # lever: no per-key dict builds or python add loops)
                j = i
                while (can_bulk and j < len(items) and items[j][2]
                       and items[j][0] == pid + (j - i)):
                    j += 1
                if j - i >= RECOVER_BULK_MIN and \
                        len({items[k][2] for k in range(i, j)}) == j - i and \
                        self.index.add_part_keys_bulk(
                            np.arange(pid, pid + (j - i)),
                            [items[k][2] for k in range(i, j)], 0,
                            start_times=np.asarray(
                                [items[k][3] for k in range(i, j)],
                                np.int64)):
                    if self.governor is not None:
                        # batched adoption: one cheap key-bytes extraction
                        # per key and ONE adopt per distinct tenant — a
                        # per-key dict build + lock + gauge update would
                        # hand back much of the bulk path's win
                        tenants: dict[str, int] = {}
                        for k in range(i, j):
                            t = self.governor.tenant_from_key_bytes(
                                items[k][2])
                            tenants[t] = tenants.get(t, 0) + 1
                        for t, cnt in tenants.items():
                            self.governor.adopt(t, cnt)
                    for k in range(i, j):
                        rpid, _rl, rblob, _rs = items[k]
                        self._part_key_to_id[rblob] = rpid
                        self._part_key_of_id[rpid] = rblob
                        recovered_keys.append((rpid, rblob))
                    i = j
                    continue
                pk, labels = _pk_and_labels(labels, blob)
                self._part_key_to_id[pk] = pid
                self._part_key_of_id[pid] = pk
                recovered_keys.append((pid, pk))
                self.index.add_part_key(pid, labels, start)
                if self.governor is not None:
                    self.governor.adopt(self.governor.tenant_of(labels))
                i += 1
            if self._native_ps is not None and recovered_keys:
                # one native batch hash + ONE batch insert (per-key ctypes
                # calls cost ~10us each — material at 100k recovered series)
                from .native import fnv1a64_batch
                hashes = fnv1a64_batch([pk for _pid, pk in recovered_keys])
                self._native_ps.insert_batch(
                    [(int(h), pk, pid)
                     for (pid, pk), h in zip(recovered_keys, hashes)])
                for (pid, _pk), h in zip(recovered_keys, hashes):
                    self._pid_hash[pid] = h
        registry.gauge(FILODB_INDEX_RECOVER_MS,
                       {"dataset": self.dataset,
                        "shard": str(self.shard_num)}) \
            .update((_time.perf_counter() - t0_index) * 1000.0)
        if hasattr(self.sink, "write_index_bucket"):
            # re-anchor the index log's trust: a fallback rebuild appends a
            # fresh GENESIS snapshot (fast path restored next restart), a
            # persistence-off recovery appends a RETIRE marker so a later
            # persistence-on restart cannot trust the now-stale content.
            # Best-effort — a failed write just defers seeding to the next
            # drain (seeded stays False) or the next recovery
            try:
                if self.index_bucket_ms and not used_frames:
                    self._write_index_genesis()
                elif not self.index_bucket_ms:
                    self.sink.write_index_bucket(
                        self.dataset, self.shard_num,
                        encode_index_bucket(INDEX_RETIRE_BUCKET, []))
            except Exception:
                log.warning("index.log trust re-anchor failed; the next "
                            "drain or recovery retries", exc_info=True)
        # 2. chunks -> device store (batched appends, flush order == time order).
        #    Chunks of purged partitions are skipped; for a reused slot, samples
        #    older than the current owner's start time belong to the purged
        #    predecessor and are dropped.
        own_start = {pid: start
                     for pid, (labels, blob, start) in latest.items()
                     if labels or blob}
        start_of = np.full(len(self.index) + 1, 1 << 62, np.int64)
        for pid, start in own_start.items():
            start_of[pid] = start
        for group, records in self.sink.read_chunksets(self.dataset, self.shard_num) or ():
            keep = [r for r in records if r.part_id in own_start]
            if not keep:
                continue
            pids = np.concatenate([np.full(len(r.ts), r.part_id, np.int32) for r in keep])
            ts = np.concatenate([r.ts for r in keep])
            vals = np.concatenate([r.values for r in keep])
            owned = ts >= start_of[pids]
            if not owned.all():
                pids, ts, vals = pids[owned], ts[owned], vals[owned]
            if len(pids):
                with self.lock:   # append donates the store buffers
                    self.store.append(pids, ts, vals)
                    # loaded chunks change query-visible data exactly like
                    # a flush would: the epoch-validated caches must see
                    # the bump (a result cached mid-recovery would
                    # otherwise validate against a pre-load vector forever)
                    self._bump_epoch_locked(int(ts.min()))
                    lead = int(ts.max())
                    if lead > self.lead_ms:
                        self.lead_ms = lead
                    if lead > self.visible_lead_ms:
                        self.visible_lead_ms = lead   # loaded = visible
        # between chunk load and replay: replayed rows flow through the
        # normal flush pipeline, so state seeded here (e.g. the streaming
        # downsampler's open buckets) sees each sample exactly once
        if on_chunks_loaded is not None:
            on_chunks_loaded()
        # 3. checkpoints -> watermarks; replay the bus past them
        cps = self.sink.read_checkpoints(self.dataset, self.shard_num)
        with self.lock:   # _pending_group_offset is ingest-staging state
            for g, off in cps.items():
                self.group_watermarks[g] = off
                self._pending_group_offset[g] = off
        replayed = 0
        if bus is not None:
            wm = self.group_watermarks.copy()
            start_off = int(wm[wm >= 0].min()) if (wm >= 0).any() else 0
            next_off = start_off
            for off, container in bus.consume(schemas or Schemas(), start_off):
                next_off = off + 1
                if accept is not None and not accept(container):
                    continue
                before = self.stats.rows_ingested
                self.ingest(container, off, recovery_watermarks=wm)
                replayed += self.stats.rows_ingested - before
            self.flush()
            # the EXACT offset replay reached: the live consumer must resume
            # here, not at a later end_offset read — frames published between
            # the replay's end snapshot and that read would be skipped
            # forever (visible as a permanent gap on an adopted shard that
            # warms while its partition keeps taking writes)
            self.recovered_through = next_off
        return replayed

    # -- purge (ref: TimeSeriesShard.purgeExpiredPartitions :751) ------------

    def purge_expired_partitions(self, cutoff_ms: int) -> int:
        """Remove partitions whose last sample is older than ``cutoff_ms``:
        index entries tombstoned, HBM rows freed for reuse, part keys recorded
        in the evicted-keys filter so a returning series is detected. Returns
        the number of partitions purged."""
        self.flush()
        if self.store is None:
            return 0
        # the whole purge mutates index + store + id maps; query threads read the
        # same structures concurrently, so it all happens under the shard lock
        with self.lock:
            # mark end-times of inactive series (the reference persists endTime
            # when a partition goes quiet; the host last_ts mirror is authoritative)
            last = self.store.last_ts
            inactive = np.nonzero((self.store.n_host > 0) & (last < cutoff_ms))[0]
            ended = {pid: int(last[pid]) for pid in inactive.tolist()
                     if self.index.is_live(pid)}
            if ended:
                # the marks alone are query-visible — a series ended at T
                # drops out of selections for windows past T even when the
                # pending-flush filter below vetoes the actual purge — so
                # they need their own bump: steps at or before the earliest
                # mark are provably unaffected (batch_min_ts class). Bump
                # BEFORE applying the marks (the flush/release pattern): a
                # mid-loop fault can then never leave marks visible under a
                # stale epoch
                self._bump_epoch_locked(min(ended.values()))
                for pid, end_ts in ended.items():
                    self.index.update_end_time(pid, end_ts)
            purged = self.index.part_ids_ended_before(cutoff_ms)
            # never purge series with data still staged for a pending flush
            # group, nor pids of a snapshot currently being written
            if len(purged) and self.sink is not None:
                staged = [pids for chunks in self._pending_chunks
                          for (pids, _, _) in chunks]
                staged.extend(self._inflight_flush.values())
                if staged:
                    pending = np.unique(np.concatenate(staged))
                    purged = np.setdiff1d(purged, pending).astype(np.int32)
            if len(purged) == 0:
                return 0
            self._release_partitions_locked(purged)
            self.stats.partitions_purged += len(purged)
        self._flush_partkey_log()   # durable write happens outside the shard lock
        return len(purged)

    # -- on-demand paging (ref: OnDemandPagingShard.scala:26,58 +
    #    DemandPagedChunkStore.scala:35 — cold chunks paged in for queries) -----

    def needs_paging(self, pids: np.ndarray, start_ms: int) -> bool:
        """True when the query needs data older than what's resident for any
        selected series and a durable sink exists to page from."""
        if self.sink is None or len(pids) == 0 or self.store is None:
            return False
        first = self.store.first_ts[pids]
        return bool((first[first >= 0] > start_ms).any())

    def read_cold_for(self, pids: np.ndarray, start_ms: int, end_ms: int):
        """Sink-side cold chunks for the given pids: pid -> ([ts...], [vals...]).
        Needs NO shard lock — sink logs are append-only and torn-tolerant, so
        wide paged scans must not stall ingest while reading disk. The scan
        is traced and its paged samples counted per tier: a remote sink
        (StoreServer ring) is the cluster-wide durable-tier ODP path."""
        cold_ts: dict[int, list] = {int(p): [] for p in pids}
        cold_val: dict[int, list] = {int(p): [] for p in pids}
        reader = getattr(self.sink, "read_chunksets", None)
        if reader is not None:
            tier = ("remote" if getattr(self.sink, "remote_tier", False)
                    else "local")
            rows = 0
            with span(SPAN_ODP_DURABLE, shard=self.shard_num,
                      tier=tier) as tags:
                for _g, records in reader(self.dataset, self.shard_num,
                                          start_ms, end_ms) or ():
                    for r in records:
                        if r.part_id in cold_ts:
                            cold_ts[r.part_id].append(r.ts)
                            cold_val[r.part_id].append(np.asarray(r.values))
                            rows += len(r.ts)
                tags["rows"] = rows
            if rows:
                registry.counter(FILODB_RETENTION_ODP_ROWS,
                                 {"dataset": self.dataset,
                                  "tier": tier}).increment(rows)
        return cold_ts, cold_val

    def age_out_durable(self, cutoff_ms: int) -> int:
        """Durable raw retention (retention.raw_ttl): drop sink samples older
        than ``cutoff_ms`` and bump ``data_epoch`` so cached results over the
        aged-out range invalidate. The heavy read-decode-rewrite half runs
        with NO locks held (copy-out); only the commit — splicing the tail
        appended since the snapshot, bounded by one flush batch per group,
        then an atomic rename — runs under all group flush locks, so the
        rewrite can never lose a concurrent append yet flushes stall only
        for the splice. Sinks without the prepare/commit split (the remote
        store client, whose age_out is one deadline-bounded RPC) keep the
        single-call form under the locks — the declared LATENCY_SPEC
        sanction."""
        import contextlib
        sink = self.sink
        if sink is None or not hasattr(sink, "age_out"):
            return 0
        prepare = getattr(sink, "age_out_prepare", None)
        if prepare is not None:
            token = prepare(self.dataset, self.shard_num, cutoff_ms)
            if token is None:
                return 0
            with contextlib.ExitStack() as stack:
                for lk in self._group_flush_locks:   # ascending: in-order
                    stack.enter_context(lk)
                dropped = int(sink.age_out_commit(token))
        else:
            with contextlib.ExitStack() as stack:
                for lk in self._group_flush_locks:   # ascending: in-order
                    stack.enter_context(lk)
                dropped = int(sink.age_out(self.dataset, self.shard_num,
                                           cutoff_ms))
        if dropped:
            with self.lock:
                # result-cache watermark: rows aged out (destructive)
                self._bump_epoch_locked(EPOCH_AFFECTS_ALL)
            registry.counter(FILODB_RETENTION_AGED_OUT_ROWS,
                             {"dataset": self.dataset,
                              "shard": str(self.shard_num)}).increment(dropped)
        return dropped

    def read_with_paging(self, pids: np.ndarray, start_ms: int, end_ms: int,
                         cold=None, column=None):
        """Merged (ts [P, C'], val [P, C'], n [P]) host arrays combining paged
        cold chunks (from the sink) with resident device data, deduped on the
        per-series resident first-timestamp boundary. ``cold`` accepts a
        pre-fetched read_cold_for result (gathered outside the shard lock);
        ``column`` selects one scalar column of a multi-column store (cold
        multi-column records are sliced by the schema layout)."""
        from .chunkstore import TS_PAD
        cold_ts, cold_val = cold if cold is not None else \
            self.read_cold_for(pids, start_ms, end_ms)
        col_off = None
        if self.schema.is_multi_column:
            nb = len(self.bucket_les) if self.bucket_les is not None else 0
            name = column or self.store.default_col
            for nm, off, w, _ih in self.schema.col_layout(nb):
                if nm == name:
                    assert w == 1, "histogram columns do not page on demand"
                    col_off = off
                    break
        rows_ts, rows_val = [], []
        # ONE batched device->host transfer for the whole paged batch, and a
        # compressed-resident store decodes/derives ONLY the selected rows
        # (gather_rows — the whole-store f32/i64 temp never materializes).
        # The previous per-pid slice (`np.asarray(tsrc[p, :cnt])`) cost one
        # full tunnel round-trip per SERIES — the dominant term of a wide
        # cold scan
        from .chunkstore import _Deferred
        tsrc, vsrc, _n = self.store.arrays(column)
        if isinstance(tsrc, np.ndarray) and isinstance(vsrc, np.ndarray):
            ts_host, val_host = tsrc[pids], vsrc[pids]
        else:
            import jax
            import jax.numpy as jnp
            rid = jnp.asarray(np.asarray(pids, np.int32))
            ts_rows = (tsrc.gather_rows(rid) if isinstance(tsrc, _Deferred)
                       else jnp.take(jnp.asarray(tsrc), rid, axis=0))
            val_rows = (vsrc.gather_rows(rid) if isinstance(vsrc, _Deferred)
                        else jnp.take(jnp.asarray(vsrc), rid, axis=0))
            ts_host, val_host = jax.device_get((ts_rows, val_rows))
        for i, p in enumerate(pids):
            p = int(p)
            cnt = int(self.store.n_host[p])
            hot_t = np.asarray(ts_host[i, :cnt])
            hot_v = np.asarray(val_host[i, :cnt])
            boundary = hot_t[0] if len(hot_t) else (1 << 62)
            if cold_ts[p]:
                ct = np.concatenate(cold_ts[p])
                cv = np.concatenate(cold_val[p])
                if col_off is not None and cv.ndim == 2:
                    cv = cv[:, col_off]
                # same slot-reuse rule as recovery (recover() step 2): sink
                # chunks older than the CURRENT owner's start time belong to
                # a released predecessor of the slot, not this series
                own_start = self.index.start_time(p)
                sel = (ct < boundary) & (ct >= own_start)
                order = np.argsort(ct[sel], kind="stable")
                st, sv = ct[sel][order], cv[sel][order]
                if len(st):
                    # keep-first timestamp dedup: a requeued flush after a
                    # partial sink failure (or a lost-response write) can
                    # leave duplicate frames in the log — recovery replay
                    # dedups via the store's out-of-order drop, and the
                    # paged read path must match it or duplicated samples
                    # double-count in sum/count_over_time
                    keep = np.concatenate([[True], np.diff(st) > 0])
                    st, sv = st[keep], sv[keep]
                rows_ts.append(np.concatenate([st, hot_t]))
                rows_val.append(np.concatenate([sv, hot_v]))
            else:
                rows_ts.append(hot_t)
                rows_val.append(hot_v)
        C = max((len(t) for t in rows_ts), default=1)
        P = len(pids)
        ts_arr = np.full((P, C), TS_PAD, np.int64)
        val_arr = np.zeros((P, C), np.float64)
        n_arr = np.zeros(P, np.int32)
        for i, (t, v) in enumerate(zip(rows_ts, rows_val)):
            ts_arr[i, :len(t)] = t
            val_arr[i, :len(t)] = v
            n_arr[i] = len(t)
        return ts_arr, val_arr, n_arr

    # -- queries ------------------------------------------------------------

    def rv_key_of(self, pid: int):
        """Memoized RangeVectorKey for a live pid (query-leaf hot path: avoids
        re-materializing the dict-encoded labels on every query). Call under
        the shard lock; purge drops cache entries for reused slots."""
        assert_owned(self.lock, "rv_key_of")   # caller-holds-lock contract
        k = self._rv_keys.get(pid)
        if k is None:
            from ..query.rangevector import RangeVectorKey
            k = self._rv_keys[pid] = RangeVectorKey.of(self.index.labels_of(pid))
        return k

    def part_ids_from_filters(self, filters: list[Filter], start: int, end: int,
                              limit: int | None = None) -> np.ndarray:
        self.flush()
        # under the shard lock: a concurrent purge mutates postings in place
        with self.lock:
            return self.index.part_ids_from_filters(filters, start, end, limit)

    def label_values(self, label: str, filters=None, top_k=None) -> list[str]:
        with self.lock:
            return self.index.label_values(label, filters, top_k=top_k)

    def label_value_counts(self, label: str, filters=None,
                           top_k=None) -> list[tuple[str, int]]:
        with self.lock:
            return self.index.label_value_counts(label, filters, top_k=top_k)

    def label_names(self, filters=None) -> list[str]:
        with self.lock:
            return self.index.label_names(filters)

    @property
    def num_series(self) -> int:
        return len(self._part_key_to_id)


class TimeSeriesMemStore:
    """Dataset -> shards facade (ref: MemStore.scala trait + TimeSeriesMemStore)."""

    def __init__(self, schemas: Schemas | None = None):
        self.schemas = schemas or Schemas()
        self._shards: dict[tuple[str, int], TimeSeriesShard] = {}
        self._configs: dict[str, StoreConfig] = {}
        self._dataset_schema: dict[str, Schema] = {}

    def setup(self, dataset: str, schema: Schema | str, shard: int,
              config: StoreConfig | None = None, device=None,
              sink: ChunkSink | None = None,
              eviction_policy: EvictionPolicy | None = None) -> TimeSeriesShard:
        if isinstance(schema, str):
            schema = self.schemas[schema]
        cfg = config or self._configs.get(dataset) or StoreConfig()
        self._configs[dataset] = cfg
        self._dataset_schema[dataset] = schema
        key = (dataset, shard)
        if key in self._shards:
            raise ValueError(f"shard {shard} of {dataset} already set up")
        s = TimeSeriesShard(dataset, schema, shard, cfg, device=device, sink=sink,
                            eviction_policy=eviction_policy)
        self._shards[key] = s
        return s

    def shard(self, dataset: str, shard: int) -> TimeSeriesShard:
        return self._shards[(dataset, shard)]

    def shards_of(self, dataset: str) -> list[TimeSeriesShard]:
        return [s for (d, _), s in sorted(self._shards.items()) if d == dataset]

    def ingest(self, dataset: str, shard: int, container: RecordContainer,
               offset: int = -1) -> None:
        self._shards[(dataset, shard)].ingest(container, offset)

    def flush_all(self, dataset: str | None = None) -> None:
        for (d, _), s in self._shards.items():
            if dataset is None or d == dataset:
                s.flush()
