"""Chunk persistence: ChunkSink/ChunkSource traits + implementations.

Reference: core/.../store/ChunkSink.scala:18 (sink trait + NullColumnStore:98),
ChunkSource.scala (read side), cassandra/.../columnstore/CassandraColumnStore.scala
(chunk table, ingestion-time index, partkey table).

TPU-native shape: a flushed chunkset is a *columnar batch* — one frame per flush
group holding per-series compressed vectors (delta-delta timestamps + XOR/
NibblePack values; the same codecs the reference stores in Cassandra cells).
The FileColumnStore keeps, per (dataset, shard):
    chunks.log     append-only chunkset frames (the chunk table)
    partkeys.log   part-key id -> labels json (the partkey/index table)
    checkpoint.json  per-flush-group offset watermarks (the checkpoint table)
"""

from __future__ import annotations

import io
import json
import os
import shutil
import struct
import threading
from dataclasses import dataclass

import numpy as np

from ..memory import deltadelta, hist as histcodec, intpack, nibblepack
from ..memory import native as _native

# nb-field flag marking a bit-packed integer value chunk (high bit: real
# histogram bucket counts never approach it)
_INTPACK_FLAG = 0x80000000
_MULTICOL_FLAG = 0x40000000

# persistence hot path prefers the C++ codecs (bit-identical; tests/test_native.py)
if _native.available():
    _pack_doubles, _unpack_doubles = _native.pack_doubles, _native.unpack_doubles
else:  # pragma: no cover - toolchain-less fallback
    _pack_doubles, _unpack_doubles = nibblepack.pack_doubles, nibblepack.unpack_doubles

# ---------------------------------------------------------------------------


@dataclass
class ChunkSetRecord:
    """One series' slice of a flushed chunkset. ``layout`` (from
    Schema.col_layout) marks multi-value-column rows: values is [n, W] with
    each named column encoded separately on the wire."""
    part_id: int
    ts: np.ndarray
    values: np.ndarray
    layout: tuple | None = None


class ChunkSink:
    """Write side (ref: ChunkSink.scala trait)."""

    def write_chunkset(self, dataset: str, shard: int, group: int,
                       records: list[ChunkSetRecord]) -> None:
        raise NotImplementedError

    def write_part_keys(self, dataset: str, shard: int, entries) -> None:
        raise NotImplementedError

    def write_checkpoint(self, dataset: str, shard: int, group: int,
                         offset: int) -> None:
        raise NotImplementedError

    def read_checkpoints(self, dataset: str, shard: int) -> dict[int, int]:
        raise NotImplementedError


class NullColumnStore(ChunkSink):
    """No-op sink for tests/ephemeral nodes (ref: ChunkSink.scala:98)."""

    def __init__(self):
        self.chunksets_written = 0
        self._checkpoints: dict[tuple, dict[int, int]] = {}

    def write_chunkset(self, dataset, shard, group, records):
        self.chunksets_written += 1

    def write_part_keys(self, dataset, shard, entries):
        pass

    def write_checkpoint(self, dataset, shard, group, offset):
        self._checkpoints.setdefault((dataset, shard), {})[group] = offset

    def read_checkpoints(self, dataset, shard):
        return dict(self._checkpoints.get((dataset, shard), {}))


_CHUNK_HDR = struct.Struct("<IIQ")     # group, n_records, flush_seq


def encode_chunkset(group: int, records) -> bytes:
    """One chunk-log frame: header + per-record codec-compressed payload.
    Shared by the local file store and the remote store client."""
    frames = []
    for r in records:
        ts_enc = deltadelta.encode(r.ts)
        vals = np.asarray(r.values)
        if r.layout is not None:   # multi-value-column row: per-column codecs
            nb = _MULTICOL_FLAG
            cols = [struct.pack("<H", len(r.layout))]
            for _nm, off, w, is_h in r.layout:
                cv = vals[:, off:off + w]
                if is_h:
                    enc = histcodec.encode_hist_series(cv)
                    kind = 2
                elif len(cv) and intpack.is_integral(cv[:, 0]):
                    enc = intpack.pack_ints(cv[:, 0].astype(np.int64))
                    kind = 1
                else:
                    enc = _pack_doubles(cv[:, 0].astype(np.float64))
                    kind = 0
                cols.append(struct.pack("<BHI", kind, w, len(enc)) + enc)
            val_enc = b"".join(cols)
        elif vals.ndim == 2:   # histogram: 2D-delta + NibblePack codec
            nb = vals.shape[1]
            val_enc = histcodec.encode_hist_series(vals)
        elif len(vals) and intpack.is_integral(vals):
            # integral chunk (counts, integer gauges): bit-packed int
            # vector, flagged in the nb field's high bit (ref:
            # IntBinaryVector bit-packed family)
            nb = _INTPACK_FLAG
            val_enc = intpack.pack_ints(vals.astype(np.int64))
        else:
            nb = 0
            val_enc = _pack_doubles(vals.astype(np.float64))
        frames.append(struct.pack("<IIIII", r.part_id, len(r.ts), nb,
                                  len(ts_enc), len(val_enc)) + ts_enc + val_enc)
    payload = b"".join(frames)
    return (_CHUNK_HDR.pack(group, len(records), 0)
            + struct.pack("<I", len(payload)) + payload)


def _decode_multicol(buf: bytes, n: int):
    """Inverse of the multi-column encoding: [n, W] f64 + wire layout
    (names are not on the wire; offsets/widths/kinds suffice — the consumer
    splits by its schema's layout, which recovery validates by width)."""
    (ncols,) = struct.unpack_from("<H", buf, 0)
    off = 2
    cols = []
    layout = []
    at = 0
    for _ in range(ncols):
        kind, w, plen = struct.unpack_from("<BHI", buf, off); off += 7
        p = buf[off:off + plen]; off += plen
        if kind == 2:
            cols.append(histcodec.decode_hist_series(p).astype(np.float64))
        elif kind == 1:
            cols.append(intpack.unpack_ints(p).astype(np.float64)[:, None])
        else:
            cols.append(_unpack_doubles(p, n)[:, None])
        layout.append((f"c{len(layout)}", at, w, kind == 2))
        at += w
    return np.concatenate(cols, axis=1), tuple(layout)


def iter_chunksets(f, start_ms: int = 0, end_ms: int = 1 << 62):
    """Parse a chunk-log stream (any binary file-like): yields (group,
    [ChunkSetRecord...]) overlapping [start_ms, end_ms]. Shared by the local
    file store and the remote store client; a torn or corrupt tail frame
    truncates (WAL semantics)."""
    while True:
        hdr = f.read(_CHUNK_HDR.size)
        if len(hdr) < _CHUNK_HDR.size:
            return
        try:
            group, n_rec, _ = _CHUNK_HDR.unpack(hdr)
            raw_len = f.read(4)
            if len(raw_len) < 4:
                return            # torn tail: a crashed append; truncate
            (plen,) = struct.unpack("<I", raw_len)
            payload = f.read(plen)
            if len(payload) < plen:
                return            # torn tail
            records = []
            off = 0
            for _ in range(n_rec):
                pid, n, nb, tlen, vlen = struct.unpack_from("<IIIII", payload, off)
                off += 20
                ts = deltadelta.decode(payload[off:off + tlen]); off += tlen
                layout = None
                if nb == _INTPACK_FLAG:
                    vals = intpack.unpack_ints(
                        payload[off:off + vlen]).astype(np.float64)
                elif nb == _MULTICOL_FLAG:
                    vals, layout = _decode_multicol(payload[off:off + vlen], n)
                elif nb:
                    vals = histcodec.decode_hist_series(
                        payload[off:off + vlen]).astype(np.float64)
                else:
                    vals = _unpack_doubles(payload[off:off + vlen], n)
                off += vlen
                if len(ts) and ts[-1] >= start_ms and ts[0] <= end_ms:
                    records.append(ChunkSetRecord(pid, ts, vals, layout))
        except (struct.error, ValueError, IndexError):
            return                # corrupt tail frame: stop at last good one
        if records:
            yield group, records


def head_frame_min_ts(f):
    """Min timestamp of the FIRST chunk-log frame on a stream (None when the
    log is empty/torn): the cheap age-out skip probe. Frames append in flush
    order, so between TTL boundaries (the steady state) the head frame holds
    nothing past the cutoff and the full read-decode-rewrite pass — which
    would drop nothing — can be skipped after one small read. Out-of-order
    older samples in LATER frames are only deferred, never retained forever:
    the cutoff advances with the data lead, so once it passes the head
    frame's own timestamps a full pass runs and drops them."""
    head = next(iter_chunksets(f), None)
    if head is None:
        return None
    _group, records = head
    return min(int(r.ts[0]) for r in records)


def encode_age_out(chunksets, cutoff_ms: int) -> tuple[bytes, int]:
    """Re-encode a chunk-log stream keeping only samples at or after
    ``cutoff_ms`` (the durable raw-retention compaction, shared by the local
    file store and the remote store client). Returns (new log bytes, samples
    dropped); records emptied entirely are elided, untouched records
    re-encode bit-identically (same codecs, same order)."""
    frames = []
    dropped = 0
    for group, records in chunksets or ():
        keep = []
        for r in records:
            sel = r.ts >= cutoff_ms
            if sel.all():
                keep.append(r)
            elif sel.any():
                keep.append(ChunkSetRecord(r.part_id, r.ts[sel],
                                           np.asarray(r.values)[sel],
                                           r.layout))
                dropped += int((~sel).sum())
            else:
                dropped += len(r.ts)
        if keep:
            frames.append(encode_chunkset(group, keep))
    return b"".join(frames), dropped


def _good_frame_prefix_len(data: bytes) -> int:
    """Byte length of the longest structurally complete frame prefix of a
    chunk log. The lock-free half of the age-out split snapshots the log
    while a flush append may be mid-write; cutting anywhere but a frame
    boundary would splice half a frame in front of the appends that land
    after the snapshot, and the WAL reader would truncate every one of
    them at the torn half."""
    off = 0
    hdr = _CHUNK_HDR.size
    while True:
        if off + hdr + 4 > len(data):
            return off
        (plen,) = struct.unpack_from("<I", data, off + hdr)
        end = off + hdr + 4 + plen
        if end > len(data):
            return off
        off = end


# ---------------------------------------------------------------------------
# Part-key index time buckets (ref: the reference persists its Lucene index
# as time-bucket blobs and recovers from them instead of re-indexing raw
# part keys — SURVEY §5 "Checkpoint / resume"). One frame per touched bucket
# per flush drain, appended to index.log in event order; every frame carries
# its own CRC so a torn or damaged tail truncates instead of poisoning
# recovery. Entries are COLUMNAR: pid/start arrays plus length-prefixed
# label blobs (the full label set in part-key pair encoding), so recovery
# rebuilds the index with bulk array loads, not per-key JSON parsing.
# ---------------------------------------------------------------------------

_INDEX_HDR = struct.Struct("<qII")     # bucket_start_ms, payload_len, crc32

# tombstone entries (releases) ride a dedicated pseudo-bucket: event order
# within the log is what resolves slot reuse, not the bucket tag
INDEX_TOMBSTONE_BUCKET = -1
# GENESIS: this frame's entries are a COMPLETE live-series snapshot — the
# log is trustworthy from the LAST genesis onward (written at shard birth,
# and re-written after any recovery that had to fall back to partkeys.log,
# so an upgraded or persistence-toggled shard never loses pre-log series)
INDEX_GENESIS_BUCKET = -2
# RETIRE: everything before this marker is STALE (appended by a recovery
# running with index persistence OFF — events will accrue only in
# partkeys.log from here, so a later persistence-on restart must not trust
# the pre-marker content; a fresh genesis after it restores trust)
INDEX_RETIRE_BUCKET = -3

# per-entry flags: bit0 = labels not representable in the pair encoding
# (separator bytes) — the entry is a placeholder and recovery must fall
# back to partkeys.log for the whole shard
INDEX_FLAG_UNPARSEABLE = 1


def encode_index_bucket(bucket_start_ms: int, entries) -> bytes:
    """One index.log frame: ``entries`` is [(pid, start_ms, label_blob)] or
    [(pid, start_ms, label_blob, flags)]; a tombstone entry carries an
    empty blob and start -1."""
    import zlib
    pids = np.asarray([e[0] for e in entries], np.int64)
    starts = np.asarray([e[1] for e in entries], np.int64)
    blobs = [e[2] for e in entries]
    flags = np.asarray([(e[3] if len(e) > 3 else 0) for e in entries],
                       np.uint8)
    lens = np.asarray([len(b) for b in blobs], np.uint32)
    payload = zlib.compress(
        struct.pack("<I", len(entries)) + pids.tobytes() + starts.tobytes()
        + lens.tobytes() + flags.tobytes() + b"".join(blobs), 1)
    return _INDEX_HDR.pack(int(bucket_start_ms), len(payload),
                           zlib.crc32(payload)) + payload


def iter_index_frames(f):
    """Parse an index.log stream: yields (bucket_start_ms, pids, starts,
    blobs, flags) per frame in append (= event) order. A torn tail or a
    CRC mismatch truncates (WAL semantics) — recovery falls back to the
    per-key partkeys.log rebuild for anything the index log cannot prove."""
    import zlib
    while True:
        hdr = f.read(_INDEX_HDR.size)
        if len(hdr) < _INDEX_HDR.size:
            return
        try:
            bucket, plen, crc = _INDEX_HDR.unpack(hdr)
            payload = f.read(plen)
            if len(payload) < plen or zlib.crc32(payload) != crc:
                return
            raw = zlib.decompress(payload)
            (n,) = struct.unpack_from("<I", raw, 0)
            off = 4
            pids = np.frombuffer(raw, np.int64, count=n, offset=off)
            off += 8 * n
            starts = np.frombuffer(raw, np.int64, count=n, offset=off)
            off += 8 * n
            lens = np.frombuffer(raw, np.uint32, count=n, offset=off)
            off += 4 * n
            flags = np.frombuffer(raw, np.uint8, count=n, offset=off)
            off += n
            blobs = []
            for ln in lens.tolist():
                blobs.append(raw[off:off + ln])
                off += ln
        except (struct.error, ValueError, zlib.error, IndexError):
            return
        yield bucket, pids, starts, blobs, flags


def labels_from_blob(blob: bytes) -> dict[str, str]:
    """Inverse of the part-key pair encoding (schemas.part_key_bytes over
    the FULL label set)."""
    if not blob:
        return {}
    out = {}
    for pair in blob.split(b"\x00"):
        k, _, v = pair.partition(b"\x01")
        out[k.decode()] = v.decode()
    return out


class FileColumnStore(ChunkSink):
    """Durable columnar chunk store on local disk (the Cassandra-equivalent)."""

    def __init__(self, root: str):
        self.root = root

    def _dir(self, dataset: str, shard: int) -> str:
        d = os.path.join(self.root, dataset, f"shard{shard}")
        os.makedirs(d, exist_ok=True)
        return d

    # -- chunks --------------------------------------------------------------

    def write_chunkset(self, dataset, shard, group, records):
        # one buffered append minimizes the torn-frame window; the reader
        # treats a torn tail as truncation (WAL semantics)
        buf = encode_chunkset(group, records)
        with open(os.path.join(self._dir(dataset, shard), "chunks.log"), "ab") as f:
            f.write(buf)

    def read_chunksets(self, dataset, shard, start_ms: int = 0,
                       end_ms: int = 1 << 62):
        """Yield (group, [ChunkSetRecord...]) overlapping [start_ms, end_ms]
        (ref: RawChunkSource.readRawPartitions time-filtered reads)."""
        path = os.path.join(self._dir(dataset, shard), "chunks.log")
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            yield from iter_chunksets(f, start_ms, end_ms)

    def age_out_prepare(self, dataset, shard, cutoff_ms: int):
        """Heavy half of durable raw retention, safe to run with NO locks
        held: snapshot the chunk log's good-frame prefix, read, decode and
        re-encode it dropping samples older than ``cutoff_ms``. Returns an
        opaque token for ``age_out_commit``, or None when nothing would
        drop (empty/absent log, or the head-frame probe shows the cutoff
        has not reached the oldest frame). Frames appended after the
        snapshot hold fresh samples by construction and are preserved
        verbatim by the commit's splice."""
        path = os.path.join(self._dir(dataset, shard), "chunks.log")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            data = f.read()
        # cut at a frame boundary: a flush append may be mid-write while we
        # read (prepare holds no locks), and splicing half a frame in front
        # of later appends would truncate every frame behind it at read
        snap = _good_frame_prefix_len(data)
        bio = io.BytesIO(data[:snap])
        head = head_frame_min_ts(bio)
        if head is None or head >= cutoff_ms:
            return None
        bio.seek(0)
        buf, dropped = encode_age_out(list(iter_chunksets(bio)), cutoff_ms)
        if not dropped:
            return None
        return (path, snap, buf, dropped)

    def age_out_commit(self, token) -> int:
        """Cheap half of durable raw retention, run under the group flush
        locks (see TimeSeriesShard.age_out_durable): splice the rewritten
        prefix with whatever was appended since the prepare snapshot —
        bounded by one flush batch per group, since the locks serialize
        appends — and atomically swap the log. Returns samples dropped."""
        path, snap, buf, dropped = token
        tmp = path + ".tmp"
        with open(tmp, "wb") as out:
            out.write(buf)
            with open(path, "rb") as f:
                f.seek(snap)
                shutil.copyfileobj(f, out)
        os.replace(tmp, path)   # atomic commit
        return dropped

    def age_out(self, dataset, shard, cutoff_ms: int) -> int:
        """Durable raw retention: atomically rewrite the chunk log dropping
        samples older than ``cutoff_ms`` (caller serializes against
        concurrent flush appends — see TimeSeriesShard.age_out_durable).
        Returns samples dropped."""
        token = self.age_out_prepare(dataset, shard, cutoff_ms)
        return self.age_out_commit(token) if token is not None else 0

    # -- part keys ------------------------------------------------------------

    def chunk_log_size(self, dataset, shard) -> int:
        """Byte size of the shard's chunk log (cheap best-replica probe)."""
        path = os.path.join(self._dir(dataset, shard), "chunks.log")
        return os.path.getsize(path) if os.path.exists(path) else 0

    def write_part_keys(self, dataset, shard, entries):
        """entries: iterable of (part_id, labels_dict, start_time)."""
        with open(os.path.join(self._dir(dataset, shard), "partkeys.log"), "a") as f:
            for pid, labels, start in entries:
                f.write(json.dumps({"id": pid, "labels": labels, "start": start},
                                   separators=(",", ":")) + "\n")

    def read_part_keys(self, dataset, shard):
        path = os.path.join(self._dir(dataset, shard), "partkeys.log")
        if not os.path.exists(path):
            return
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    return            # torn tail line from a crashed append
                yield e["id"], e["labels"], e["start"]

    def write_index_bucket(self, dataset, shard, frame: bytes) -> None:
        """Append one pre-encoded index time-bucket frame (CRC inside the
        frame; torn tails truncate at read)."""
        with open(os.path.join(self._dir(dataset, shard), "index.log"),
                  "ab") as f:
            f.write(frame)

    def read_index_frames(self, dataset, shard):
        """Yield (bucket_start_ms, pids, starts, blobs) in event order."""
        path = os.path.join(self._dir(dataset, shard), "index.log")
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            yield from iter_index_frames(f)

    def write_meta(self, dataset, shard, meta: dict):
        path = os.path.join(self._dir(dataset, shard), "meta.json")
        with open(path, "w") as f:
            json.dump(meta, f)

    def read_meta(self, dataset, shard) -> dict:
        path = os.path.join(self._dir(dataset, shard), "meta.json")
        if not os.path.exists(path):
            return {}
        with open(path) as f:
            return json.load(f)

    # -- checkpoints (ref: cassandra/.../metastore/CheckpointTable.scala) ------

    # serializes the checkpoint read-modify-write across ALL instances of a
    # process (tests open several stores over one root): two flush groups
    # committing concurrently must not lose each other's watermark — the
    # same contract OP_CHECKPOINT gives the remote tier server-side
    _checkpoint_lock = threading.Lock()

    def write_checkpoint(self, dataset, shard, group, offset):
        path = os.path.join(self._dir(dataset, shard), "checkpoint.json")
        with FileColumnStore._checkpoint_lock:
            cp = self.read_checkpoints(dataset, shard)
            cp[group] = offset
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({str(k): v for k, v in cp.items()}, f)
            os.replace(tmp, path)   # atomic commit

    def read_checkpoints(self, dataset, shard):
        path = os.path.join(self._dir(dataset, shard), "checkpoint.json")
        if not os.path.exists(path):
            return {}
        with open(path) as f:
            return {int(k): v for k, v in json.load(f).items()}
