"""Eviction policies + evicted-part-key membership filter.

Reference: core/.../memstore/PartitionEvictionPolicy.scala:1-43 (pluggable policy
deciding when the shard must reclaim memory; WriteBufferFreeEvictionPolicy /
CompositeEvictionPolicy) and TimeSeriesShard.scala:93-96 (bloom filter of evicted
part keys, consulted on ingest so a returning series is detected, :1092).

TPU-native framing: "memory pressure" is HBM-row occupancy of the preallocated
``SeriesStore`` (sample columns) and series-slot occupancy (rows), instead of JVM
write buffers + off-heap blocks.
"""

from __future__ import annotations

import numpy as np


class EvictionPolicy:
    """Decides when a shard should reclaim store capacity."""

    def should_evict(self, store, config) -> bool:  # pragma: no cover - interface
        raise NotImplementedError


class CapacityEvictionPolicy(EvictionPolicy):
    """Evict only when some series row is full — the minimal policy (and the
    historical default): compaction happens exactly when an append could wrap."""

    def should_evict(self, store, config) -> bool:
        return bool(store.n_host.max(initial=0) >= config.samples_per_series)


class HeadroomEvictionPolicy(EvictionPolicy):
    """Keep at least ``headroom`` fraction of sample capacity free on the fullest
    row (ref: WriteBufferFreeEvictionPolicy's min-free-percent idea)."""

    def __init__(self, headroom: float = 0.1):
        assert 0.0 < headroom < 1.0
        self.headroom = headroom

    def should_evict(self, store, config) -> bool:
        cap = config.samples_per_series
        return bool(store.n_host.max(initial=0) >= cap * (1.0 - self.headroom))


class CompositeEvictionPolicy(EvictionPolicy):
    """Evict when any sub-policy says so (ref: CompositeEvictionPolicy)."""

    def __init__(self, *policies: EvictionPolicy):
        self.policies = policies

    def should_evict(self, store, config) -> bool:
        return any(p.should_evict(store, config) for p in self.policies)


class BloomFilter:
    """Fixed-size bloom filter over part-key bytes (ref: TimeSeriesShard's
    evictedPartKeys bloom, sized for millions of keys at low fp rate)."""

    def __init__(self, capacity: int = 1 << 20, hashes: int = 4):
        # ~9.6 bits/key at k=4 gives ~2% fp; round bits to a power of two
        bits = 1
        while bits < capacity * 10:
            bits <<= 1
        self._mask = bits - 1
        self._bits = np.zeros(bits >> 3, np.uint8)
        self._k = hashes
        self.count = 0

    def _positions(self, key: bytes):
        import zlib
        h1 = zlib.crc32(key)
        h2 = zlib.adler32(key) | 1
        for i in range(self._k):
            yield (h1 + i * h2) & self._mask

    def add(self, key: bytes) -> None:
        for pos in self._positions(key):
            self._bits[pos >> 3] |= 1 << (pos & 7)
        self.count += 1

    def __contains__(self, key: bytes) -> bool:
        if not self.count:      # nothing ever evicted: registration hot path
            return False
        return all(self._bits[p >> 3] & (1 << (p & 7)) for p in self._positions(key))
