"""Computed (derived) columns — ``:func arg...`` expressions over ingest batches.

Reference: core/src/main/scala/filodb.core/metadata/ComputedColumn.scala (expression
analysis, ``AllComputations`` registry, InvalidFunctionSpec errors),
SimpleComputations.scala (:string/:getOrElse/:round/:stringPrefix/:hash) and
TimeComputations.scala (:timeslice/:monthOfYear).

TPU-native difference: the reference computes values row-at-a-time through
``TypedFieldExtractor``s in the ingest hot loop; here a computed column is a
*vectorized* function over a whole ``RecordContainer`` (numpy for numeric sources,
one pass over the distinct label sets for string sources), so the cost is
per-batch, not per-record.

A computed column reads either a data column of the schema (``timestamp``,
``value``...) or a label tag; the analyzer resolves which at analysis time.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np

from .record import RecordContainer, fnv1a64
from .schemas import ColumnType, Schema



class InvalidFunctionSpec(ValueError):
    """Base for expression-analysis failures (ref: ComputedColumn.scala:61-66)."""


class NoSuchFunction(InvalidFunctionSpec):
    pass


class WrongNumberArguments(InvalidFunctionSpec):
    def __init__(self, given: int, expected: int):
        super().__init__(f"wrong number of arguments: given {given}, expected {expected}")


class BadArgument(InvalidFunctionSpec):
    pass


class NotComputedColumn(InvalidFunctionSpec):
    pass


@dataclass(frozen=True)
class ComputedColumn:
    """An analyzed expression ready to evaluate against containers.

    ``compute(container)`` returns a per-record numpy array (numeric results) or a
    list[str] (string results), parallel to ``container.ts``.
    """
    expr: str
    ctype: ColumnType
    source: str | None                    # data-column or label name ('' for const)
    _fn: Callable[[RecordContainer], "np.ndarray | list[str]"]

    @property
    def name(self) -> str:
        return self.expr

    def compute(self, container: RecordContainer):
        return self._fn(container)


def is_computed(expr: str) -> bool:
    return expr.startswith(":")


def _parse_duration_ms(arg: str) -> int:
    from ..config import parse_duration_ms
    try:
        return parse_duration_ms(arg)
    except ValueError as e:
        raise BadArgument(str(e)) from None


def _numeric_source(schema: Schema, name: str) -> ColumnType:
    for c in schema.columns:
        if c.name == name:
            if c.ctype not in (ColumnType.INT, ColumnType.LONG, ColumnType.DOUBLE,
                               ColumnType.TIMESTAMP):
                raise BadArgument(f"column {name} of type {c.ctype.value} is not numeric")
            return c.ctype
    raise BadArgument(f"no numeric data column named {name!r} in schema {schema.name}")


def _numeric_values(container: RecordContainer, name: str) -> np.ndarray:
    # The columnar container carries exactly the timestamp + value columns.
    if name == container.schema.columns[0].name:
        return container.ts
    if container.values.ndim != 1:
        raise BadArgument(f"column {name!r} is not a scalar column in this container")
    return container.values


def _label_values(container: RecordContainer, tag: str, default: str | None = None) -> list[str]:
    """One lookup per *distinct* label set, then a vectorized gather per record."""
    distinct = [ls.get(tag, default) for ls in container.label_sets]
    missing = [i for i, v in enumerate(distinct) if v is None]
    if missing:
        raise BadArgument(f"label {tag!r} missing from series {missing[0]} and no default given")
    return [distinct[i] for i in container.part_idx]


def _is_data_column(schema: Schema, name: str) -> bool:
    return any(c.name == name for c in schema.columns)


def _analyze_string(args: list[str], schema: Schema) -> ComputedColumn:
    # :string <const> — constant string column (SimpleComputations.scala:19)
    if len(args) != 1:
        raise WrongNumberArguments(len(args), 1)
    const = args[0]
    return ComputedColumn(f":string {const}", ColumnType.STRING, None,
                          lambda c: [const] * len(c))


def _analyze_get_or_else(args: list[str], schema: Schema) -> ComputedColumn:
    # :getOrElse <tag> <default> (SimpleComputations.scala:40)
    if len(args) != 2:
        raise WrongNumberArguments(len(args), 2)
    tag, default = args
    if _is_data_column(schema, tag):
        raise BadArgument(f"{tag!r} is a data column; :getOrElse applies to label tags")
    return ComputedColumn(f":getOrElse {tag} {default}", ColumnType.STRING, tag,
                          lambda c: _label_values(c, tag, default))


def _analyze_round(args: list[str], schema: Schema) -> ComputedColumn:
    # :round <col> <to-nearest> — rounds DOWN to a multiple (SimpleComputations.scala:73)
    if len(args) != 2:
        raise WrongNumberArguments(len(args), 2)
    col, nearest_s = args
    ctype = _numeric_source(schema, col)
    try:
        nearest = float(nearest_s) if ctype == ColumnType.DOUBLE else int(nearest_s)
    except ValueError as e:
        raise BadArgument(str(e)) from None
    if nearest <= 0:
        raise BadArgument(f"round-to value must be positive, got {nearest_s}")

    def fn(c: RecordContainer):
        v = _numeric_values(c, col)
        if ctype == ColumnType.DOUBLE:
            return np.floor(v / nearest) * nearest
        return (v.astype(np.int64) // int(nearest)) * int(nearest)

    return ComputedColumn(f":round {col} {nearest_s}", ctype, col, fn)


def _analyze_string_prefix(args: list[str], schema: Schema) -> ComputedColumn:
    # :stringPrefix <tag> <numChars> (SimpleComputations.scala:103)
    if len(args) != 2:
        raise WrongNumberArguments(len(args), 2)
    tag, n_s = args
    try:
        n = int(n_s)
    except ValueError as e:
        raise BadArgument(str(e)) from None
    if n < 0:
        raise BadArgument("prefix length must be >= 0")
    return ComputedColumn(f":stringPrefix {tag} {n}", ColumnType.STRING, tag,
                          lambda c: [s[:n] for s in _label_values(c, tag, "")])


def _analyze_hash(args: list[str], schema: Schema) -> ComputedColumn:
    # :hash <col-or-tag> <numBuckets> (SimpleComputations.scala:121)
    if len(args) != 2:
        raise WrongNumberArguments(len(args), 2)
    src, nb_s = args
    try:
        buckets = int(nb_s)
    except ValueError as e:
        raise BadArgument(str(e)) from None
    if buckets <= 0:
        raise BadArgument("bucket count must be positive")

    if _is_data_column(schema, src):
        _numeric_source(schema, src)

        def fn(c: RecordContainer):
            v = _numeric_values(c, src).astype(np.int64)
            return np.abs(v % buckets).astype(np.int32)
    else:
        # hash once per distinct label set, then a vectorized gather per record
        def fn(c: RecordContainer):
            distinct = np.asarray(
                [fnv1a64(ls.get(src, "").encode()) % buckets for ls in c.label_sets],
                np.int32)
            return distinct[c.part_idx]

    return ComputedColumn(f":hash {src} {buckets}", ColumnType.INT, src, fn)


def _analyze_timeslice(args: list[str], schema: Schema) -> ComputedColumn:
    # :timeslice <tsCol> <duration> (TimeComputations.scala:22)
    if len(args) != 2:
        raise WrongNumberArguments(len(args), 2)
    col, dur_s = args
    ctype = _numeric_source(schema, col)
    if ctype not in (ColumnType.LONG, ColumnType.TIMESTAMP):
        raise BadArgument(f":timeslice needs a long/timestamp column, got {ctype.value}")
    dur = _parse_duration_ms(dur_s)

    def fn(c: RecordContainer):
        v = _numeric_values(c, col).astype(np.int64)
        return (v // dur) * dur

    return ComputedColumn(f":timeslice {col} {dur_s}", ColumnType.TIMESTAMP, col, fn)


def _analyze_month_of_year(args: list[str], schema: Schema) -> ComputedColumn:
    # :monthOfYear <tsCol> — 1..12 in UTC (TimeComputations.scala:51)
    if len(args) != 1:
        raise WrongNumberArguments(len(args), 1)
    col = args[0]
    ctype = _numeric_source(schema, col)
    if ctype not in (ColumnType.LONG, ColumnType.TIMESTAMP):
        raise BadArgument(f":monthOfYear needs a long/timestamp column, got {ctype.value}")

    def fn(c: RecordContainer):
        ms = _numeric_values(c, col).astype("datetime64[ms]")
        months = ms.astype("datetime64[M]").astype(np.int64) % 12 + 1
        return months.astype(np.int32)

    return ComputedColumn(f":monthOfYear {col}", ColumnType.INT, col, fn)


ALL_COMPUTATIONS: dict[str, Callable[[list[str], Schema], ComputedColumn]] = {
    "string": _analyze_string,
    "getOrElse": _analyze_get_or_else,
    "round": _analyze_round,
    "stringPrefix": _analyze_string_prefix,
    "hash": _analyze_hash,
    "timeslice": _analyze_timeslice,
    "monthOfYear": _analyze_month_of_year,
}


def analyze(expr: str, schema: Schema) -> ComputedColumn:
    """Parse + validate a ``:func arg...`` expression against a schema.

    Raises ``NotComputedColumn`` / ``NoSuchFunction`` / ``WrongNumberArguments`` /
    ``BadArgument`` (ref: ComputedColumn.analyze, ComputedColumn.scala:45-57).
    """
    if not is_computed(expr):
        raise NotComputedColumn(expr)
    parts = expr[1:].split()
    if not parts:
        raise NoSuchFunction("(empty)")
    fname, args = parts[0], parts[1:]
    analyzer = ALL_COMPUTATIONS.get(fname)
    if analyzer is None:
        raise NoSuchFunction(fname)
    return analyzer(args, schema)
