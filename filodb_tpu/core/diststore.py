"""Distributed, durable chunk store — the Cassandra-layer equivalent.

Reference: cassandra/.../columnstore/CassandraColumnStore.scala:47 (chunk +
ingestion-time-index + partkey tables, token-range ``getScanSplits`` feeding
Spark batch jobs) and metastore/CheckpointTable.scala. Cassandra supplies
replication and remote durability; here the same story is built from the
framework's own parts:

  - ``StoreServer``: a TCP daemon exposing one node's column-store files
    through three verbs (APPEND for the chunk/part-key logs, PUT for atomic
    meta/checkpoint replacement, GET for reads) — the "storage node".
  - ``RemoteStore``: a ChunkSink client speaking that protocol; byte-level
    formats are identical to FileColumnStore (the chunk-log parser is
    shared), so local and remote stores interoperate.
  - ``ReplicatedColumnStore``: fans writes out to ``replication`` replicas
    chosen on a ring keyed by (dataset, shard); reads fail over to the first
    healthy replica. Write succeeds if at least one replica accepted. A
    replica that misses a write stays divergent for those frames (effective
    RF degrades until the log is re-replicated operationally); reads defend
    against divergence by picking the replica with the most distinct
    in-range samples (see ``read_chunksets``), and recovery's replay dedups
    duplicate frames from retried flushes.
  - ``get_scan_splits``: time-range splits (the token-range analog), aligned
    to a resolution so batch downsampling over splits never splits a bucket.
"""

from __future__ import annotations

import io
import json
import logging
import socket
import socketserver
import struct
import threading
import time
import zlib

import numpy as np

from ..utils.metrics import FILODB_RETENTION_REPLICA_FAILOVER, registry
from ..utils.netio import recv_exact as _recv_exact
from .store import (ChunkSink, encode_age_out, encode_chunkset,
                    head_frame_min_ts, iter_chunksets)

log = logging.getLogger(__name__)

_REQ = struct.Struct("<BII")      # op, header_len, payload_len
_RESP = struct.Struct("<BQ")      # status (0 ok), u64 body_len (logs can be big)

OP_APPEND, OP_PUT, OP_GET, OP_STAT = 1, 2, 3, 4
# streaming/checkpoint ops of the durable-tier flush path (PR 10):
#   OP_APPEND_CRC — CRC32-verified chunk-frame append: the server recomputes
#     the payload checksum and refuses a torn/corrupted frame instead of
#     appending garbage the log parser would silently truncate at
#   OP_CHECKPOINT — server-side atomic per-(dataset, shard, group) watermark
#     merge: the old client read-modify-write of checkpoint.json lost a
#     concurrent group's commit when two flush groups checkpointed at once
#   OP_COMMIT — atomic rename of a staged ``.rewrite`` object over its live
#     twin: age-out rewrites stage slices beside the log and commit once,
#     so a connection lost mid-rewrite leaves the live log untouched (a
#     truncating in-place PUT destroyed already-replicated frames)
OP_APPEND_CRC, OP_CHECKPOINT, OP_COMMIT = 5, 6, 7

_MAX_HEADER = 1 << 16             # refuse absurd frames instead of OOMing
_MAX_PAYLOAD = 256 << 20

_ALLOWED = {"chunks.log", "partkeys.log", "meta.json", "checkpoint.json",
            "chunks.log.rewrite", "index.log"}


class StoreServer:
    """One storage node: serves a FileColumnStore directory over TCP."""

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0):
        import os
        self.root = root
        os.makedirs(root, exist_ok=True)
        # serializes checkpoint merges (OP_CHECKPOINT): two flush groups
        # committing concurrently must not lose each other's watermark
        self._cp_lock = threading.Lock()
        # per-object commit generation, bumped whenever a whole object is
        # REPLACED (OP_COMMIT age-out promotion, OP_PUT): ranged readers
        # compare the generation across their read to detect that offsets
        # from the old file landed mid-frame in a rewritten one
        self._gen_lock = threading.Lock()
        self._gens: dict = {}
        # established connections, severed by stop(): RemoteStore clients
        # pool their socket, so a handler thread blocked in recv would keep
        # SERVING a "stopped" node forever — an in-process kill must look
        # like a process kill (reset the peer) for failover to engage
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def setup(self):
                with outer._conns_lock:
                    outer._conns.add(self.request)

            def finish(self):
                with outer._conns_lock:
                    outer._conns.discard(self.request)

            def handle(self):
                try:
                    while True:
                        hdr = _recv_exact(self.request, _REQ.size)
                        op, hlen, plen = _REQ.unpack(hdr)
                        if hlen > _MAX_HEADER or plen > _MAX_PAYLOAD:
                            return   # garbage/hostile frame: drop connection
                        raw = _recv_exact(self.request, hlen)
                        payload = _recv_exact(self.request, plen) if plen else b""
                        try:
                            meta = json.loads(raw)
                            body = outer._serve(op, meta, payload)
                            self.request.sendall(_RESP.pack(0, len(body)) + body)
                        except Exception as e:  # noqa: BLE001 - to client
                            msg = str(e).encode()
                            self.request.sendall(_RESP.pack(1, len(msg)) + msg)
                except (ConnectionError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="store-server")

    def _path(self, meta) -> str:
        import os
        name = meta["name"]
        dataset = str(meta["dataset"]).replace("/", "_").replace("..", "_")
        if name not in _ALLOWED:
            raise ValueError(f"unknown store object {name!r}")
        d = os.path.join(self.root, dataset, f"shard{int(meta['shard'])}")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, name)

    def _serve(self, op: int, meta, payload: bytes) -> bytes:
        import os
        path = self._path(meta)
        if op == OP_APPEND:
            with open(path, "ab") as f:
                f.write(payload)
            return b""
        if op == OP_APPEND_CRC:
            # refuse a frame whose bytes were damaged in flight: appending it
            # would poison the log tail (the WAL parser stops at the first
            # bad frame, hiding every later good one)
            want = int(meta["crc"])
            got = zlib.crc32(payload)
            if got != want:
                raise ValueError(
                    f"chunk frame crc mismatch (got {got:#x}, want "
                    f"{want:#x}); refusing append")
            with open(path, "ab") as f:
                f.write(payload)
            return b""
        if op == OP_CHECKPOINT:
            # atomic server-side merge of one group's watermark
            with self._cp_lock:
                cp = {}
                if os.path.exists(path):
                    with open(path) as f:
                        cp = json.load(f)
                cp[str(int(meta["group"]))] = int(meta["offset"])
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(cp, f)
                os.replace(tmp, path)
            return b""
        if op == OP_PUT:
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, path)
            self._bump_gen(path)
            return b""
        if op == OP_COMMIT:
            # atomically promote a staged rewrite over the live object; the
            # stage must exist (a lost rewrite must surface, not no-op)
            if not path.endswith(".rewrite"):
                raise ValueError("commit target must be a staged "
                                 "'.rewrite' object")
            live = path[:-len(".rewrite")]
            os.replace(path, live)
            self._bump_gen(live)
            return b""
        if op == OP_GET:
            if not os.path.exists(path):
                return b""
            offset = int(meta.get("offset", 0))
            length = meta.get("length")
            with open(path, "rb") as f:
                f.seek(offset)
                return f.read(int(length)) if length is not None else f.read()
        if op == OP_STAT:
            size = os.path.getsize(path) if os.path.exists(path) else 0
            with self._gen_lock:
                gen = self._gens.get(path, 0)
            return struct.pack("<QQ", size, gen)
        raise ValueError(f"unknown op {op}")

    def _bump_gen(self, path: str) -> None:
        with self._gen_lock:
            self._gens[path] = self._gens.get(path, 0) + 1

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "StoreServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self._thread.join(timeout=3)


class RemoteStore(ChunkSink):
    """ChunkSink client of a StoreServer; wire formats match FileColumnStore.

    Connect and read are BOUNDED (``connect_timeout_s`` / ``timeout_s``): a
    dead backend surfaces as a timeout the ReplicatedColumnStore fails over
    from, instead of stalling the query/flush thread on a silent socket."""

    remote_tier = True     # ODP accounting: pages come over the wire

    def __init__(self, addr: str, timeout_s: float = 30.0,
                 connect_timeout_s: float = 5.0):
        self.addr = addr
        self.timeout_s = float(timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self._sock = None
        self._lock = threading.Lock()

    def _conn(self) -> socket.socket:
        if self._sock is None:
            host, port = self.addr.rsplit(":", 1)
            s = socket.create_connection((host, int(port)),
                                         timeout=self.connect_timeout_s)
            s.settimeout(self.timeout_s)   # bounds every recv/send after
            self._sock = s
        return self._sock

    def _request(self, op: int, dataset, shard, name, payload: bytes = b"",
                 **extra) -> bytes:
        meta = json.dumps({"dataset": dataset, "shard": shard,
                           "name": name, **extra}).encode()
        with self._lock:
            try:
                s = self._conn()
                s.sendall(_REQ.pack(op, len(meta), len(payload)) + meta + payload)
                status, blen = _RESP.unpack(_recv_exact(s, _RESP.size))
                body = _recv_exact(s, blen) if blen else b""
            except (ConnectionError, OSError):
                self.close()
                raise
        if status != 0:
            raise IOError(f"remote store error: {body.decode()}")
        return body

    # -- ChunkSink: writes ---------------------------------------------------

    def write_chunkset(self, dataset, shard, group, records):
        buf = encode_chunkset(group, records)
        self._request(OP_APPEND_CRC, dataset, shard, "chunks.log", buf,
                      crc=zlib.crc32(buf))

    def write_part_keys(self, dataset, shard, entries):
        lines = "".join(
            json.dumps({"id": pid, "labels": labels, "start": start},
                       separators=(",", ":")) + "\n"
            for pid, labels, start in entries)
        self._request(OP_APPEND, dataset, shard, "partkeys.log", lines.encode())

    def write_index_bucket(self, dataset, shard, frame: bytes):
        # CRC-verified append: a frame damaged in flight is refused by the
        # server, and the frame's OWN crc (inside the payload) still guards
        # the at-rest bytes at recovery time
        self._request(OP_APPEND_CRC, dataset, shard, "index.log", frame,
                      crc=zlib.crc32(frame))

    def read_index_frames(self, dataset, shard):
        from .store import iter_index_frames
        blob = self._request(OP_GET, dataset, shard, "index.log")
        yield from iter_index_frames(io.BytesIO(blob))

    def write_meta(self, dataset, shard, meta: dict):
        self._request(OP_PUT, dataset, shard, "meta.json",
                      json.dumps(meta).encode())

    def write_checkpoint(self, dataset, shard, group, offset):
        # one round trip, merged atomically server-side: the old client
        # read-modify-write lost a concurrent group's commit
        self._request(OP_CHECKPOINT, dataset, shard, "checkpoint.json",
                      group=int(group), offset=int(offset))

    # -- reads ---------------------------------------------------------------

    def read_chunksets(self, dataset, shard, start_ms: int = 0,
                       end_ms: int = 1 << 62):
        # stream the log in ranged chunks instead of buffering it whole: the
        # parser sees a buffered file-like over ranged GETs. The read takes
        # no lock against an age-out rewrite (OP_COMMIT swaps the file), so
        # bracket it with the server's commit generation: offsets from the
        # old file land mid-frame in the rewritten one and iter_chunksets
        # would silently truncate — raise instead, so the replicated layer
        # fails over (or the caller retries) rather than serving a partial
        # answer as complete
        gen0 = self._stat(dataset, shard, "chunks.log")[1]
        raw = _RangedReader(self, dataset, shard, "chunks.log")
        yield from iter_chunksets(io.BufferedReader(raw, 1 << 20),
                                  start_ms, end_ms)
        if self._stat(dataset, shard, "chunks.log")[1] != gen0:
            raise IOError("chunks.log was rewritten (age-out commit) during "
                          "a ranged read; rereading required")

    def read_part_keys(self, dataset, shard):
        blob = self._request(OP_GET, dataset, shard, "partkeys.log")
        for line in blob.decode().splitlines():
            if not line.strip():
                continue
            try:
                e = json.loads(line)
            except ValueError:
                return
            yield e["id"], e["labels"], e["start"]

    def _stat(self, dataset, shard, name) -> tuple:
        """(byte size, commit generation) of a store object."""
        body = self._request(OP_STAT, dataset, shard, name)
        return struct.unpack("<QQ", body) if body else (0, 0)

    def chunk_log_size(self, dataset, shard) -> int:
        """Byte size of the shard's chunk log (cheap best-replica probe)."""
        return self._stat(dataset, shard, "chunks.log")[0]

    def read_meta(self, dataset, shard) -> dict:
        blob = self._request(OP_GET, dataset, shard, "meta.json")
        return json.loads(blob) if blob else {}

    def read_checkpoints(self, dataset, shard):
        blob = self._request(OP_GET, dataset, shard, "checkpoint.json")
        return {int(k): v for k, v in json.loads(blob).items()} if blob else {}

    # age_out rewrite slice size: comfortably under the server's
    # _MAX_PAYLOAD frame cap (a whole-log single PUT would be silently
    # dropped — connection severed, no response — once the log outgrew it)
    _AGE_OUT_SLICE = 64 << 20

    def age_out(self, dataset, shard, cutoff_ms: int) -> int:
        """Durable raw retention: rewrite the shard's chunk log dropping
        samples older than ``cutoff_ms`` (the caller serializes against
        concurrent flush appends — see TimeSeriesShard.age_out_durable).
        The rewrite stages beside the live log in bounded CRC'd slices and
        commits with ONE atomic server-side rename (OP_COMMIT): a
        connection lost mid-rewrite leaves the live log untouched — a
        truncating in-place PUT would have destroyed already-replicated
        frames on that replica. Returns samples dropped."""
        # steady-state skip: probe the head frame with ONE small ranged
        # read — when it holds nothing past the cutoff, the full pass
        # would pull and decode the whole log over the network (and buffer
        # the rewrite in memory) to drop zero samples, all while the
        # caller holds every group flush lock (see head_frame_min_ts)
        raw = _RangedReader(self, dataset, shard, "chunks.log")
        head = head_frame_min_ts(io.BufferedReader(raw, 1 << 20))
        if head is None or head >= cutoff_ms:
            return 0
        buf, dropped = encode_age_out(
            self.read_chunksets(dataset, shard), cutoff_ms)
        if dropped:
            first, rest = buf[:self._AGE_OUT_SLICE], buf[self._AGE_OUT_SLICE:]
            self._request(OP_PUT, dataset, shard, "chunks.log.rewrite", first)
            for at in range(0, len(rest), self._AGE_OUT_SLICE):
                sl = rest[at:at + self._AGE_OUT_SLICE]
                self._request(OP_APPEND_CRC, dataset, shard,
                              "chunks.log.rewrite", sl, crc=zlib.crc32(sl))
            self._request(OP_COMMIT, dataset, shard, "chunks.log.rewrite")
        return dropped

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


class _RangedReader(io.RawIOBase):
    """File-like over ranged GETs (wrap in io.BufferedReader)."""

    _CHUNK = 4 << 20

    def __init__(self, store: "RemoteStore", dataset, shard, name):
        self._store = store
        self._args = (dataset, shard, name)
        self._pos = 0

    def readable(self):
        return True

    def readinto(self, b):
        want = min(len(b), self._CHUNK)
        blob = self._store._request(OP_GET, *self._args,
                                    offset=self._pos, length=want)
        b[:len(blob)] = blob
        self._pos += len(blob)
        return len(blob)


class ReplicatedColumnStore(ChunkSink):
    """Replication + failover over N backend stores (local or remote).

    Writes go to ``replication`` replicas chosen on a STABLE ring keyed by
    crc32(dataset:shard) — Python's hash() randomizes per process, which
    would strand previously written data. At least one replica must accept a
    write. Reads consult every reachable replica and serve the one with the
    most data: an outage can leave a replica with a gappy log, and a partial
    answer must not mask a complete one (ref: Cassandra replica placement;
    read-best stands in for read repair)."""

    remote_tier = True     # ODP accounting: pages come over the wire

    WRITE_ATTEMPTS = 2     # per-replica retries before the write is skipped
    # writes safe to re-send to the SAME replica: meta/checkpoint replace
    # atomically, and part-key / index-bucket events dedup at recovery
    # (latest-per-pid wins, so a duplicated frame replays identically).
    # Chunk appends are NOT here — a lost response after a server-side apply
    # would duplicate the frame in that replica's log; they get one attempt
    # per replica and rely on cross-replica failover instead
    _IDEMPOTENT_WRITES = frozenset({"write_meta", "write_checkpoint",
                                    "write_part_keys",
                                    "write_index_bucket"})

    def __init__(self, backends: list, replication: int = 2):
        assert backends, "need at least one backend"
        self.backends = backends
        self.replication = min(replication, len(backends))
        # optional epoch fence (cluster/epoch.py StoreFence): consulted
        # before EVERY replica write so a deposed shard owner's flush or
        # checkpoint raises FencedWriteError instead of corrupting the
        # shard a replacement node already warmed
        self.write_guard = None

    def _write(self, dataset, shard, fn_name, *args):
        if self.write_guard is not None:
            self.write_guard(dataset, shard, fn_name)
        return self._write_unguarded(dataset, shard, fn_name, *args)

    def _replicas(self, dataset, shard):
        key = f"{dataset}:{shard}".encode()
        start = zlib.crc32(key) % len(self.backends)
        return [self.backends[(start + i) % len(self.backends)]
                for i in range(self.replication)]

    @staticmethod
    def _count_failover(op: str) -> None:
        registry.counter(FILODB_RETENTION_REPLICA_FAILOVER,
                         {"op": op}).increment()

    def _write_unguarded(self, dataset, shard, fn_name, *args):
        wrote = 0
        last_err = None
        attempts = (self.WRITE_ATTEMPTS
                    if fn_name in self._IDEMPOTENT_WRITES else 1)
        for b in self._replicas(dataset, shard):
            # idempotent writes get one bounded same-replica retry (a
            # transient fault lands on retry); non-idempotent chunk appends
            # take one attempt per replica — failover, never re-send (see
            # _IDEMPOTENT_WRITES)
            for attempt in range(attempts):
                try:
                    getattr(b, fn_name)(dataset, shard, *args)
                    wrote += 1
                    break
                except Exception as e:  # noqa: BLE001 - replica tolerated
                    last_err = e
                    log.warning("replica write %s failed on %r "
                                "(attempt %d): %s", fn_name, b, attempt + 1, e)
                    if attempt + 1 < attempts:
                        # brief linear backoff before the same-replica
                        # retry: the transient fault (GC pause, fd churn)
                        # needs a beat to clear, and a hot re-send burns
                        # the attempt budget in microseconds
                        time.sleep(0.05 * (attempt + 1))
        if wrote == 0:
            raise IOError(f"all {self.replication} replicas failed") from last_err
        return wrote

    def write_chunkset(self, dataset, shard, group, records):
        self._write(dataset, shard, "write_chunkset", group, records)

    def write_part_keys(self, dataset, shard, entries):
        self._write(dataset, shard, "write_part_keys", list(entries))

    def write_meta(self, dataset, shard, meta):
        self._write(dataset, shard, "write_meta", meta)

    def write_checkpoint(self, dataset, shard, group, offset):
        self._write(dataset, shard, "write_checkpoint", group, offset)

    def _read_all(self, dataset, shard, fn_name, *args):
        """Results from every reachable replica: [(backend, result), ...]."""
        out = []
        last_err = None
        for b in self._replicas(dataset, shard):
            try:
                res = getattr(b, fn_name)(dataset, shard, *args)
                out.append((b, list(res) if res is not None and
                            fn_name in ("read_chunksets", "read_part_keys")
                            else res))
            except Exception as e:  # noqa: BLE001 - fail over
                last_err = e
                self._count_failover(fn_name)
                log.warning("replica read %s failed on %r: %s", fn_name, b, e)
        if not out:
            raise IOError("all replicas failed") from last_err
        return out

    def read_chunksets(self, dataset, shard, start_ms: int = 0,
                       end_ms: int = 1 << 62):
        """Best-replica read: a replica that missed appends during an outage
        must not mask a complete sibling.

        Range-bounded reads (queries, scan splits) materialize every
        reachable replica's overlapping records and serve the one with the
        most samples IN RANGE — exact, and bounded by the window. Unbounded
        reads (recovery scans the whole log) pick by a cheap size probe and
        stream, trying every replica in descending-size order; a failed stat
        only demotes a replica to the end of the order, never excludes it."""
        probed = []
        for b in self._replicas(dataset, shard):
            size = None
            if hasattr(b, "chunk_log_size"):
                try:
                    size = b.chunk_log_size(dataset, shard)
                except Exception as e:  # noqa: BLE001 - stat only demotes
                    log.warning("replica stat failed on %r: %s", b, e)
            probed.append((b, size))
        sizes = [s for _b, s in probed if s is not None]
        bounded = start_ms > 0 or end_ms < 1 << 62
        diverged = len(set(sizes)) != 1 or len(sizes) != len(probed)
        if bounded and diverged:
            # replicas disagree: materialize the window from each reachable
            # one and serve the most complete — exact, bounded by the window
            results = self._read_all(dataset, shard, "read_chunksets",
                                     start_ms, end_ms)

            def total(res):
                # count DISTINCT (pid, ts) samples: retried flushes can leave
                # duplicate frames, and raw lengths would let a
                # duplicate-inflated replica outrank a sibling holding more
                # distinct data
                per_pid: dict[int, list] = {}
                for _g, recs in res:
                    for r in recs:
                        per_pid.setdefault(r.part_id, []).append(r.ts)
                return sum(len(np.unique(np.concatenate(v)))
                           for v in per_pid.values())
            return max((res for _b, res in results), key=total)
        # replicas agree (or the read is an unbounded recovery scan): stream
        # from one, in descending-size order with failover
        order = sorted(probed, key=lambda p: -(p[1] if p[1] is not None else -1))
        last_err = None
        for b, _size in order:
            try:
                return list(b.read_chunksets(dataset, shard, start_ms, end_ms))
            except Exception as e:  # noqa: BLE001 - fail over
                last_err = e
                self._count_failover("read_chunksets")
                log.warning("replica read failed on %r: %s", b, e)
        raise IOError("all replicas failed") from last_err

    def read_part_keys(self, dataset, shard):
        results = self._read_all(dataset, shard, "read_part_keys")
        return max((res or [] for _b, res in results), key=len)

    def write_index_bucket(self, dataset, shard, frame: bytes):
        self._write(dataset, shard, "write_index_bucket", frame)

    def read_index_frames(self, dataset, shard):
        """Best-replica read of the index time buckets, trust-aware: a
        replica's log is only usable when a GENESIS frame follows its last
        RETIRE marker, and reachable replicas must AGREE on that — a
        sibling that missed a RETIRE write (gappy outage) could otherwise
        win the entry-count race and resurrect a stale log. On
        disagreement this returns an empty list, which recovery treats as
        untrusted (partkeys.log fallback — never a silent loss). Among
        agreeing-trusted replicas, the one holding the most index EVENTS
        wins."""
        from .store import INDEX_GENESIS_BUCKET, INDEX_RETIRE_BUCKET
        backends = [b for b in self._replicas(dataset, shard)
                    if hasattr(b, "read_index_frames")]
        if not backends:
            return []
        results = []
        last_err = None
        for b in backends:
            try:
                results.append(list(b.read_index_frames(dataset, shard)))
            except Exception as e:  # noqa: BLE001 - fail over
                last_err = e
                self._count_failover("read_index_frames")
                log.warning("replica index read failed on %r: %s", b, e)
        if not results:
            raise IOError("all replicas failed") from last_err

        def trusted(fr) -> bool:
            gen_at = retire_at = -1
            for i, frame in enumerate(fr):
                if frame[0] == INDEX_GENESIS_BUCKET:
                    gen_at = i
                elif frame[0] == INDEX_RETIRE_BUCKET:
                    retire_at = i
            return gen_at >= 0 and gen_at > retire_at

        verdicts = [trusted(fr) for fr in results]
        if not all(verdicts):
            if any(verdicts):
                log.warning("index.log replicas disagree on trust anchors "
                            "for %s shard %s; forcing partkeys.log fallback",
                            dataset, shard)
            return []
        return max(results,
                   key=lambda fr: sum(len(frame[1]) for frame in fr))

    def read_meta(self, dataset, shard) -> dict:
        for _b, res in self._read_all(dataset, shard, "read_meta"):
            if res:
                return res
        return {}

    def read_checkpoints(self, dataset, shard):
        # per-group max across replicas: the freshest durable watermark wins
        merged: dict[int, int] = {}
        for _b, res in self._read_all(dataset, shard, "read_checkpoints"):
            for g, off in (res or {}).items():
                merged[g] = max(merged.get(g, -1), off)
        return merged

    def age_out(self, dataset, shard, cutoff_ms: int) -> int:
        """Age raw samples past the retention horizon out of EVERY replica
        (each rewrites its own view — replicas may hold different frame
        sets after an outage; a per-replica rewrite never copies one
        replica's gaps onto another). Returns the max dropped count."""
        if self.write_guard is not None:
            self.write_guard(dataset, shard, "age_out")
        dropped = 0
        for b in self._replicas(dataset, shard):
            if not hasattr(b, "age_out"):
                continue
            try:
                dropped = max(dropped, b.age_out(dataset, shard, cutoff_ms))
            except Exception as e:  # noqa: BLE001 - replica tolerated
                self._count_failover("age_out")
                log.warning("replica age_out failed on %r: %s", b, e)
        return dropped

    def close(self):
        for b in self.backends:
            if hasattr(b, "close"):
                b.close()


def get_scan_splits(store, dataset, shard, n_splits: int,
                    align_ms: int = 60_000) -> list[tuple[int, int]]:
    """Time-range scan splits over a shard's persisted chunks (the
    ``getScanSplits`` token-range analog, CassandraColumnStore.scala:47).
    Boundaries align to ``align_ms`` so a batch job mapping over splits never
    splits a downsample bucket across two workers."""
    lo, hi = None, None
    for _g, records in store.read_chunksets(dataset, shard) or ():
        for r in records:
            if len(r.ts):
                lo = int(r.ts[0]) if lo is None else min(lo, int(r.ts[0]))
                hi = int(r.ts[-1]) if hi is None else max(hi, int(r.ts[-1]))
    if lo is None:
        return []
    n_splits = max(1, n_splits)
    lo_al = (lo // align_ms) * align_ms
    hi_al = ((hi // align_ms) + 1) * align_ms
    span = hi_al - lo_al
    per = max(((span // n_splits) // align_ms) * align_ms, align_ms)
    splits = []
    start = lo_al
    while start < hi_al:
        end = min(start + per, hi_al)
        if len(splits) == n_splits - 1:
            end = hi_al
        splits.append((start, end - 1))    # inclusive ranges, disjoint
        start = end
    return splits
