"""Column filters used to select time series by label.

Reference: core/.../query/KeyFilter.scala (Filter ADT: Equals, In, And,
NotEquals, EqualsRegex, NotEqualsRegex) + ColumnFilter.
"""

from __future__ import annotations

import re
from dataclasses import dataclass


@dataclass(frozen=True)
class Filter:
    label: str

    def matches(self, value: str) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError


@dataclass(frozen=True)
class Equals(Filter):
    value: str

    def matches(self, value: str) -> bool:
        return value == self.value


@dataclass(frozen=True)
class NotEquals(Filter):
    value: str

    def matches(self, value: str) -> bool:
        return value != self.value


@dataclass(frozen=True)
class In(Filter):
    values: tuple[str, ...]

    def matches(self, value: str) -> bool:
        return value in self.values


@dataclass(frozen=True)
class EqualsRegex(Filter):
    pattern: str

    def matches(self, value: str) -> bool:
        return re.fullmatch(self.pattern, value) is not None


@dataclass(frozen=True)
class NotEqualsRegex(Filter):
    pattern: str

    def matches(self, value: str) -> bool:
        return re.fullmatch(self.pattern, value) is None
