"""Device-resident columnar series store — the HBM equivalent of the off-heap chunk
substrate.

Reference mapping:
  - memory/.../BlockManager.scala + MemFactory.scala (off-heap blocks, reclaim)
      -> preallocated padded device arrays, amortized compaction instead of blocks
  - core/.../memstore/TimeSeriesPartition.scala (write buffers -> frozen chunks)
      -> host staging buffers -> one batched device scatter per flush group
  - memory/.../data/ChunkMap.scala (per-partition chunk index)
      -> not needed: each series is a contiguous sorted row [series, capacity]

Layout per (shard, schema): ``ts[S, C] int64`` (pad = +sentinel), ``val[S, C]``
(f32 by default; f64 for parity testing), ``n[S] int32`` valid counts. All query
kernels read these arrays directly; ingest appends via an out-of-bounds-dropping
scatter with donated buffers (in-place HBM update, no realloc).

Why not compressed chunks in HBM? The reference compresses to fit ~1M series in a
1GB JVM heap. A TPU chip has 16GB+ HBM: 1M series x 1k samples x (8B ts + 4B val)
fits raw, and raw arrays keep the query path a pure gather/reduce. Compression
(NibblePack & co) lives at the persistence/wire layer (core/store.py).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import diagnostics

TS_PAD = np.int64(1) << np.int64(62)   # sentinel > any real timestamp


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _scatter_append(ts, val, n, rows, cols, new_ts, new_val, counts_add):
    ts = ts.at[rows, cols].set(new_ts, mode="drop")
    val = val.at[rows, cols].set(new_val, mode="drop")
    n = n + counts_add
    return ts, val, n


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _scatter_append_multi(ts, val, extra, n, rows, cols, new_ts, new_val,
                          new_extra, counts_add):
    """Multi-value-column append: the default column plus named scalar
    columns scatter in ONE dispatch (extra/new_extra are dicts — pytree
    donation covers every leaf)."""
    ts = ts.at[rows, cols].set(new_ts, mode="drop")
    val = val.at[rows, cols].set(new_val, mode="drop")
    extra = {k: v.at[rows, cols].set(new_extra[k], mode="drop")
             for k, v in extra.items()}
    n = n + counts_add
    return ts, val, extra, n


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _compact(ts, val, n, cutoff):
    """Drop samples with ts < cutoff by shifting each series row left (one gather)."""
    S, C = ts.shape
    k = jax.vmap(lambda row: jnp.searchsorted(row, cutoff, side="left"))(ts)  # [S]
    idx = jnp.arange(C)[None, :] + k[:, None]                                 # [S, C]
    valid = idx < C
    idx = jnp.where(valid, idx, C - 1)
    new_ts = jnp.where(valid, jnp.take_along_axis(ts, idx, axis=1), TS_PAD)
    if val.ndim == 3:   # histogram store [S, C, B]
        new_val = jnp.where(valid[:, :, None],
                            jnp.take_along_axis(val, idx[:, :, None], axis=1), 0)
    else:
        new_val = jnp.where(valid, jnp.take_along_axis(val, idx, axis=1), 0)
    new_n = jnp.maximum(n - k.astype(n.dtype), 0)
    # re-pad anything beyond the new count (handles rows where k > old n)
    pos = jnp.arange(C)[None, :]
    new_ts = jnp.where(pos < new_n[:, None], new_ts, TS_PAD)
    return new_ts, new_val, new_n


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _compact_multi(ts, val, extra, n, cutoff):
    """Multi-column twin of ``_compact``: one gather per column, shared
    shift indices."""
    S, C = ts.shape
    k = jax.vmap(lambda row: jnp.searchsorted(row, cutoff, side="left"))(ts)
    idx = jnp.arange(C)[None, :] + k[:, None]
    valid = idx < C
    idx = jnp.where(valid, idx, C - 1)
    new_ts = jnp.where(valid, jnp.take_along_axis(ts, idx, axis=1), TS_PAD)
    if val.ndim == 3:
        new_val = jnp.where(valid[:, :, None],
                            jnp.take_along_axis(val, idx[:, :, None], axis=1), 0)
    else:
        new_val = jnp.where(valid, jnp.take_along_axis(val, idx, axis=1), 0)
    new_extra = {kk: jnp.where(valid, jnp.take_along_axis(vv, idx, axis=1), 0)
                 for kk, vv in extra.items()}
    new_n = jnp.maximum(n - k.astype(n.dtype), 0)
    pos = jnp.arange(C)[None, :]
    new_ts = jnp.where(pos < new_n[:, None], new_ts, TS_PAD)
    return new_ts, new_val, new_extra, new_n


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _free_rows(ts, n, pids):
    ts = ts.at[pids, :].set(TS_PAD, mode="drop")
    n = n.at[pids].set(0, mode="drop")
    return ts, n


def _pad_size(m: int) -> int:
    """Bucket flush sizes to powers of two to bound jit recompilations."""
    size = 1024
    while size < m:
        size *= 2
    return size


@jax.jit
def _decode_narrow(q, vmin, scale, pool, pool_rows):
    """Reconstruct the f32 value block from the quant16 narrow-resident
    state: quantized rows decode as vmin + (q + 32768) * scale (bit-exact
    for rows the encoder marked ok — ops/narrow.py contract); raw-pool rows
    overlay their exact f32 values (pool pad rows carry row index S ->
    dropped)."""
    v = vmin[:, None] + (q.astype(jnp.float32) + 32768.0) * scale[:, None]
    return v.at[pool_rows].set(pool, mode="drop")


@jax.jit
def _decode_delta(dv, anchor, pool, pool_rows):
    """Reconstruct the f32 value block from the delta16/delta8 scalar state
    (ops/narrow.py build_narrow_delta): v = anchor + cumsum(dv), bit-exact
    for ok rows (integer deltas, |prefix| <= 2^23); raw-pool rows overlay
    their exact f32 values."""
    v = anchor[:, None] + jnp.cumsum(dv.astype(jnp.float32), axis=1)
    return v.at[pool_rows].set(pool, mode="drop")


def _derive_ts_impl(first, n, interval, C):
    """Reconstruct the i64 timestamp block of a grid-contiguous store from
    per-row first timestamps: ts[r, k] = first[r] + k * interval for k < n[r]
    (TS_PAD beyond, and everywhere for empty rows)."""
    col = jax.lax.broadcasted_iota(jnp.int64, (first.shape[0], C), 1)
    live = (col < n[:, None]) & (first[:, None] >= 0)
    return jnp.where(live, first[:, None] + col * interval, TS_PAD)


_derive_ts = jax.jit(_derive_ts_impl, static_argnums=(3,))


def _verify_ts_block_impl(ts, first, n, interval, C):
    """Fused derive-and-compare reduction over a ROW BLOCK — a whole-store
    comparison at 1M x 768 materializes multi-GB i64 hi/lo split temps and
    dies exactly when HBM is tight (the situation compression exists for)."""
    return jnp.all(ts == _derive_ts_impl(first, n, interval, C))


_verify_ts_block = jax.jit(_verify_ts_block_impl, static_argnums=(4,))

_VERIFY_BLOCK_ROWS = 1 << 16


def _verify_ts(ts, first, n, interval, C) -> bool:
    S = ts.shape[0]
    B = _VERIFY_BLOCK_ROWS
    if S <= B:
        return bool(_verify_ts_block(ts, first, n, interval, C))
    for i in range(0, S, B):
        j = min(i + B, S)
        if not bool(_verify_ts_block(ts[i:j], first[i:j], n[i:j],
                                     interval, C)):
            return False
    return True


@jax.jit
def _decode_hist(dd, first_d, pool, pool_rows):
    """Reconstruct the f32 [S, C, B] bucket block from the hist-resident
    state: v = cumsum_b(first_d + cumsum_c dd) (bit-exact for rows the
    encoder marked ok — ops/narrow.py build_narrow_hist contract); pool rows
    overlay their exact f32 blocks. Cells beyond a row's valid count extend
    the last frame constantly (the raw store holds zeros there) — every
    consumer masks by ``n``, same as the scalar decode's out-of-range cells."""
    d = first_d[:, None, :] + jnp.cumsum(dd.astype(jnp.float32), axis=1)
    v = jnp.cumsum(d, axis=2)
    return v.at[pool_rows].set(pool, mode="drop")


@jax.jit
def _decode_hist_rows(dd, first_d, pool, pool_slot, rid):
    """Decode ONLY the given store rows ([P] ids) of a hist-resident block —
    minority/pool fixes must not materialize the full [S, C, B] f32 block."""
    ddg = jnp.take(dd, rid, axis=0).astype(jnp.float32)
    d = jnp.take(first_d, rid, axis=0)[:, None, :] + jnp.cumsum(ddg, axis=1)
    v = jnp.cumsum(d, axis=2)
    slot = jnp.take(pool_slot, rid, mode="clip")
    pv = jnp.take(pool, jnp.maximum(slot, 0), axis=0, mode="clip")
    return jnp.where((slot >= 0)[:, None, None], pv, v)


@jax.jit
def _decode_narrow_rows(q, vmin, scale, pool, pool_slot, rid):
    """Decode ONLY the given store rows ([P] ids): quantized reconstruction
    with pool-value overlay — minority-cohort fixes must not materialize the
    full [S, C] block (several GB at 1M x 768) for a handful of rows."""
    qg = jnp.take(q, rid, axis=0)
    v = (jnp.take(vmin, rid)[:, None]
         + (qg.astype(jnp.float32) + 32768.0)
         * jnp.take(scale, rid)[:, None])
    slot = jnp.take(pool_slot, rid, mode="clip")
    pv = jnp.take(pool, jnp.maximum(slot, 0), axis=0, mode="clip")
    return jnp.where((slot >= 0)[:, None], pv, v)


@jax.jit
def _decode_delta_rows(dv, anchor, pool, pool_slot, rid):
    """Row-wise delta16/delta8 decode with pool-value overlay — the delta
    twin of :func:`_decode_narrow_rows`."""
    dvg = jnp.take(dv, rid, axis=0).astype(jnp.float32)
    v = jnp.take(anchor, rid)[:, None] + jnp.cumsum(dvg, axis=1)
    slot = jnp.take(pool_slot, rid, mode="clip")
    pv = jnp.take(pool, jnp.maximum(slot, 0), axis=0, mode="clip")
    return jnp.where((slot >= 0)[:, None], pv, v)


# row-wise derivation is the same rule applied to a gathered first/n pair
_derive_ts_rows = _derive_ts


class _Deferred:
    """Base for lazy views of elided store blocks: shape metadata for
    planning; ``materialize()`` reconstructs. General query paths funnel
    through query/exec._dval; the fused/grid paths never materialize."""

    __slots__ = ("_store", "_arr")
    ndim = 2

    def __init__(self, store: "SeriesStore"):
        self._store = store
        self._arr = None

    @property
    def shape(self):
        return (self._store.S, self._store.C)

    def materialize(self):
        if self._arr is None:
            self._arr = self._build()
        return self._arr

    def __getitem__(self, idx):
        return self.materialize()[idx]


class DeferredDecode(_Deferred):
    """Lazy f32 view of a narrow-resident store's value block."""

    dtype = np.dtype(np.float32)

    def _build(self):
        return self._store.value_block()

    def gather_rows(self, rid):
        """[P, C] f32 of the given rows only (row-wise decode; falls back to
        the materialized block if one already exists or the store changed
        residency since this view was handed out)."""
        st = self._store
        if self._arr is None and st._narrow is not None:
            kind, ops, pool, _pp, slot, _ok = st._narrow
            if kind == "quant16":
                return _decode_narrow_rows(*ops, pool, slot, rid)
            return _decode_delta_rows(*ops, pool, slot, rid)
        return jnp.take(self.materialize(), rid, axis=0)


class DeferredDecodeHist(_Deferred):
    """Lazy f32 view of a hist-resident store's [S, C, B] bucket block."""

    dtype = np.dtype(np.float32)
    ndim = 3

    @property
    def shape(self):
        return (self._store.S, self._store.C, self._store.nbuckets)

    def _build(self):
        return self._store.value_block()

    def gather_rows(self, rid):
        """[P, C, B] f32 of the given rows only (row-wise decode + pool
        overlay; falls back to a materialized block if one exists or the
        store changed residency since this view was handed out)."""
        st = self._store
        if self._arr is None and st._nhist is not None:
            dd, first_d, pool, _pp, slot, _ok = st._nhist
            return _decode_hist_rows(dd, first_d, pool, slot, rid)
        return jnp.take(self.materialize(), rid, axis=0)


class DeferredTs(_Deferred):
    """Lazy i64 view of an elided (grid-derived) timestamp block."""

    dtype = np.dtype(np.int64)

    def _build(self):
        return self._store.ts_block()

    def gather_rows(self, rid):
        """[P, C] i64 of the given rows only (row-wise derivation)."""
        st = self._store
        if self._arr is None and st._ts_elided:
            first_g = jnp.take(jnp.asarray(st.first_ts), rid)
            n_g = jnp.take(st.n, rid)
            return _derive_ts_rows(first_g, n_g, jnp.int64(st.grid_interval),
                                   st.C)
        return jnp.take(self.materialize(), rid, axis=0)


@dataclass
class SeriesStoreStats:
    samples_appended: int = 0
    out_of_order_dropped: int = 0
    capacity_dropped: int = 0
    compactions: int = 0
    frees: int = 0


class SeriesStore:
    """One shard's device store for a non-histogram schema value column."""

    def __init__(self, max_series: int, capacity: int, dtype=jnp.float32,
                 device=None, nbuckets: int = 0, layout=None,
                 default_col: str | None = None):
        """``layout`` (from Schema.col_layout) declares multi-value-column
        storage: the schema's DEFAULT column lives in ``self.val`` and every
        other data column gets its own named [S, C] array in ``self.extra``
        — one ts/n pair serves all columns (ref: multi-column datasets,
        Schemas.scala / filodb-defaults.conf:17-106; a column is selected at
        query time via __col__)."""
        # round the row dimension up to a fused-kernel-friendly shape (mult
        # of 8 up to 512, mult of 512 beyond): wide selections then always
        # qualify for the single-pass Pallas path, at a cost of <= 511 empty
        # rows; the logical slot budget stays config.max_series_per_shard
        m = 8 if max_series <= 512 else 512
        self.S = (max_series + m - 1) // m * m
        self.C = capacity
        self.dtype = dtype
        self.nbuckets = nbuckets   # 0 = scalar values; >0 = histogram [S, C, B]
        self.layout = layout       # [(name, offset, width, is_hist)] or None
        self.default_col = None
        # local_devices, not devices: under multi-host jax.distributed the
        # global list leads with rank 0's (non-addressable) device
        dev = device or jax.local_devices()[0]
        S = self.S
        vshape = (S, capacity) if not nbuckets else (S, capacity, nbuckets)
        self.ts = jax.device_put(jnp.full((S, capacity), TS_PAD, jnp.int64), dev)
        self.val = jax.device_put(jnp.zeros(vshape, dtype), dev)
        self.extra: dict[str, jax.Array] = {}
        if layout is not None:
            # default col = the schema's value_column (else the histogram
            # col / last col); every other column is a named scalar array
            hist = [nm for nm, _o, _w, ih in layout if ih]
            names = [nm for nm, _o, _w, _ih in layout]
            self.default_col = (default_col if default_col in names
                                else hist[0] if hist else layout[-1][0])
            for nm, _off, _w, is_h in layout:
                if nm != self.default_col:
                    assert not is_h, "only one histogram column per schema"
                    self.extra[nm] = jax.device_put(
                        jnp.zeros((S, capacity), dtype), dev)
        self.n = jax.device_put(jnp.zeros(S, jnp.int32), dev)
        # host mirrors: ingest-path bookkeeping without device->host syncs
        self.n_host = np.zeros(S, np.int32)
        self.last_ts = np.full(S, -(1 << 62), np.int64)
        self.first_ts = np.full(S, -1, np.int64)
        # scrape-grid tracking: when every series stays aligned to a common
        # (base, interval) grid with contiguous samples, queries take the MXU
        # band-matmul fast path (ops/gridfns.py) instead of per-row searches
        self.grid_base: int | None = None
        self.grid_interval: int | None = None
        self.grid_ok = True
        # start-cohort summary cache: recomputing per-row offsets per QUERY is
        # an O(S) host pass; starts only change on new series/compact/free
        self._cohorts = None
        # concurrency diagnostics: the shard attaches its lock so donating
        # mutations can assert the locking discipline; the detective records
        # donation provenance for use-after-donation reports
        self.owner_lock = None
        self.detective = diagnostics.DonationDetective()
        self.stats = SeriesStoreStats()
        # backpressure: device mutations are dispatched asynchronously; an
        # unthrottled ingest loop would queue scatters faster than the device
        # (or a tunneled link) retires them, building an unbounded backlog
        # that every query fetch then waits behind — and eventually blocking
        # the dispatcher itself INSIDE the shard lock. Callers drain via
        # throttle() after releasing the lock.
        self._appends_since_sync = 0
        self.max_inflight = 8
        # lazily-built u16 quantized mirror of the default value column
        # (ops/narrow.py); the query leaf consults it when enabled
        from ..ops.narrow import NarrowMirror
        self.narrow = NarrowMirror()
        # narrow-RESIDENT state (StoreConfig.narrow_resident /
        # compressed_residency): (kind, ops, pool, pp, slot, ok_host) where
        # kind names the decode variant (ops/decodereg.py: "quant16" |
        # "delta16" | "delta8") and ops its device operands ((q, vmin,
        # scale) or (dv, anchor)). When set, the narrow form IS the only
        # resident value copy — self.val is None and f32 views decode on
        # demand (see compress_resident)
        self._narrow = None
        # ok-contract fallback bookkeeping: when a flush WANTED compression
        # but every encoding failed the contract/cohort gate, the reason
        # ("resets" | "non-integer" | "range") lands here for the flush
        # path's filodb_store_residency_fallback counter — "compressed" and
        # "tried and fell back" must be distinguishable signals
        self.residency_decline: str | None = None
        # cohort-pool gate (StoreConfig.narrow_cohort_gate): the fraction of
        # live rows allowed to fail the ok-contract before raw f32 is the
        # cheaper residency
        self.cohort_gate = 0.25
        # histogram twin: (dd i8/i16 [S,C,B], first_d f32 [S,B], pool, pp,
        # slot, ok_host) — the 2D-delta form of the cumulative bucket block
        # (compressed_residency="all")
        self._nhist = None
        # grid-derived timestamp elision: ts[S, C] freed, derived from
        # (first_ts, n, grid_interval) on demand — the 8B/sample column is
        # redundant on a grid-contiguous store (compress_resident)
        self._ts_elided = False

    def _pre_donate(self, what: str) -> None:
        """Every buffer-donating mutation funnels through here: assert the
        locking discipline (diagnostics mode) and record provenance."""
        if self.owner_lock is not None:
            diagnostics.assert_owned(self.owner_lock, what)
        self.detective.record(what)

    # -- narrow-resident lifecycle ------------------------------------------
    #
    # Reference role: the read hot path of the reference keeps values ONLY in
    # compressed form (NibblePack/delta chunks) and decompresses on access
    # (memory/.../format/vectors/DoubleVector.scala:1-60, doc/compression.md)
    # — bytes-per-sample is the capacity lever. TPU analog: after a flush the
    # value column compresses to the narrowest decode variant that carries
    # it bit-exactly (ops/decodereg.py: delta8 anchor+i8 deltas, quant16
    # (q, vmin, scale), delta16) and the f32 array is FREED; rows that don't
    # round-trip bit-exactly keep their raw f32 in a small cohort pool.
    # Appends rehydrate (write buffers stay raw in the reference too); the
    # next flush re-compresses. Queries stream the narrow state in the fused
    # kernel, or decode a transient f32 for general paths.

    def mutation_epoch(self) -> tuple:
        """Changes whenever a donating mutation ran (append/compact/free) —
        the two-phase compression's staleness check."""
        s = self.stats
        return (s.samples_appended, s.compactions, s.frees)

    def _cohort_pool(self, bad: np.ndarray):
        """(pool, pp, slot) for the rows that don't round-trip bit-exactly:
        their raw f32 rows, the padded row-id vector (pads scatter-drop on
        decode), and the per-row pool slot (-1 = quantized) so row-wise
        decodes overlay pool values without touching the full block."""
        Rp = 1
        while Rp < len(bad):
            Rp *= 2
        pp = np.full(Rp, self.S, np.int32)
        pp[:len(bad)] = bad
        pool = jnp.take(self.val, jnp.asarray(np.minimum(pp, self.S - 1)),
                        axis=0)
        slot = np.full(self.S, -1, np.int32)
        slot[bad] = np.arange(len(bad), dtype=np.int32)
        return pool, jnp.asarray(pp), jnp.asarray(slot)

    def _bad_rows(self, ok_host: np.ndarray):
        """Live rows failing the bit-exactness contract, or None when they
        exceed the cohort gate (StoreConfig.narrow_cohort_gate, default 25%
        of live rows — raw f32 is then the cheaper residency)."""
        live = self.n_host > 0
        bad = np.nonzero(live & ~ok_host)[0].astype(np.int32)
        if len(bad) > self.cohort_gate * max(int(live.sum()), 1):
            return None
        return bad

    @staticmethod
    def _majority_reason(live_bad: np.ndarray,
                         reasons: list[tuple[str, np.ndarray]]) -> str:
        """Classify a residency decline: the first reason (in precedence
        order) that explains at least as many failing rows as any later
        one. ``reasons`` maps tag -> per-row failure mask."""
        counts = [(tag, int((live_bad & mask).sum())) for tag, mask in reasons]
        best = max(counts, key=lambda kv: kv[1])
        return best[0] if best[1] else counts[-1][0]

    def _prepare_scalar(self):
        """Narrow scalar residency, narrowest-first: delta8 (1B/sample),
        then quant16 (2B but keeps active-column slicing — see
        ops/decodereg.py full_columns), then delta16 (2B, full columns).
        Counter-shaped rows (large anchor, small integer increments) fail
        the quantized contract but carry exactly in the delta form."""
        from ..ops.narrow import (build_narrow, build_narrow_delta,
                                  cast_narrow_delta_i8)
        dv16, anchor, okd16, okd8, integral = build_narrow_delta(
            self.val, self.n)
        okd8_host = np.asarray(okd8)
        bad = self._bad_rows(okd8_host)
        if bad is not None:
            pool, pp, slot = self._cohort_pool(bad)
            dv8 = cast_narrow_delta_i8(dv16)   # donates/frees the i16 block
            return ("n", ("delta8", (dv8, anchor), pool, pp, slot, okd8_host))
        q, vmin, scale, okq = build_narrow(self.val, self.n)
        okq_host = np.asarray(okq)
        bad = self._bad_rows(okq_host)
        if bad is not None:
            pool, pp, slot = self._cohort_pool(bad)
            return ("n", ("quant16", (q, vmin, scale), pool, pp, slot,
                          okq_host))
        okd16_host = np.asarray(okd16)
        bad = self._bad_rows(okd16_host)
        if bad is not None:
            pool, pp, slot = self._cohort_pool(bad)
            return ("n", ("delta16", (dv16, anchor), pool, pp, slot,
                          okd16_host))
        # every encoding breached the cohort gate: classify for the flush
        # path's fallback counter (non-integer deltas vs integral-but-
        # out-of-range) — mostly continuous floats keep raw f32
        live_bad = (self.n_host > 0) & ~okq_host & ~okd16_host
        integral_host = np.asarray(integral)
        self.residency_decline = self._majority_reason(
            live_bad, [("non-integer", ~integral_host),
                       ("range", integral_host)])
        return None

    def _prepare_hist(self):
        """2D-delta residency for the [S, C, B] bucket block: the narrowest
        signed dtype (i8, then i16) whose bit-exact rows keep the cohort pool
        under the gate wins — quiet histograms' delta-of-deltas are near zero,
        so i8 usually carries them at a quarter of the raw f32 bytes."""
        from ..ops.narrow import build_narrow_hist, cast_narrow_hist_i8
        dd16, first_d, ok16, ok8, mono, exact = build_narrow_hist(
            self.val, self.n)
        ok8_host, ok16_host = np.asarray(ok8), np.asarray(ok16)
        bad8 = self._bad_rows(ok8_host)
        if bad8 is not None:
            dd, bad, ok_host = cast_narrow_hist_i8(dd16), bad8, ok8_host
        else:
            bad16 = self._bad_rows(ok16_host)
            if bad16 is None:
                # mostly inexact/bursty rows: keep raw f32, but say why —
                # counter resets (mono fail) vs non-integer round-trips vs
                # integral-but-out-of-range deltas
                mono_host, exact_host = np.asarray(mono), np.asarray(exact)
                live_bad = (self.n_host > 0) & ~ok16_host
                self.residency_decline = self._majority_reason(
                    live_bad, [("resets", ~mono_host),
                               ("non-integer", mono_host & ~exact_host),
                               ("range", mono_host & exact_host)])
                return None
            dd, bad, ok_host = dd16, bad16, ok16_host
        pool, pp, slot = self._cohort_pool(bad)
        return ("h", (dd, first_d, pool, pp, slot, ok_host))

    def compress_prepare(self, hist: bool = True):
        """Phase 1 (NO lock needed): stream the store into the compressed
        form — quantized scalar values / 2D-delta bucket blocks + cohort
        pool, and the ts-derivability verdict. Pure reads + host fetches; a
        concurrent donating mutation surfaces as RuntimeError (caller retries
        next flush). Returns None when the store/data doesn't qualify
        (multi-column, f64, mostly non-quantizable rows, or a histogram
        store with ``hist=False`` — the shard's residency-mode gate).
        ``residency_decline`` carries the ok-contract failure reason when
        the data itself (not eligibility) caused the None."""
        prep_val = None
        self.residency_decline = None
        if self._narrow is None and self._nhist is None:
            if self.dtype != jnp.float32 or self.val is None:
                return None
            if self.nbuckets:
                # histogram stores compress their DEFAULT [S, C, B] bucket
                # block — the dominant bytes; a multi-column store's named
                # scalar columns (prom-histogram's sum/count) stay raw
                if not hist:
                    return None
                prep_val = self._prepare_hist()
            elif self.layout is None:
                prep_val = self._prepare_scalar()
            else:
                return None   # multi-column scalar stores stay raw
            if prep_val is None:
                return None
        ts_ok = False
        if not self._ts_elided and self.ts is not None \
                and self.grid_info() is not None:
            # the grid invariant guarantees derivability; verify anyway —
            # a silently wrong timestamp block must be impossible
            ts_ok = bool(_verify_ts(self.ts, jnp.asarray(self.first_ts),
                                    self.n, jnp.int64(self.grid_interval),
                                    self.C))
        return (prep_val, ts_ok)

    def compress_commit(self, prep) -> None:
        """Phase 2 (under the shard lock): swap the compressed state in and
        free the raw blocks. Caller verified mutation_epoch() is unchanged."""
        prep_val, ts_ok = prep
        self._pre_donate("SeriesStore.compress_resident")
        if prep_val is not None:
            kind, data = prep_val
            if kind == "h":
                self._nhist = data
            else:
                self._narrow = data
            self.val = None    # the f32 block's HBM is released here
        if ts_ok and not self._ts_elided:
            self.ts = None     # the 8B/sample block's HBM released here
            self._ts_elided = True

    @property
    def _val_compressed(self) -> bool:
        return self._narrow is not None or self._nhist is not None

    def compress_resident(self, hist: bool = True) -> bool:
        """One-call form (caller holds the shard lock): adopt the
        compressed-resident state — i16 quantized rows (or i8/i16 2D-delta
        bucket blocks) + raw-f32 cohort pool as the only value copy,
        timestamps elided on grid-contiguous stores. Returns True when
        resident-narrow (already or newly)."""
        if self._val_compressed and (self._ts_elided
                                     or self.grid_info() is None):
            return True
        prep = self.compress_prepare(hist=hist)
        if prep is None:
            return self._val_compressed
        self.compress_commit(prep)
        return self._val_compressed or self._ts_elided

    def _rehydrate(self) -> None:
        """Restore the resident f32/i64 blocks (mutations write raw); the
        next compress_resident() re-adopts the compressed state."""
        if not self._val_compressed and not self._ts_elided:
            return
        self._pre_donate("SeriesStore.rehydrate")
        if self._narrow is not None:
            kind, ops, pool, pp, _slot, _ok = self._narrow
            dec = _decode_narrow if kind == "quant16" else _decode_delta
            self.val = dec(*ops, pool, pp)
            self._narrow = None
        elif self._nhist is not None:
            dd, first_d, pool, pp, _slot, _ok = self._nhist
            self.val = _decode_hist(dd, first_d, pool, pp)
            self._nhist = None
        if self._ts_elided:
            self.ts = _derive_ts(jnp.asarray(self.first_ts), self.n,
                                 jnp.int64(self.grid_interval), self.C)
            self._ts_elided = False

    def value_block(self):
        """f32 value block: the resident array, or a TRANSIENT decode of the
        narrow state (not retained — capacity stays at the compressed form +
        pool)."""
        if self._narrow is not None:
            kind, ops, pool, pp, _slot, _ok = self._narrow
            dec = _decode_narrow if kind == "quant16" else _decode_delta
            return dec(*ops, pool, pp)
        if self._nhist is not None:
            dd, first_d, pool, pp, _slot, _ok = self._nhist
            return _decode_hist(dd, first_d, pool, pp)
        return self.val

    def ts_block(self):
        """i64 timestamp block: resident, or a TRANSIENT grid derivation."""
        if not self._ts_elided:
            return self.ts
        return _derive_ts(jnp.asarray(self.first_ts), self.n,
                          jnp.int64(self.grid_interval), self.C)

    def narrow_operands(self):
        """(kind, operands, ok_host) when narrow-resident, else None — the
        fused kernel's direct-stream form: ``kind`` names the decode variant
        (ops/decodereg.py) and ``operands = (block, *row_operands)`` its
        device arrays ((q, vmin, scale) or (dv, anchor))."""
        if self._narrow is None:
            return None
        kind, ops, _pool, _pp, _slot, ok = self._narrow
        return kind, ops, ok

    def hist_operands(self):
        """(dd, first_d, ok_host) when hist-resident, else None — the narrow
        hist grid kernels' direct-stream operands (ops/gridfns.py *_narrow)."""
        if self._nhist is None:
            return None
        dd, first_d, _pool, _pp, _slot, ok = self._nhist
        return dd, first_d, ok

    @property
    def is_narrow_resident(self) -> bool:
        return self._val_compressed or self._ts_elided

    def resident_value_bytes(self) -> int:
        """Resident HBM bytes of the value state (capacity accounting)."""
        if self._narrow is not None:
            _kind, ops, pool, _pp, _slot, _ok = self._narrow
            return (sum(o.size * o.dtype.itemsize for o in ops)
                    + pool.size * 4)
        if self._nhist is not None:
            dd, first_d, pool, _pp, _slot, _ok = self._nhist
            return (dd.size * dd.dtype.itemsize + first_d.size * 4
                    + pool.size * 4)
        v = self.val
        return 0 if v is None else v.size * v.dtype.itemsize

    def resident_sample_bytes(self) -> int:
        """Total resident HBM of the (ts + value) sample state — the
        retention-per-HBM-byte accounting: ts elision + narrow values take a
        12B/sample f32 store to ~1-2B/sample (delta8 / quant16)."""
        t = 0 if self._ts_elided or self.ts is None \
            else self.ts.size * self.ts.dtype.itemsize
        return t + self.resident_value_bytes()

    # -- ingest -------------------------------------------------------------

    def append(self, part_ids: np.ndarray, ts: np.ndarray, values: np.ndarray) -> int:
        """Batched append of samples (one flush group). Samples must be presented
        in ingest order; per-series out-of-order or over-capacity samples drop
        (reference behavior: TimeSeriesPartition drops out-of-order rows).
        Returns the number of samples actually written."""
        if len(part_ids) == 0:
            return 0
        part_ids = np.asarray(part_ids, np.int32)
        ts = np.asarray(ts, np.int64)
        # stable sort by series, then position within batch = running offset
        order = np.argsort(part_ids, kind="stable")
        r = part_ids[order]
        t = ts[order]
        v = np.asarray(values)[order]
        # out-of-order detection: a sample must exceed both the stored last_ts and
        # the running max of earlier in-batch samples of its series (fast path when
        # nothing violates — the common time-ordered-stream case)
        prev_t = np.concatenate([[0], t[:-1]])
        same_series = np.concatenate([[False], np.diff(r) == 0])
        viol = (t <= self.last_ts[r]) | (same_series & (t <= prev_t))
        keep = ~viol
        if viol.any():
            # slow path: exact per-series running-max filter, only for violators
            for s in np.unique(r[viol]):
                mask = r == s
                tt = t[mask]
                run = self.last_ts[s]
                kk = np.empty(len(tt), bool)
                for i, x in enumerate(tt):
                    kk[i] = x > run
                    if kk[i]:
                        run = x
                keep[mask] = kk
            self.stats.out_of_order_dropped += int((~keep).sum())
            r, t, v = r[keep], t[keep], v[keep]
        # running occurrence index within the (filtered) sorted batch -> dense cols
        boundaries = np.concatenate([[0], np.nonzero(np.diff(r))[0] + 1])
        occ = np.arange(len(r)) - np.repeat(
            boundaries, np.diff(np.concatenate([boundaries, [len(r)]])))
        cols = self.n_host[r] + occ
        over = cols >= self.C
        if over.any():
            self.stats.capacity_dropped += int(over.sum())
            r, t, v, cols = r[~over], t[~over], v[~over], cols[~over]
        m = len(r)
        if m == 0:
            return 0
        self._rehydrate()      # mutations write the raw f32 block
        self._pre_donate("SeriesStore.append")
        # host bookkeeping
        uniq, first_pos = np.unique(r, return_index=True)
        newly = uniq[self.n_host[uniq] == 0]
        self.first_ts[newly] = t[first_pos[self.n_host[uniq] == 0]]
        if len(newly):
            self._cohorts = None   # new starts can change the cohort summary
        self._track_grid(r, t, uniq, first_pos)
        np.maximum.at(self.last_ts, r, t)
        counts = np.bincount(r, minlength=self.S).astype(np.int32)
        self.n_host += counts
        # pad to bucketed size; padded rows use row index S => dropped by scatter
        P = _pad_size(m)
        v = np.asarray(v)
        rp = np.full(P, self.S, np.int32); rp[:m] = r
        cp = np.zeros(P, np.int32); cp[:m] = cols
        tp = np.zeros(P, np.int64); tp[:m] = t
        if self.layout is None:
            vp = np.zeros((P,) + v.shape[1:], v.dtype); vp[:m] = v
            self.ts, self.val, self.n = _scatter_append(
                self.ts, self.val, self.n,
                jnp.asarray(rp), jnp.asarray(cp), jnp.asarray(tp),
                jnp.asarray(vp).astype(self.dtype), jnp.asarray(counts))
        else:
            # split the flat [m, W] ingest row by the schema layout: default
            # column (scalar or histogram span) + named scalar columns
            dv = None
            ev = {}
            for nm, off, w, _is_h in self.layout:
                colv = v[:, off] if w == 1 else v[:, off:off + w]
                if nm == self.default_col:
                    dv = colv
                else:
                    ev[nm] = colv
            vp = np.zeros((P,) + dv.shape[1:], dv.dtype); vp[:m] = dv
            evp = {}
            for k, a in ev.items():
                ap = np.zeros(P, a.dtype); ap[:m] = a
                evp[k] = jnp.asarray(ap).astype(self.dtype)
            self.ts, self.val, self.extra, self.n = _scatter_append_multi(
                self.ts, self.val, self.extra, self.n,
                jnp.asarray(rp), jnp.asarray(cp), jnp.asarray(tp),
                jnp.asarray(vp).astype(self.dtype), evp, jnp.asarray(counts))
        self.stats.samples_appended += m
        self._appends_since_sync += 1
        return m

    def throttle(self) -> None:
        """Bound the in-flight device mutations (call OUTSIDE the shard
        lock): after ``max_inflight`` un-synced appends, block until the
        LATEST scatter retires, so a hot ingest loop runs at the device's
        retirement rate instead of growing a backlog that starves concurrent
        query fetches. Blocks on the current ``n`` output (a queued older
        handle would already be donated/deleted by a newer append); if a
        concurrent append donates it mid-wait, retry on the replacement."""
        if self._appends_since_sync <= self.max_inflight:
            return
        for _ in range(4):
            arr = self.n
            try:
                arr.block_until_ready()
                break
            except Exception:
                if arr is self.n:
                    raise   # a REAL device failure, not a racing donation
                continue    # donated by a racing append: retry on the new n
        self._appends_since_sync = 0

    def _track_grid(self, r, t, uniq, first_pos) -> None:
        """Maintain the shard scrape-grid invariant on each append batch:
        common (base, interval), per-series contiguity, uniform start."""
        if not self.grid_ok:
            return
        if self.grid_base is None:
            self.grid_base = int(t[0])
        if self.grid_interval is None:
            same = np.concatenate([[False], np.diff(r) == 0])
            if same.any():
                i = int(np.argmax(same))
                self.grid_interval = int(t[i] - t[i - 1])
            else:
                existing = self.n_host[r] > 0
                if existing.any():
                    i = int(np.argmax(existing))
                    self.grid_interval = int(t[i] - self.last_ts[r[i]])
            if self.grid_interval is not None and self.grid_interval <= 0:
                self.grid_ok = False
            if self.grid_interval is None:
                return
            # interval just established: starts recorded before it was known
            # (earlier batches) must land on the grid too, else their offsets
            # in grid_offsets() would silently misalign
            live = self.n_host > 0
            if self.grid_ok and live.any():
                starts = self.first_ts[live]
                if (((starts - self.grid_base) % self.grid_interval) != 0).any():
                    self.grid_ok = False
                    return
        iv = self.grid_interval
        ok = ((t - self.grid_base) % iv == 0).all()
        # contiguity within the batch
        same = np.concatenate([[False], np.diff(r) == 0])
        if ok and same.any():
            ok = (np.diff(t)[same[1:]] == iv).all()
        # contiguity vs stored tail for series with history
        if ok:
            existing = self.n_host[uniq] > 0
            if existing.any():
                heads = t[first_pos[existing]]
                ok = (heads == self.last_ts[uniq[existing]] + iv).all()
        if not ok:
            self.grid_ok = False

    def grid_info(self):
        """(base_ts, interval_ms) when the shard stays on a common scrape grid
        (common interval, on-grid timestamps, per-series contiguity), else None.

        Series may START at different grid cells — churn (a new pod appearing
        mid-stream) no longer demotes the shard: per-series start cells come
        from :meth:`grid_offsets`, and the query layer runs the band-matmul
        path on the majority start cohort, correcting minority rows via the
        general kernels. Compaction shifts every row's offset uniformly, so
        the majority cohort survives it."""
        if not self.grid_ok or not self.grid_interval:
            return None
        if not (self.n_host > 0).any():
            return None
        return int(self.grid_base), int(self.grid_interval)

    def grid_offsets(self, rows: np.ndarray) -> np.ndarray:
        """Start cell of each given row (its first sample's grid cell index
        relative to ``grid_base``); 0 for empty rows."""
        first = self.first_ts[rows]
        return np.where(first >= 0,
                        (first - self.grid_base) // self.grid_interval,
                        0).astype(np.int64)

    def grid_cohorts(self):
        """Cached start-cohort summary over live rows: ``("uniform", off)``
        when every live series starts at the same grid cell (the overwhelmingly
        common shape — one scrape cohort), else ``("mixed", offsets[S])``.
        Invalidated whenever starts can move (new series, compaction, frees)."""
        if self._cohorts is None:
            live = self.n_host > 0
            if not live.any():
                self._cohorts = ("uniform", 0)
            else:
                offs = self.grid_offsets(np.arange(self.S))
                lv = offs[live]
                if (lv == lv[0]).all():
                    self._cohorts = ("uniform", int(lv[0]))
                else:
                    self._cohorts = ("mixed", offs)
        return self._cohorts

    def compact(self, cutoff_ts: int) -> None:
        """Evict samples older than ``cutoff_ts`` (amortized; ref: block reclaim
        by time bucket, BlockManager.scala markBucketedBlocksReclaimable)."""
        self._rehydrate()      # the shift gathers the raw f32 block
        self._pre_donate("SeriesStore.compact")
        if self.extra:
            self.ts, self.val, self.extra, self.n = _compact_multi(
                self.ts, self.val, self.extra, self.n, jnp.int64(cutoff_ts))
        else:
            self.ts, self.val, self.n = _compact(self.ts, self.val, self.n,
                                                 jnp.int64(cutoff_ts))
        self.n_host = np.array(self.n)  # fresh writable host copy
        new_first = np.array(self.ts[:, 0])
        self.first_ts = np.where(self.n_host > 0, new_first, -1)
        self._cohorts = None
        self.stats.compactions += 1

    def free_rows(self, part_ids: np.ndarray) -> None:
        """Release the rows of purged partitions so their slots can be reused
        (ref: TimeSeriesShard partition purge frees the partition's memory).
        Stale val cells stay in HBM but are masked by n=0; the ts rows are
        reset to padding so grid/first-ts scans never see them. Buffers are
        donated in-place — no transient second copy of the [S, C] arrays."""
        if len(part_ids) == 0:
            return
        self._rehydrate()      # the scatter resets the raw ts block
        self.stats.frees += 1
        self._pre_donate("SeriesStore.free_rows")
        m = len(part_ids)
        P = _pad_size(m)
        # padded entries use row S -> dropped by the out-of-bounds scatter mode
        pp = np.full(P, self.S, np.int32)
        pp[:m] = np.asarray(part_ids, np.int32)
        self.ts, self.n = _free_rows(self.ts, self.n, jnp.asarray(pp))
        self.n_host[part_ids] = 0
        self.first_ts[part_ids] = -1
        self.last_ts[part_ids] = -(1 << 62)
        self._cohorts = None

    # -- query access -------------------------------------------------------

    def arrays(self, column: str | None = None):
        """(ts[S,C], val, n[S]) device arrays for query kernels; ``column``
        selects a named value column of a multi-column store (None = the
        schema's default column). Compressed-resident stores return deferred
        views (the grid/fused paths plan from shape metadata and never
        materialize; general paths decode transients at exec._dval)."""
        ts = DeferredTs(self) if self._ts_elided else self.ts
        return ts, self.column_array(column), self.n

    def column_array(self, column: str | None = None):
        if column is None or column == self.default_col:
            if self._narrow is not None:
                # deferred view: the fused path streams the i16 state and
                # never decodes; general paths materialize a transient f32
                # at their single choke points (query/exec.py _dval)
                return DeferredDecode(self)
            if self._nhist is not None:
                return DeferredDecodeHist(self)
            return self.val
        if column in self.extra:
            return self.extra[column]
        raise KeyError(f"unknown value column {column!r}")

    def snapshot_arrays(self, column: str | None = None):
        """(ts, val) blocks materialized ONCE for per-series slicing loops —
        callers iterating many pids must use this instead of per-pid
        series_snapshot (which would re-decode a compressed-resident store's
        full block per series)."""
        v = self.column_array(column)
        if isinstance(v, _Deferred):
            v = v.materialize()
        return self.ts_block(), v

    def series_snapshot(self, part_id: int, column: str | None = None):
        """Host copy of one series (tests/debug; loops use snapshot_arrays)."""
        cnt = int(self.n_host[part_id])
        t, v = self.snapshot_arrays(column)
        return (np.asarray(t[part_id, :cnt]), np.asarray(v[part_id, :cnt]))
