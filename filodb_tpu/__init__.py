"""filodb_tpu — a TPU-native, Prometheus-compatible, in-memory time-series database.

A ground-up JAX/XLA re-design with the capabilities of FiloDB (reference:
filodb.coordinator / filodb.core / filodb.memory / filodb.query Scala modules):
columnar compressed storage, PromQL distributed query execution, sharded ingestion
with checkpointed recovery, durable persistence, downsampling, HTTP API.

See ARCHITECTURE.md for the design mapping.
"""

__version__ = "0.1.0"

# Epoch-millisecond timestamps are int64 end-to-end (device searchsorted included),
# so 64-bit mode is required. All library arrays specify dtypes explicitly; value
# columns stay f32 on device unless a store is configured for f64 parity runs.
import jax as _jax  # noqa: E402

_jax.config.update("jax_enable_x64", True)

