"""filodb_tpu — a TPU-native, Prometheus-compatible, in-memory time-series database.

A ground-up JAX/XLA re-design with the capabilities of FiloDB (reference:
filodb.coordinator / filodb.core / filodb.memory / filodb.query Scala modules):
columnar compressed storage, PromQL distributed query execution, sharded ingestion
with checkpointed recovery, durable persistence, downsampling, HTTP API.

See ARCHITECTURE.md for the design mapping.
"""

__version__ = "0.1.0"
