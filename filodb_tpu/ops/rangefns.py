"""Range functions (PeriodicSamplesMapper kernels): all series x all output steps
in one compiled program.

Reference semantics: query/.../exec/rangefn/RateFunctions.scala (Prometheus
extrapolatedRate, kept numerically consistent), AggrOverTimeFunctions.scala
(*_over_time incl. accurate stddev/stdvar), RangeFunction.scala:38-226 (chunked vs
sliding selection — here everything is one data-parallel path).

A window for output step t covers sample timestamps in (t - window, t] (left-open,
Prometheus range-vector semantics). Output is [P, T] float64 with NaN where the
function is undefined (missing samples); presenters drop NaN rows/steps.

Kernels are cached per (function, accum dtype); shapes recompile per (P, C, T)
bucket which the exec layer pads to stabilize.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import windows as W

NAN = jnp.nan

# functions needing counter-reset correction (ref: needsCounterCorrection)
COUNTER_FNS = {"rate", "increase", "irate"}

RANGE_FNS = [
    "rate", "increase", "delta", "irate", "idelta",
    "sum_over_time", "count_over_time", "avg_over_time", "min_over_time",
    "max_over_time", "stddev_over_time", "stdvar_over_time", "last_over_time",
    "changes", "resets", "deriv", "predict_linear", "quantile_over_time",
    "holt_winters", "last_sample",
]


def _extrapolated(out_ts, window_ms, first_t, first_v, last_t, last_v, cnt,
                  is_counter: bool, is_rate: bool, acc=jnp.float64):
    """Prometheus extrapolatedRate (ref RateFunctions.scala:37-80), vectorized.

    ``first_t``/``last_t`` are int64 epoch ms: all time arithmetic stays integer
    and only the (small) differences are cast to ``acc`` — mandatory for f32
    accumulation, where epoch-ms magnitudes lose whole-second precision.
    """
    win_start = out_ts[None, :] - window_ms
    win_end = out_ts[None, :]
    dur_start = (first_t - win_start).astype(acc) / 1000.0
    dur_end = (win_end - last_t).astype(acc) / 1000.0
    sampled = (last_t - first_t).astype(acc) / 1000.0
    avg_dur = sampled / (cnt - 1.0)
    delta = last_v - first_v
    if is_counter:
        dur_zero = jnp.where(delta > 0, sampled * (first_v / delta), jnp.inf)
        dur_start = jnp.where((delta > 0) & (first_v >= 0) & (dur_zero < dur_start),
                              dur_zero, dur_start)
    thresh = avg_dur * 1.1
    extrap = sampled
    extrap = extrap + jnp.where(dur_start < thresh, dur_start, avg_dur / 2)
    extrap = extrap + jnp.where(dur_end < thresh, dur_end, avg_dur / 2)
    scaled = delta * (extrap / sampled)
    if is_rate:
        scaled = scaled / ((win_end - win_start).astype(acc) / 1000.0)
    return jnp.where(cnt >= 2, scaled, NAN)


def _linreg_sums(ctx):
    """Window sums for linear regression over (t_rel_seconds, value)."""
    ts, valid, left, right = ctx["ts"], ctx["valid"], ctx["left"], ctx["right"]
    v = ctx["fval"]
    t_rel = jnp.where(valid, (ts - ctx["t0"]).astype(jnp.float64) / 1000.0, 0.0)
    p_t = W.prefix_sum(t_rel, valid)
    p_t2 = W.prefix_sum(t_rel * t_rel, valid)
    p_v = W.prefix_sum(v, valid)
    p_tv = W.prefix_sum(t_rel * v, valid)
    cnt = (right - left).astype(jnp.float64)
    s_t = W.window_sum(p_t, left, right)
    s_t2 = W.window_sum(p_t2, left, right)
    s_v = W.window_sum(p_v, left, right)
    s_tv = W.window_sum(p_tv, left, right)
    # slope/intercept of least squares fit v = a + b * t_rel
    denom = cnt * s_t2 - s_t * s_t
    slope = jnp.where(denom != 0, (cnt * s_tv - s_t * s_v) / denom, NAN)
    intercept = (s_v - slope * s_t) / cnt
    return cnt, slope, intercept


def _periodic(fn, ts, val, n, out_ts, window_ms, arg0, arg1, w_cap, acc):
    """Core dispatch; ``fn`` and ``w_cap`` are static."""
    valid = W.valid_mask(ts, n)
    left, right = W.window_edges(ts, out_ts, window_ms)
    cnt_i = right - left
    cnt = cnt_i.astype(acc)
    fval = jnp.where(valid, val, 0).astype(acc)
    ctx = dict(ts=ts, val=val, fval=fval, valid=valid, left=left, right=right,
               t0=out_ts[0] - window_ms)

    def first_last(values):
        f_v = W.take(values, left)
        l_v = W.take(values, right - 1)
        f_t = W.take(ts, left)          # int64: cast only differences downstream
        l_t = W.take(ts, right - 1)
        return f_t, f_v, l_t, l_v

    if fn in ("rate", "increase", "delta"):
        is_counter = fn != "delta"
        if is_counter:
            # window-relative correction: first sample stays raw; the last sample
            # carries only the resets *inside* the window (corr[last] - corr[first])
            corrected = W.counter_correct(val, valid, dtype=acc)
            corr = corrected - fval
            f_v = W.take(fval, left)
            l_v = W.take(fval, right - 1) + (W.take(corr, right - 1) - W.take(corr, left))
            f_t = W.take(ts, left)
            l_t = W.take(ts, right - 1)
        else:
            f_t, f_v, l_t, l_v = first_last(fval)
        return _extrapolated(out_ts, window_ms, f_t, f_v, l_t, l_v, cnt,
                             is_counter, fn == "rate", acc)

    if fn in ("irate", "idelta"):
        i2 = right - 1
        i1 = right - 2
        v2 = W.take(fval, i2)
        v1 = W.take(fval, i1)
        dt = (W.take(ts, i2) - W.take(ts, i1)).astype(acc)
        if fn == "irate":
            dv = jnp.where(v2 >= v1, v2 - v1, v2)  # reset => counter restarted
            res = dv / (dt / 1000.0)
        else:
            res = v2 - v1
        return jnp.where(cnt_i >= 2, res, NAN)

    if fn == "sum_over_time":
        s = W.window_sum(W.prefix_sum(fval, valid, dtype=acc), left, right)
        return jnp.where(cnt_i >= 1, s, NAN)

    if fn == "count_over_time":
        return jnp.where(cnt_i >= 1, cnt, NAN)

    if fn == "avg_over_time":
        s = W.window_sum(W.prefix_sum(fval, valid, dtype=acc), left, right)
        return jnp.where(cnt_i >= 1, s / cnt, NAN)

    if fn in ("min_over_time", "max_over_time"):
        op = "min" if fn == "min_over_time" else "max"
        r = W.window_minmax(fval, valid, left, right, op)
        return jnp.where(cnt_i >= 1, r, NAN)

    if fn in ("stddev_over_time", "stdvar_over_time"):
        # center per series first: variance is shift-invariant and centering kills
        # the E[x^2]-E[x]^2 cancellation (near-constant windows come out exactly 0)
        nvalid = jnp.maximum(valid.sum(axis=1), 1)
        row_mean = (jnp.where(valid, fval, 0).sum(axis=1) / nvalid)[:, None]
        cv = jnp.where(valid, fval - row_mean, 0.0)
        s = W.window_sum(W.prefix_sum(cv, valid, dtype=acc), left, right)
        s2 = W.window_sum(W.prefix_sum(cv * cv, valid, dtype=acc), left, right)
        mean = s / cnt
        var = jnp.maximum(s2 / cnt - mean * mean, 0.0)
        var = jnp.where(cnt_i <= 1, 0.0, var)  # one sample: exactly zero spread
        r = var if fn == "stdvar_over_time" else jnp.sqrt(var)
        return jnp.where(cnt_i >= 1, r, NAN)

    if fn in ("last_over_time", "last_sample"):
        l_v = W.take(fval, right - 1)
        l_t = W.take(ts, right - 1)
        # last_sample additionally enforces staleness: arg0 = stale_ms
        if fn == "last_sample":
            fresh = (out_ts[None, :] - l_t) <= arg0
            return jnp.where((cnt_i >= 1) & fresh, l_v, NAN)
        return jnp.where(cnt_i >= 1, l_v, NAN)

    if fn in ("changes", "resets"):
        prev = jnp.concatenate([fval[:, :1], fval[:, :-1]], axis=1)
        pair_ok = valid & jnp.concatenate(
            [jnp.zeros_like(valid[:, :1]), valid[:, :-1]], axis=1)
        if fn == "changes":
            ind = pair_ok & (fval != prev)
        else:
            ind = pair_ok & (fval < prev)
        pfx = W.prefix_sum(ind.astype(acc), jnp.ones_like(valid), dtype=acc)
        c = W.take(pfx, right) - W.take(pfx, jnp.minimum(left + 1, right))
        return jnp.where(cnt_i >= 1, c, NAN)

    if fn == "deriv":
        cnt_r, slope, _ = _linreg_sums(ctx)
        return jnp.where(cnt_r >= 2, slope, NAN)

    if fn == "predict_linear":
        cnt_r, slope, intercept = _linreg_sums(ctx)
        # intercept is at t_rel = 0 (t0); predict at out_ts + arg0 seconds
        t_pred = (out_ts[None, :] - ctx["t0"]).astype(jnp.float64) / 1000.0 + arg0
        return jnp.where(cnt_r >= 2, intercept + slope * t_pred, NAN)

    if fn == "quantile_over_time":
        vals, mask = W.gather_windows(ts, fval, valid, left, right, w_cap)
        # NaN-fill then sort: NaNs sort to the end
        svals = jnp.sort(vals, axis=2)
        k = mask.sum(axis=2).astype(jnp.float64)
        rank = arg0 * (k - 1.0)
        lo = jnp.clip(jnp.floor(rank).astype(jnp.int32), 0, w_cap - 1)
        hi = jnp.clip(lo + 1, 0, w_cap - 1)
        frac = rank - lo
        v_lo = jnp.take_along_axis(svals, lo[:, :, None], axis=2)[:, :, 0]
        v_hi = jnp.take_along_axis(svals, hi[:, :, None], axis=2)[:, :, 0]
        v_hi = jnp.where(hi[:, :].astype(jnp.float64) > (k - 1), v_lo, v_hi)
        r = v_lo + (v_hi - v_lo) * frac
        return jnp.where(cnt_i >= 1, r, NAN)

    if fn == "holt_winters":
        # double exponential smoothing (ref HoltWinters in RangeFunction.scala;
        # Prometheus holt_winters): level/trend scan over the window samples
        vals, mask = W.gather_windows(ts, fval, valid, left, right, w_cap, fill=0.0)
        sf, tf = arg0, arg1
        v0 = vals[:, :, 0]
        v1 = jnp.where(mask[:, :, 1], vals[:, :, 1], v0)

        def body(carry, xm):
            s, b = carry
            x, m = xm
            s_new = sf * x + (1 - sf) * (s + b)
            b_new = tf * (s_new - s) + (1 - tf) * b
            s2 = jnp.where(m, s_new, s)
            b2 = jnp.where(m, b_new, b)
            return (s2, b2), None

        # Prometheus: s = x0, b = x1 - x0, then smooth over samples 1..n-1
        init = (v0, v1 - v0)
        xs = (jnp.moveaxis(vals[:, :, 1:], 2, 0), jnp.moveaxis(mask[:, :, 1:], 2, 0))
        (s_fin, _), _ = jax.lax.scan(body, init, xs)
        return jnp.where(cnt_i >= 2, s_fin, NAN)

    raise ValueError(f"unknown range function {fn}")  # pragma: no cover


def _kernel(fn: str, w_cap: int, acc_name: str, shape_key: tuple):
    """The per-shape compiled program via the explicit plan cache (query/
    plancache.py): the key carries the padded row/step buckets the exec
    layer already stabilizes, so repeated dashboard shapes hit a cached
    executable and the cache's capacity bound actually bounds retained
    programs (functools.cache + jax's internal cache bounded neither)."""
    from ..query.plancache import plan_cache
    acc = jnp.dtype(acc_name)
    return plan_cache.program(
        "periodic", (fn, w_cap, acc_name) + shape_key,
        lambda: functools.partial(_periodic, fn, w_cap=w_cap, acc=acc))


HIST_FNS = {"rate", "increase", "delta", "sum_over_time", "last_sample",
            "last_over_time"}


def periodic_samples_hist(ts, val, n, out_ts, window_ms, fn: str,
                          arg0: float = 0.0, w_cap: int = 256,
                          accum: str = "float64"):
    """General (off-grid) histogram range functions: val [S, C, B] cumulative
    bucket counts -> [S, T, B], any timestamp layout.

    Buckets share their series' timestamps, so the scalar kernel is vmapped
    over the bucket axis — the searchsorted window edges depend only on the
    (unbatched) timestamps and are computed once, while per-bucket counter
    correction and extrapolation batch across B (ref: HistogramVector read
    through chunked range functions, RateFunctions.scala applied per bucket).
    """
    assert fn in HIST_FNS, f"{fn} not supported on histograms"
    from ..query.plancache import plan_cache
    S, C, B = val.shape
    acc = jnp.dtype(accum)

    def build():
        body = functools.partial(_periodic, fn, w_cap=w_cap, acc=acc)

        def hist(ts, val, n, out_ts, window_ms, arg0, arg1):
            def one_bucket(vb):
                return body(ts, vb, n, out_ts, window_ms, arg0, arg1)
            return jnp.moveaxis(jax.vmap(one_bucket, in_axes=2)(val), 0, 2)
        return hist

    k = plan_cache.program(
        "periodic-hist",
        (fn, w_cap, accum, S, C, B, len(out_ts), str(val.dtype)), build)
    return k(ts, val, n, jnp.asarray(out_ts), jnp.int64(window_ms),
             jnp.float64(arg0), jnp.float64(0.0))


def periodic_samples(ts, val, n, out_ts, window_ms, fn: str,
                     arg0: float = 0.0, arg1: float = 0.0, w_cap: int = 256,
                     accum: str = "float64"):
    """Evaluate range function ``fn`` for every series row at every output step.

    ts/val/n: store arrays (already gathered to the selected rows) — see windows.py.
    out_ts: int64 [T] output step timestamps. window_ms: range window (for
    ``last_sample`` pass the staleness lookback as both window and arg0).
    Returns float64 [P, T] with NaN for undefined points.
    """
    S, C = val.shape
    k = _kernel(fn, w_cap, accum, (S, C, len(out_ts), str(val.dtype)))
    return k(ts, val, n, jnp.asarray(out_ts),
             jnp.int64(window_ms), jnp.float64(arg0),
             jnp.float64(arg1))
