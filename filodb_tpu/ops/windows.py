"""Window-edge machinery for range functions — the TPU replacement for the
reference's per-row window iterators.

Reference: query/.../exec/PeriodicSamplesMapper.scala (ChunkedWindowIterator walks
chunks sample-by-sample per window; SlidingWindowIterator keeps an add/remove
queue). On TPU the same computation is data-parallel: for S series and T output
steps we locate all S*T window edges with a vmapped binary search (O(log C) each),
then answer window reductions from precomputed prefix sums (sum/count/stddev/
regression) or two-level block aggregates (min/max) — no per-sample iteration.

Conventions:
  - ``ts``  int64 [P, C] sorted per row, padded with TS_PAD (greater than any real ts)
  - ``val`` float  [P, C] value column; entries beyond the row's count are garbage
    and must be masked via ``valid``
  - a window for output step t covers sample timestamps in [t - window_ms, t]
    (closed range — Prometheus 2.x era semantics, matching the reference)
  - ``left``/``right`` [P, T] index the half-open sample range [left, right)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.chunkstore import TS_PAD  # noqa: F401  (re-exported for kernels)


def valid_mask(ts, n):
    """[P, C] bool: which sample slots hold real data."""
    C = ts.shape[1]
    return jnp.arange(C)[None, :] < n[:, None]


def window_edges(ts, out_ts, window_ms):
    """Return (left, right) [P, T] half-open sample index ranges per output step."""
    def row_edges(row):
        right = jnp.searchsorted(row, out_ts, side="right")
        left = jnp.searchsorted(row, out_ts - window_ms, side="left")
        return left, right
    left, right = jax.vmap(row_edges)(ts)
    return left, right


def take(arr, idx):
    """Gather arr[p, idx[p, t]] -> [P, T] (idx clipped to valid range)."""
    return jnp.take_along_axis(arr, jnp.clip(idx, 0, arr.shape[1] - 1), axis=1)


def prefix_sum(x, valid, dtype=jnp.float64):
    """Exclusive prefix sums: out[:, j] = sum(x[:, :j]); shape [P, C+1]."""
    xz = jnp.where(valid, x, 0).astype(dtype)
    cs = jnp.cumsum(xz, axis=1)
    zero = jnp.zeros((x.shape[0], 1), dtype)
    return jnp.concatenate([zero, cs], axis=1)


def window_sum(pfx, left, right):
    """Sum over [left, right) from an exclusive prefix-sum table."""
    return take(pfx, right) - take(pfx, left)


def counter_correct(val, valid, dtype=jnp.float64):
    """Apply cumulative counter-reset correction along the time axis.

    Reference: chunk drop metadata on ChunkSetInfo + CounterVectorReader
    (DoubleVector.scala) feed corrections into rate; here the correction prefix
    is recomputed on device: corr[j] = sum of drops (prev - cur when cur < prev)
    up to j, so corrected values are monotonic and window deltas are exact.
    """
    v = jnp.where(valid, val, 0).astype(dtype)
    prev = jnp.concatenate([v[:, :1], v[:, :-1]], axis=1)
    pair_valid = valid & jnp.concatenate([jnp.zeros_like(valid[:, :1]), valid[:, :-1]], axis=1)
    drop = jnp.where(pair_valid, jnp.maximum(prev - v, 0), 0)
    return v + jnp.cumsum(drop, axis=1)


# ---- two-level block aggregates for min/max ---------------------------------

def block_agg(val, valid, block: int, op: str):
    """Per-block aggregates [P, C // block] (C must be a multiple of block)."""
    P, C = val.shape
    nb = C // block
    neutral = jnp.inf if op == "min" else -jnp.inf
    v = jnp.where(valid, val, neutral).reshape(P, nb, block)
    return (jnp.min if op == "min" else jnp.max)(v, axis=2)


def window_minmax(val, valid, left, right, op: str, block: int = 32):
    """Min/max over [left, right) via edge gathers + full-block reduce.

    Work per output step is 2*block + C/block elements — O(sqrt C)-ish instead of
    O(window) — and every access is a static-shape gather XLA can fuse.
    """
    P, C = val.shape
    if C % block:
        # pad to a block multiple with invalid cells (neutral under the
        # reduce); windows never index past the caller's right <= C, so the
        # tail contributes nothing (non-pow2 capacities: downsample-family
        # stores sized to their bucket count)
        pad = (-C) % block
        val = jnp.pad(val, ((0, 0), (0, pad)))
        valid = jnp.pad(valid, ((0, 0), (0, pad)))
        C += pad
    nb = C // block
    neutral = jnp.inf if op == "min" else -jnp.inf
    red = jnp.minimum if op == "min" else jnp.maximum
    blocks = block_agg(val, valid, block, op)            # [P, NB]

    lb = -(-left // block)      # first full block  = ceil(l / B)
    rb = right // block         # end of full blocks = floor(r / B)

    # full blocks in [lb, rb)
    bidx = jnp.arange(nb)[None, None, :]                          # [1, 1, NB]
    bmask = (bidx >= lb[:, :, None]) & (bidx < rb[:, :, None])
    full = jnp.where(bmask, blocks[:, None, :], neutral)
    acc = (jnp.min if op == "min" else jnp.max)(full, axis=2)      # [P, T]

    vv = jnp.where(valid, val, neutral)
    off = jnp.arange(block)[None, None, :]                         # [1, 1, B]

    # left partial edge: [l, min(lb*B, r))
    le_end = jnp.minimum(lb * block, right)
    li = left[:, :, None] + off
    lmask = li < le_end[:, :, None]
    lgather = _gather3(vv, li, C)
    lpart = (jnp.min if op == "min" else jnp.max)(jnp.where(lmask, lgather, neutral), axis=2)

    # right partial edge: [max(rb*B, l), r)
    re_start = jnp.maximum(rb * block, left)
    ri = re_start[:, :, None] + off
    rmask = ri < right[:, :, None]
    rgather = _gather3(vv, ri, C)
    rpart = (jnp.min if op == "min" else jnp.max)(jnp.where(rmask, rgather, neutral), axis=2)

    return red(red(acc, lpart), rpart)


def _gather3(vv, idx, C):
    """vv [P, C], idx [P, T, B] -> [P, T, B]."""
    P, T, B = idx.shape
    flat = jnp.clip(idx, 0, C - 1).reshape(P, T * B)
    return jnp.take_along_axis(vv, flat, axis=1).reshape(P, T, B)


def gather_windows(ts, val, valid, left, right, w_cap: int, fill=jnp.nan):
    """Materialize up to ``w_cap`` window samples per step: values [P, T, W] with
    ``fill`` beyond the window. Used by order-statistics / sequential functions
    (quantile_over_time, holt_winters) where no prefix structure applies."""
    P, C = val.shape
    off = jnp.arange(w_cap)[None, None, :]
    idx = left[:, :, None] + off
    mask = idx < right[:, :, None]
    vals = _gather3(jnp.where(valid, val, fill), idx, C)
    return jnp.where(mask, vals, fill), mask
