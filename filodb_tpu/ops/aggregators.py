"""Cross-series aggregation: the map/reduce over [P, T] result matrices.

Reference: query/.../exec/AggrOverRangeVectors.scala (RowAggregator framework:
Sum/Min/Max/Count/Avg/Stddev/Stdvar/TopK/BottomK/CountValues/Quantile with
map -> reduce -> present phases, plus the row-major ``fastReduce`` path).

TPU-native shape: grouping labels are resolved host-side to dense group ids [P];
the reduce is one ``segment_sum``-family call over the series axis — the same
O(P*T) data-parallel pass regardless of group count. Across shards the partial
[G, T] matrices reduce further via ``psum`` on the mesh (parallel/).

NaN convention: NaN marks a missing sample; aggregates exclude NaN and emit NaN
for groups with no present samples at a step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

BASIC_OPS = ("sum", "min", "max", "avg", "count", "stddev", "stdvar", "group")


@functools.partial(jax.jit, static_argnums=(0, 3))
def segment_aggregate(op: str, values, group_ids, num_groups: int):
    """values [P, T] f64 (NaN=missing), group_ids int32 [P] -> [G, T].

    For avg/stddev/stdvar returns the *present* final value; for mesh-distributed
    reduces use ``partial_aggregate``/``combine_partials`` instead so partial sums
    survive the cross-shard psum.
    """
    parts = partial_aggregate(op, values, group_ids, num_groups)
    return present_partials(op, parts)


MATMUL_GROUP_LIMIT = 64   # one-hot [G, S] matmul reduce up to this many groups


def partial_aggregate(op: str, values, group_ids, num_groups: int,
                      stable: bool = False):
    """Map phase: per-group partial state tensors, each [G, T] (ref: RowAggregator
    .map/.reduceAggregate). Partials are psum/min/max-combinable across shards.

    TPU note: scatter-based ``segment_sum`` is ~50x slower than a matmul reduce
    on TPU, so for small group counts (the common dashboard shape: sum()/by(dc))
    sums ride an MXU one-hot matmul [G, S] @ [S, T]; large-G reduces keep
    segment_sum.

    ``stable=True`` forces the segment_sum reduce for every group count: the
    scatter-add folds rows in ROW ORDER, each output column independently, so
    the result is invariant under the padded-T step bucket AND under row
    padding (padded/excluded rows contribute exact 0.0) — the bit-stability
    the composed two-step path and the mesh reduction schedule require. The
    one-hot matmul's contraction order is tiling-dependent (it may
    reassociate with T), which is exactly the PR 13 fold-order caveat.
    """
    present = ~jnp.isnan(values)
    zeroed = jnp.where(present, values, 0.0)
    acc = values.dtype if values.dtype in (jnp.float32, jnp.float64) else jnp.float64

    if not stable and num_groups <= MATMUL_GROUP_LIMIT:
        onehot = (group_ids[None, :] == jnp.arange(num_groups, dtype=group_ids.dtype)[:, None]
                  ).astype(acc)                                   # [G, S]
        def gsum(x):
            return onehot @ x
    else:
        def gsum(x):
            return jax.ops.segment_sum(x, group_ids, num_groups)

    cnt = gsum(present.astype(acc))
    if op in ("count", "group"):
        return {"count": cnt}
    if op == "sum":
        return {"sum": gsum(zeroed), "count": cnt}
    if op == "min":
        v = jnp.where(present, values, jnp.inf)
        return {"min": jax.ops.segment_min(v, group_ids, num_groups), "count": cnt}
    if op == "max":
        v = jnp.where(present, values, -jnp.inf)
        return {"max": jax.ops.segment_max(v, group_ids, num_groups), "count": cnt}
    if op == "avg":
        return {"sum": gsum(zeroed), "count": cnt}
    if op in ("stddev", "stdvar"):
        return {"sum": gsum(zeroed), "sumsq": gsum(zeroed * zeroed), "count": cnt}
    raise ValueError(f"not a basic segment op: {op}")


def resolve_partials(parts):
    """Normalize a partials carrier: a lazily-fetched device bundle (e.g.
    fusedgrid.PaddedPartials) resolves to its host dict here — at present/
    merge time, outside any shard lock."""
    return parts.resolve() if hasattr(parts, "resolve") else parts


def _xp_of(*dicts):
    """numpy for host partials, jnp for device partials. Partial state is
    tiny ([G, T]); once fetched to host, finishing in numpy avoids device
    round-trips (material on a tunneled link). Mixed inputs resolve to host."""
    vals = [v for d in dicts for v in d.values()]
    if vals and all(isinstance(v, jax.Array) for v in vals):
        return jnp
    return np


def combine_partials(op: str, a, b) -> dict:
    """Reduce phase across shards (host or psum path)."""
    a, b = resolve_partials(a), resolve_partials(b)
    xp = _xp_of(a, b)
    if xp is not jnp:
        a = jax.device_get(a)
        b = jax.device_get(b)
    out = {}
    for k in a:
        if k == "min":
            out[k] = xp.minimum(a[k], b[k])
        elif k == "max":
            out[k] = xp.maximum(a[k], b[k])
        else:
            out[k] = a[k] + b[k]
    return out


def present_partials(op: str, parts):
    """Present phase: partial state -> final [G, T] values (NaN where empty)."""
    parts = resolve_partials(parts)
    xp = _xp_of(parts)
    cnt = parts["count"]
    empty = cnt == 0
    cnt = xp.where(empty, 1.0, cnt)  # avoid 0/0 noise; result masked below
    if op == "count":
        return xp.where(empty, xp.nan, cnt)
    if op == "group":
        return xp.where(empty, xp.nan, 1.0)
    if op == "sum":
        return xp.where(empty, xp.nan, parts["sum"])
    if op == "min":
        return xp.where(empty, xp.nan, parts["min"])
    if op == "max":
        return xp.where(empty, xp.nan, parts["max"])
    if op == "avg":
        return xp.where(empty, xp.nan, parts["sum"] / cnt)
    if op in ("stddev", "stdvar"):
        mean = parts["sum"] / cnt
        import contextlib
        guard = (np.errstate(invalid="ignore", divide="ignore")
                 if xp is not jnp else contextlib.nullcontext())
        with guard:
            var = xp.maximum(parts["sumsq"] / cnt - mean * mean, 0.0)
            r = var if op == "stdvar" else xp.sqrt(var)
        return xp.where(empty, xp.nan, r)
    raise ValueError(op)


# ---- mergeable quantile sketch (ref: AggrOverRangeVectors quantile uses a
# t-digest; the TPU-native shape is a DDSketch-style log-bucketed histogram:
# fixed [G, B, T] count tensors that psum/merge exactly and bound the
# RELATIVE error of the presented quantile by (gamma-1)/(gamma+1)) ----------

SKETCH_GAMMA = 1.04            # rel. error (gamma-1)/(gamma+1) ~ 1.96%
SKETCH_MIN = 1e-12             # values below collapse into the zero bucket
SKETCH_BUCKETS = 2048          # per sign: covers 1e-12 .. ~7e22 at gamma=1.04
# layout: [0..B) negative buckets (mirrored, descending magnitude),
#         [B] zero, (B..2B] positive buckets
SKETCH_WIDTH = 2 * SKETCH_BUCKETS + 1


def quantile_sketch(values, group_ids, num_groups: int):
    """Map phase: [P, T] values -> [G, W, T] log-bucket counts (host numpy).

    Mergeable across shards by addition (or psum). NaN values are absent.
    """
    vals = np.asarray(values, np.float64)
    gids = np.asarray(group_ids)
    P, T = vals.shape
    B = SKETCH_BUCKETS
    lg = np.log(SKETCH_GAMMA)
    mag = np.abs(vals)
    with np.errstate(divide="ignore", invalid="ignore"):
        bi = np.ceil(np.log(mag / SKETCH_MIN) / lg)
        bi = np.nan_to_num(bi, nan=1.0, posinf=B - 1, neginf=1.0)
    # outermost slot of each sign is reserved for true +/-Inf samples
    bi = np.clip(bi, 1, B - 1).astype(np.int64)
    idx = np.where(mag <= SKETCH_MIN, B,
                   np.where(vals > 0, B + bi, B - bi))      # [P, T]
    idx = np.where(np.isposinf(vals), 2 * B, idx)
    idx = np.where(np.isneginf(vals), 0, idx)
    present = ~np.isnan(vals)
    counts = np.zeros((num_groups, SKETCH_WIDTH, T), np.float32)
    t_idx = np.broadcast_to(np.arange(T)[None, :], (P, T))
    g_idx = np.broadcast_to(gids[:, None], (P, T))
    np.add.at(counts, (g_idx[present], idx[present], t_idx[present]), 1.0)
    return counts


def present_quantile_sketch(counts, q: float):
    """[G, W, T] counts -> [G, T] phi-quantile estimates.

    PromQL semantics: rank = q*(n-1) with linear interpolation between the
    two straddling order statistics; each order statistic is located in the
    sketch and represented by its bucket's geometric midpoint, so the
    per-value relative error stays bounded by (gamma-1)/(gamma+1) ~ 1%."""
    G, W, T = counts.shape
    B = SKETCH_BUCKETS
    total = counts.sum(axis=1)                               # [G, T]
    rank = np.maximum(q, 0.0) * np.maximum(total - 1, 0)     # PromQL phi rank
    lo_r = np.floor(rank)
    frac = rank - lo_r
    cum = np.cumsum(counts, axis=1)
    # order statistic at 0-indexed rank r sits in the first bucket whose
    # cumulative count reaches r+1
    sel_lo = (cum < lo_r[:, None, :] + 1 - 1e-9).sum(axis=1)
    sel_hi = (cum < np.minimum(lo_r + 2, np.maximum(total, 1))[:, None, :]
              - 1e-9).sum(axis=1)
    sel_lo = np.clip(sel_lo, 0, W - 1)
    sel_hi = np.clip(sel_hi, 0, W - 1)
    # bucket -> representative value; outermost slots are true +/-Inf
    k = np.arange(W, dtype=np.float64)
    pos = k - B
    mags = SKETCH_MIN * np.power(SKETCH_GAMMA, np.abs(pos)) * 2 / (1 + SKETCH_GAMMA)
    rep = np.sign(pos) * mags
    rep[B] = 0.0
    rep[0] = -np.inf
    rep[W - 1] = np.inf
    lo_v, hi_v = rep[sel_lo], rep[sel_hi]
    with np.errstate(invalid="ignore"):
        interp = lo_v * (1 - frac) + hi_v * frac
    # integral ranks and equal straddles take the value directly — the
    # interpolation form would produce inf*0 = NaN for +/-Inf samples
    out = np.where((frac == 0) | (lo_v == hi_v), lo_v, interp)
    out = np.where(total > 0, out, np.nan)
    if q < 0:
        out = np.where(total > 0, -np.inf, np.nan)
    if q > 1:
        out = np.where(total > 0, np.inf, np.nan)
    return out


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def topk_mask(values, group_ids, num_groups: int, k: int, bottom: bool = False):
    """Per-step top-k filter: True where values[p, t] is among the k largest
    (smallest for bottomk) present values of its group at step t.

    Rank-within-group computed by counting, per element, how many group members
    beat it — O(P^2 T) pairwise within groups would be too big, so we instead
    compute per-element rank via sort: argsort per column with a composite key
    (group, -value) and positional counting.
    """
    P, T = values.shape
    neg = jnp.where(jnp.isnan(values), -jnp.inf if not bottom else jnp.inf, values)
    sortval = -neg if not bottom else neg
    # composite sort: primary group, secondary value
    order = jnp.lexsort((sortval, group_ids[:, None] * jnp.ones((1, T), jnp.int32)), axis=0)
    # rank within group: position since the group's first row in sorted order
    g_sorted = jnp.take_along_axis(group_ids[:, None] * jnp.ones((1, T), jnp.int32), order, axis=0)
    idx = jnp.arange(P)[:, None] * jnp.ones((1, T), jnp.int32)
    # first occurrence index of each group per column
    is_first = jnp.concatenate([jnp.ones((1, T), bool), g_sorted[1:] != g_sorted[:-1]], axis=0)
    first_pos = jnp.where(is_first, idx, 0)
    first_pos = jax.lax.associative_scan(jnp.maximum, first_pos, axis=0)
    rank_sorted = idx - first_pos
    # scatter ranks back to original row positions
    rank = _scatter_rows(rank_sorted, order, P)
    present = ~jnp.isnan(values)
    return (rank < k) & present


def _scatter_rows(src, order, P):
    """out[order[i, t], t] = src[i, t]."""
    T = src.shape[1]
    cols = jnp.broadcast_to(jnp.arange(T)[None, :], src.shape)
    out = jnp.zeros_like(src)
    return out.at[order.reshape(-1), cols.reshape(-1)].set(src.reshape(-1))


@functools.partial(jax.jit, static_argnums=(2,))
def group_quantile(values, group_ids, num_groups: int, q):
    """Cross-series quantile per group per step (ref: QuantileRowAggregator uses
    t-digest; we compute the exact quantile — a strictly better answer the TPU
    can afford because the whole matrix is resident).

    Sort rows by (group, value) per column, then linearly interpolate at rank
    q*(k-1) inside each group's contiguous run.
    """
    P, T = values.shape
    big = jnp.where(jnp.isnan(values), jnp.inf, values)
    gcol = group_ids[:, None] * jnp.ones((1, T), jnp.int32)
    order = jnp.lexsort((big, gcol), axis=0)
    v_sorted = jnp.take_along_axis(big, order, axis=0)
    present = ~jnp.isnan(values)
    cnt = jax.ops.segment_sum(present.astype(jnp.int32), group_ids, num_groups)  # [G, T]
    # start position of each group's run per column = cumulative counts of all rows
    # (incl. missing, which sort to +inf *within the group run*) — compute from
    # total group sizes instead
    gsize = jax.ops.segment_sum(jnp.ones_like(group_ids, jnp.int32), group_ids, num_groups)
    gstart = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(gsize)[:-1]])  # [G]
    rank = q * jnp.maximum(cnt.astype(jnp.float64) - 1.0, 0.0)                   # [G, T]
    lo = jnp.floor(rank).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, jnp.maximum(cnt - 1, 0))
    frac = rank - lo

    def take_rank(r):  # r: [G, T] rank within group -> gather from v_sorted
        pos = jnp.clip(gstart[:, None] + r, 0, P - 1)               # [G, T]
        return jnp.take_along_axis(v_sorted, pos, axis=0)

    v_lo = take_rank(lo)
    v_hi = take_rank(hi)
    res = v_lo + (v_hi - v_lo) * frac
    return jnp.where(cnt == 0, jnp.nan, res)
