"""Narrow (compressed) on-device value mirror for the fused query path.

Reference role: the read hot path of the reference decompresses NibblePack/
delta-encoded chunks ON ACCESS (memory/.../format/NibblePack.scala:12-37,
format/vectors/DoubleVector.scala, doc/compression.md) — bytes-per-sample is
its main lever against memory bandwidth. The TPU analog here: a u16
quantized mirror of the f32 store, built in ONE device pass and decoded in
VMEM inside the fused Pallas kernel, halving the HBM bytes the north-star
query streams.

Losslessness contract: per row, scale is the largest power of two with
(vmax - vmin) / scale < 65536; a row is marked ``ok`` only when EVERY valid
cell round-trips bit-exactly (min + q * scale == v in f32). Integer-valued
counters/gauges (the common Prometheus shape: request counts, bytes, 10ms
timings) qualify; arbitrary continuous floats do not and take the raw-f32
path — rows that fail are excluded from the narrow kernel (n forced to 0)
and folded in via the general kernels, exactly like minority grid cohorts.

The mirror is rebuilt lazily per store mutation epoch: serving workloads
flush every few seconds but answer many queries per second, so one extra
streaming pass per flush buys half the bytes on every query between
flushes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=())
def build_narrow(val, n):
    """One streaming pass: (q i16[S,C], vmin f32[S], scale f32[S], ok bool[S]).

    scale is the SMALLEST power of two with (vmax - vmin) / scale <= 65535
    (maximal precision within the u16 range; power of two => exact f32
    multiplication); ok rows round-trip bit-exactly. Rows with < 1 valid
    sample are ok with scale 1 (all cells masked anyway)."""
    S, C = val.shape
    col = jax.lax.broadcasted_iota(jnp.int32, (S, C), 1)
    valid = col < n[:, None]
    big = jnp.float32(3.4e38)
    v = val.astype(jnp.float32)
    vmin = jnp.min(jnp.where(valid, v, big), axis=1)
    vmax = jnp.max(jnp.where(valid, v, -big), axis=1)
    empty = ~valid[:, 0]
    vmin = jnp.where(empty, 0.0, vmin)
    vmax = jnp.where(empty, 0.0, vmax)
    span = vmax - vmin
    # smallest power-of-two scale with span/scale <= 65535:
    # scale = 2^ceil(log2(span/65535)); span 0 -> scale 1
    exp = jnp.ceil(jnp.log2(jnp.maximum(span, 1e-37) / 65535.0))
    scale = jnp.exp2(jnp.maximum(exp, -126.0)).astype(jnp.float32)
    scale = jnp.where(span > 0, scale, 1.0)
    d = v - vmin[:, None]
    q = jnp.clip(jnp.round(d / scale[:, None]), 0, 65535)
    recon = vmin[:, None] + q * scale[:, None]
    exact = jnp.where(valid, recon == v, True)
    ok = jnp.all(exact, axis=1)
    # stored biased as int16 (q - 32768): Mosaic casts i16->f32 directly and
    # fast, while u16 needs a slow i32 hop (measured 2.6x slower)
    return (q - 32768.0).astype(jnp.int16), vmin, scale, ok


# ---- histogram stores -------------------------------------------------------
#
# Device analog of the wire codec's 2D-delta (memory/hist.py, ref
# doc/compression.md "Histograms"): buckets are cumulative, so the bucket-axis
# delta d[s,c,:] is small and non-negative, and the time-axis delta of THOSE
# (dd) is near zero for quiet series. The resident form keeps dd as i8/i16
# [S, C, B] plus each row's first-frame bucket deltas f32 [S, B]; the f32
# block reconstructs as v = cumsum_b(first_d + cumsum_c dd). Every reduction
# over the time axis the grid kernels need commutes with the bucket cumsum,
# so queries can matmul the narrow dd block directly (ops/gridfns.py
# *_narrow) — the whole-store f32 temp never exists.
#
# Losslessness contract (same as the scalar form): a row is ``ok`` only when
# every valid cell round-trips bit-exactly in f32 — integer-valued bucket
# counts below 2^24 qualify; rows that don't keep raw f32 in the cohort pool.

@jax.jit
def build_narrow_hist(val, n):
    """One streaming pass over a [S, C, B] cumulative-bucket block:
    (dd i16[S, C, B], first_d f32[S, B], ok16 bool[S], ok8 bool[S],
    mono bool[S], exact bool[S]).

    ``mono``/``exact`` report the monotonicity and round-trip legs of the
    contract separately so a declining store can say WHY (counter resets
    vs non-integer data vs out-of-range deltas — the residency-fallback
    metric's reason tag). ``okN`` marks rows that BOTH round-trip
    bit-exactly, stay MONOTONE over
    time, and whose dd fits the N-bit signed range; the caller picks the
    narrowest dtype whose pool stays under the cohort gate. Monotonicity is
    part of the contract because the raw rate/increase kernels clamp negative
    per-step increments (counter-reset correction) — a nonlinear step the
    narrow kernels' telescoped matmuls cannot reproduce, so a row with a
    reset must take the cohort pool and the raw path. dd is zero at cell 0
    (the first frame lives in ``first_d``) and beyond each row's valid
    count, so decodes extend the last frame constantly — consumers mask by
    ``n`` exactly like the raw store's kernels do."""
    col = jax.lax.broadcasted_iota(jnp.int32, val.shape[:2], 1)
    valid = col < n[:, None]
    v = jnp.where(valid[:, :, None], val.astype(jnp.float32), 0.0)
    d = jnp.diff(v, axis=2, prepend=0.0)           # bucket deltas [S, C, B]
    first_d = d[:, 0, :]
    dd = jnp.diff(d, axis=1, prepend=0.0)          # 2D delta along time
    pair = (valid & (col > 0))[:, :, None]
    dd = jnp.where(pair, dd, 0.0)
    # bit-exact round trip: integer components stay exact through both
    # cumsums as long as every partial sum is f32-representable
    v_rec = jnp.cumsum(first_d[:, None, :] + jnp.cumsum(dd, axis=1), axis=2)
    exact = jnp.where(valid[:, :, None], v_rec == v, True)
    # counter-reset detection: any negative per-step bucket increment
    # (inc = cumsum_b dd) disqualifies the row — see contract above
    inc = jnp.cumsum(dd, axis=2)
    mono_row = jnp.all(jnp.all(jnp.where(pair, inc >= 0.0, True),
                               axis=2), axis=1)
    exact_row = jnp.all(jnp.all(exact, axis=2), axis=1)
    ok_rt = exact_row & mono_row
    fit16 = jnp.all(jnp.all((dd >= -32768.0) & (dd <= 32767.0), axis=2), axis=1)
    fit8 = jnp.all(jnp.all((dd >= -128.0) & (dd <= 127.0), axis=2), axis=1)
    return (dd.astype(jnp.int16), first_d, ok_rt & fit16, ok_rt & fit8,
            mono_row, exact_row)


@jax.jit
def cast_narrow_hist_i8(dd16):
    """i16 -> i8 narrowing for stores whose ok rows all fit 8 bits (pool rows
    may wrap — their dd is never read; decodes overlay the pool row-wise)."""
    return dd16.astype(jnp.int8)


# ---- scalar delta (counter/gauge) form --------------------------------------
#
# Device analog of the wire codec's delta-delta/NibblePack framing
# (memory/deltadelta.py, ref doc/compression.md): a monotone counter's raw
# values are huge (1e9-class) but its per-step increments are tiny, so the
# quantized form above fails its bit-exact contract — span/65535 rounds the
# low bits away. The delta form stores each row as a f32 ANCHOR (first valid
# value) plus i16/i8 per-step value deltas; the fused kernels reconstruct
# v = anchor + cumsum(dv) in VMEM per tile. Unlike the hist form there is NO
# monotonicity requirement: the decode is the full exact value sequence, so
# the rate kernels' counter-reset clamp applies to the same numbers it would
# see raw.

@functools.partial(jax.jit, donate_argnums=())
def build_narrow_delta(val, n):
    """One streaming pass: (dv i16[S,C], anchor f32[S], ok16, ok8, integral).

    anchor is each row's first valid value; dv[s,0] = 0 and dv is zero beyond
    the valid count, so ``anchor + cumsum(dv)`` extends the last frame
    constantly (consumers mask by ``n``). ``okN`` marks rows that round-trip
    bit-exactly through the f32 cumsum AND whose every prefix stays within
    2^23 of the anchor (so per-tile reassociation of the cumsum cannot change
    the result) AND whose deltas fit the N-bit signed range. ``integral``
    reports whether the row's deltas were integer-valued at all — callers use
    it to classify declines (non-integer data vs out-of-range)."""
    S, C = val.shape
    col = jax.lax.broadcasted_iota(jnp.int32, (S, C), 1)
    valid = col < n[:, None]
    v = val.astype(jnp.float32)
    anchor = jnp.where(valid[:, 0], v[:, 0], 0.0)
    d = jnp.diff(v, axis=1, prepend=0.0)
    pair = valid & (col > 0)
    dvq = jnp.where(pair, jnp.round(d), 0.0)
    integral = jnp.all(jnp.where(pair, d == dvq, True), axis=1)
    # bit-exact round trip through the SAME reduction the kernels run
    prefix = jnp.cumsum(dvq, axis=1)
    recon = anchor[:, None] + prefix
    exact = jnp.where(valid, recon == v, True)
    # reassociation safety: tiles decode cumsum locally then offset by the
    # previous tile's total; every partial sum must be integer-exact in f32,
    # which |prefix| <= 2^23 guarantees for integer deltas
    bound = jnp.all(jnp.where(valid, jnp.abs(prefix) <= 8388608.0, True), axis=1)
    ok_rt = integral & jnp.all(exact, axis=1) & bound
    fit16 = jnp.all((dvq >= -32768.0) & (dvq <= 32767.0), axis=1)
    fit8 = jnp.all((dvq >= -128.0) & (dvq <= 127.0), axis=1)
    return dvq.astype(jnp.int16), anchor, ok_rt & fit16, ok_rt & fit8, integral


@functools.lru_cache(1)
def _cast_delta_i8_call():
    # donation declared only where XLA honors it (the CPU backend warns and
    # ignores it — same gate as parallel/distributed._donate_argnums)
    donate = () if jax.default_backend() == "cpu" else (0,)
    return jax.jit(lambda dv16: dv16.astype(jnp.int8), donate_argnums=donate)


def cast_narrow_delta_i8(dv16):
    """i16 -> i8 narrowing when every ok row fits 8 bits; donates (frees) the
    i16 intermediate — flush-path encode never holds both widths."""
    return _cast_delta_i8_call()(dv16)


class NarrowMirror:
    """Narrow mirror of a SeriesStore's value column, refreshed at FLUSH
    time (outside the shard lock — the build streams the whole store and
    fetches the per-row ok flags, which must never block queries/ingest
    waiting on the lock) and only CONSULTED by the query leaf."""

    def __init__(self):
        self._epoch = -1
        self._data = None

    @staticmethod
    def _store_epoch(store) -> int:
        return (store.stats.samples_appended
                + store.stats.compactions * 1_000_003)

    def refresh(self, store) -> None:
        """(Re)build if the store mutated since the last build. Call OUTSIDE
        the shard lock (flush-time); one streaming pass + one host fetch."""
        if store.dtype != jnp.float32 or store.val.ndim != 2:
            return
        epoch = self._store_epoch(store)
        if self._data is None or self._epoch != epoch:
            q, vmin, scale, ok = build_narrow(store.val, store.n)
            import numpy as np
            self._data = (q, vmin, scale, np.asarray(ok))
            self._epoch = epoch

    def get(self, store):
        """(q, vmin, scale, ok_host) when a CURRENT mirror exists, else None
        — never builds (query leaves run under the shard lock)."""
        if self._data is None or self._epoch != self._store_epoch(store):
            return None
        return self._data
