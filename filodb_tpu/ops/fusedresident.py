"""Fused compressed-resident query kernels: the single-pass execution tier.

Reference role: the reference FiloDB's performance core is hand-rolled
columnar kernels (NibblePack, 2D-delta, XOR) that compute ON compressed data
in place — select, decode, window function, and aggregation run as one
iterator chain per chunk (PAPER.md §0; doc/compression.md). This module is
the TPU analog for the top query shapes: delta reconstruction, bucket-cumsum
commutation, the range function, and the segment reduce execute as ONE
device program per shape, with no intermediate f32 materialization of the
decoded store — per-tile state lives in registers/VMEM.

The registry below keys three fused shapes, each implemented TWICE from the
same tiling plan and selected at plan time by ``query.fused_kernels``:

  shape           query pattern                       tile math shared by
  --------------  ----------------------------------  --------------------
  rate_sum        sum/avg/...(rate|increase|delta)    fusedgrid.tile_contrib
  window_reduce   sum/...(avg_over_time|sum_over_time fusedgrid.tile_contrib
                  |count_over_time)
  hist_quantile   histogram_quantile(q, sum(fn(h[w])) hist_tile_contrib
                  over i8/i16 2D-delta-resident blocks  (this module)

Backends per shape:
  * ``pallas`` — a Pallas kernel streaming [Sb, ...] row tiles; on CPU it
    runs under ``pl.pallas_call(..., interpret=True)`` so tier-1 exercises
    the real kernel body, and the compiled Mosaic path lights up on TPU.
  * ``xla`` — an XLA-fused fallback built from the SAME tiling plan: one
    ``lax.scan`` walks the identical tiles through the identical tile math
    (variant parity by construction). This is also the portable path for
    backends without Pallas.
  * ``off`` — the composed two-step chain (grid kernel + segment reduce
    with the intermediate [S, T(,B)] matrix), the A/B baseline.

Both variants of a shape are DISTINCT kernel variants in the process-global
compiled-plan cache (query/plancache.py): the variant name is part of the
key, so switching modes never aliases programs and warmup covers whichever
variant will serve.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.metrics import (FILODB_QUERY_FUSED_FALLBACK,
                             FILODB_QUERY_FUSED_SERVED, registry)
from . import decodereg, fusedgrid, gridfns

MODES = ("off", "xla", "pallas")

# process-global execution mode, like the plan cache and the tracer: every
# serving path (in-process exec, fused-hist engine route, mesh collectives,
# warmup) must agree on the variant or warm programs would miss at serve
# time. Set once at startup from ``query.fused_kernels`` (standalone.py);
# tests flip it under try/finally.
_mode: str = "pallas"

HIST_FUSED_FNS = frozenset({"rate", "increase", "delta"})
MAX_BUCKETS = 64    # [Sb, C, B] tile + [G, Tp*B] accumulators stay in VMEM

# the declarative registry: shape name -> (window fns, reduce ops) it serves.
# exec.py / engine.py consult it for plan-time eligibility; the bench suite
# and warmup iterate it so every shape is covered by measurement and
# pre-tracing alike.
FUSED_SHAPES = {
    "rate_sum": (frozenset(fusedgrid.FUSED_FNS),
                 frozenset(fusedgrid.FUSED_OPS)),
    "window_reduce": (frozenset(fusedgrid.FUSED_WINDOW_FNS),
                      frozenset(fusedgrid.FUSED_OPS)),
    "hist_quantile": (HIST_FUSED_FNS, frozenset({"sum"})),
}


def mode() -> str:
    """The active fused-kernel mode ("off" | "xla" | "pallas")."""
    return _mode


def set_mode(m: str) -> None:
    """Select the fused-kernel tier (config: ``query.fused_kernels``)."""
    global _mode
    if m not in MODES:
        raise ValueError(f"query.fused_kernels must be one of {MODES}, "
                         f"got {m!r}")
    _mode = m


def scalar_shape_of(fn: str) -> str | None:
    """Registry shape serving a scalar window fn, or None."""
    if fn in fusedgrid.FUSED_FNS:
        return "rate_sum"
    if fn in fusedgrid.FUSED_WINDOW_FNS:
        return "window_reduce"
    return None


def count_served(shape: str) -> None:
    registry.counter(FILODB_QUERY_FUSED_SERVED,
                     {"shape": shape, "mode": _mode}).increment()


def count_fallback(shape: str) -> None:
    """A query matched a fused shape but fell back to the composed path
    (shape gate, group cap, off-grid store, ...)."""
    registry.counter(FILODB_QUERY_FUSED_FALLBACK, {"shape": shape}).increment()


# ---------------------------------------------------------------------------
# scalar shapes (rate_sum / window_reduce): thin mode dispatch over the two
# backends that share ops/fusedgrid.tile_contrib and its tiling plan
# ---------------------------------------------------------------------------

def scalar_aggregate(op: str, fn: str, val, n, gids, num_groups: int,
                     out_ts: np.ndarray, window_ms: int, base_ts: int,
                     interval_ms: int, fetch: bool = True, narrow=None):
    """Mode-routed one-pass ``op(fn(metric[w]))`` partials (see
    fusedgrid.fused_grid_aggregate for operand contracts;
    ``narrow=(kind, operands)`` streams a registered narrow block —
    ops/decodereg.py — decoded in VMEM per tile). Caller checked
    eligibility and guarantees ``mode() != "off"``."""
    assert _mode != "off"
    out = fusedgrid.fused_grid_aggregate(
        op, fn, val, n, gids, num_groups, out_ts, window_ms, base_ts,
        interval_ms, fetch=fetch, narrow=narrow, variant=_mode)
    count_served(scalar_shape_of(fn) or "rate_sum")
    return out


# ---------------------------------------------------------------------------
# hist_quantile: fused histogram_quantile over i8/i16 2D-delta-resident
# [S, C, B] blocks — the narrow dd state streams through static matmuls and
# ONE bucket cumsum per tile; the decoded f32 store never exists
# ---------------------------------------------------------------------------

_roundup = fusedgrid._roundup


def hist_fusable(S: int, C: int, T: int, B: int, num_groups: int) -> bool:
    """Shape gate: per-tile operands + [G, Tp*B] accumulators stay in VMEM.
    Unlike the scalar tier there is no active-column slicing: the quantile's
    first-sample prefix bands need every column from cell 0."""
    return (C <= fusedgrid.MAX_CAPACITY
            and _roundup(max(T, 1), 128) * B <= fusedgrid.MAX_STEPS * 8
            and num_groups <= fusedgrid.MAX_GROUPS
            and 0 < B <= MAX_BUCKETS
            and (S % 512 == 0 or (S <= 512 and S % 8 == 0)))


def hist_tile_contrib(fn: str, window_ms: int, interval_ms: int, B: int,
                      ddf, first_d, n, band_open, prefix_lo, lo, hi, rel):
    """Shared per-tile math of the hist_quantile shape: the decoded 2D-delta
    tile ``ddf [Sb, Ca, B]`` (+ ``first_d [Sb, B]`` first-frame bucket
    deltas, ``n [Sb, 1]`` valid counts) -> ``(contrib, okf)`` both
    ``[Sb, Tp*B]`` flat in the aggregators layout (t*B + b). Both backends
    call this — the Pallas body on VMEM refs, the XLA twin inside its scan.

    The bucket-cumsum commutation (ops/gridfns.py narrow-hist notes): the
    window delta of cumulative buckets equals ``cumsum_b(dd @ band_open)``
    and the first-sample value ``F + cumsum_b(dd @ prefix_lo)`` — every
    reduction is LINEAR in the frames, so the per-tile matmuls read the
    NARROW dd encoding directly and the per-(series, step) extrapolation
    algebra is identical to _grid_hist_kernel_narrow elementwise."""
    f32 = jnp.float32
    Sb, Ca, _B = ddf.shape
    Tp = band_open.shape[1]
    flat = ddf.transpose(0, 2, 1).reshape(Sb * B, Ca)         # [Sb*B, Ca]
    delta = jnp.cumsum(
        jnp.dot(flat, band_open, preferred_element_type=f32)
        .reshape(Sb, B, Tp), axis=1)                          # [Sb, B, Tp]
    F = jnp.cumsum(first_d, axis=1)                           # [Sb, B]
    f_v = F[:, :, None] + jnp.cumsum(
        jnp.dot(flat, prefix_lo, preferred_element_type=f32)
        .reshape(Sb, B, Tp), axis=1)

    last_cell = n - 1                                         # [Sb, 1]
    f_idx = jnp.maximum(lo, 0)                                # [1, Tp]
    l_idx = jnp.minimum(hi, last_cell)                        # [Sb, Tp]
    cnt = jnp.maximum(l_idx - f_idx + 1, 0)
    cnt_f = cnt.astype(f32)
    relf = rel.astype(f32)
    f_rel = (f_idx * interval_ms).astype(f32)
    l_rel = (l_idx * interval_ms).astype(f32)
    dur_start = (f_rel - (relf - window_ms)) / 1000.0         # [Sb, Tp]
    dur_end = (relf - l_rel) / 1000.0
    sampled = (l_rel - f_rel) / 1000.0
    avg_dur = sampled / (cnt_f - 1.0)
    thresh = avg_dur * 1.1
    if fn != "delta":
        # per-bucket counter zero-clamp — same expressions as the composed
        # narrow kernel (_grid_hist_kernel_narrow), per tile
        dur_zero = jnp.where(delta > 0,
                             sampled[:, None, :] * (f_v / delta), jnp.inf)
        ds = jnp.broadcast_to(dur_start[:, None, :], delta.shape)
        ds = jnp.where((delta > 0) & (f_v >= 0) & (dur_zero < ds),
                       dur_zero, ds)
        extrap = sampled[:, None, :] \
            + jnp.where(ds < thresh[:, None, :], ds,
                        avg_dur[:, None, :] / 2) \
            + jnp.where(dur_end[:, None, :] < thresh[:, None, :],
                        dur_end[:, None, :], avg_dur[:, None, :] / 2)
        factor = extrap / sampled[:, None, :]
    else:
        extrap = sampled \
            + jnp.where(dur_start < thresh, dur_start, avg_dur / 2) \
            + jnp.where(dur_end < thresh, dur_end, avg_dur / 2)
        factor = (extrap / sampled)[:, None, :]
    scaled = delta * factor
    if fn == "rate":
        scaled = scaled * (1000.0 / window_ms)

    ok = cnt >= 2                                             # [Sb, Tp]
    contrib = jnp.where(ok[:, None, :], scaled, 0.0)          # [Sb, B, Tp]
    okb = jnp.broadcast_to(ok[:, None, :], contrib.shape).astype(f32)
    # aggregators layout: [G, T*B] with flat index t*B + b
    return (contrib.transpose(0, 2, 1).reshape(Sb, Tp * B),
            okb.transpose(0, 2, 1).reshape(Sb, Tp * B))


def _hist_fold(Sb: int, G: int, gid, contrib, okf):
    """Per-group fold of one tile's flat [Sb, Tp*B] contributions on the
    MXU — identical in both backends."""
    f32 = jnp.float32
    gcol = jax.lax.broadcasted_iota(jnp.int32, (Sb, G), 1)
    oh = (gcol == gid).astype(f32)
    dn = (((0,), (0,)), ((), ()))
    return (jax.lax.dot_general(oh, contrib, dn, preferred_element_type=f32),
            jax.lax.dot_general(oh, okf, dn, preferred_element_type=f32))


def _hist_kernel_body(fn: str, window_ms: int, interval_ms: int, Sb: int,
                      Ca: int, Tp: int, B: int, G: int,
                      dd_ref, fd_ref, n_ref, gid_ref, band_ref, plo_ref,
                      lo_ref, hi_ref, rel_ref, sum_ref, cnt_ref):
    i = pl.program_id(0)
    # i8/i16 decode in VMEM via the registered hist twin (ops/decodereg.py)
    ddf = decodereg.decode_hist(dd_ref[:], fd_ref[:])
    contrib, okf = hist_tile_contrib(fn, window_ms, interval_ms, B,
                                     ddf, fd_ref[:], n_ref[:], band_ref[:],
                                     plo_ref[:], lo_ref[:], hi_ref[:],
                                     rel_ref[:])
    psum, pcnt = _hist_fold(Sb, G, gid_ref[:], contrib, okf)

    @pl.when(i == 0)
    def _():
        sum_ref[:] = jnp.zeros_like(sum_ref)
        cnt_ref[:] = jnp.zeros_like(cnt_ref)

    sum_ref[:] += psum
    cnt_ref[:] += pcnt


@functools.lru_cache(maxsize=32)
def build_hist_pallas(fn: str, window_ms: int, interval_ms: int, S: int,
                      Sb: int, C: int, Tp: int, B: int, G: int,
                      interpret: bool):
    """The raw (traceable) fused hist-quantile map-phase pallas_call: grid
    over [Sb] row tiles of the [S, C, B] dd block, [G, Tp*B] partial-state
    accumulators resident in VMEM across the sequential grid. The compiled
    (non-interpret) path targets TPU with the lane-dim caveat documented in
    COMPONENTS.md (B rides the minor axis of the tile; pad B to the lane
    multiple on real hardware when Mosaic requires it)."""
    body = functools.partial(_hist_kernel_body, fn, window_ms, interval_ms,
                             Sb, C, Tp, B, G)
    acc = pl.BlockSpec((G, Tp * B), lambda i: (0, 0), memory_space=pltpu.VMEM)
    const = functools.partial(pl.BlockSpec, index_map=lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
    row = lambda shape: pl.BlockSpec(shape, lambda i: (i, 0),  # noqa: E731
                                     memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((Sb, C, B), lambda i: (i, 0, 0),
                     memory_space=pltpu.VMEM),                  # dd
        row((Sb, B)),                                           # first_d
        row((Sb, 1)), row((Sb, 1)),                             # n, gid
        const((C, Tp)), const((C, Tp)),                         # bands
        const((1, Tp)), const((1, Tp)), const((1, Tp)),         # lo, hi, rel
    ]
    return pl.pallas_call(
        body,
        grid=(S // Sb,),
        in_specs=in_specs,
        out_specs=(acc, acc),
        out_shape=tuple(jax.ShapeDtypeStruct((G, Tp * B), jnp.float32)
                        for _ in range(2)),
        interpret=interpret,
    )


def build_hist_xla_tiles(fn: str, window_ms: int, interval_ms: int, S: int,
                         Sb: int, C: int, Tp: int, B: int, G: int):
    """XLA-fused twin of :func:`build_hist_pallas` from the same tiling
    plan: lax.scan over the identical [Sb, C, B] tiles through the identical
    hist_tile_contrib + fold; intermediates bounded by one tile."""
    f32 = jnp.float32
    nt = S // Sb

    def call(dd, first_d, n2, g2, band, plo, lo, hi, rel):
        tiles = (dd.reshape(nt, Sb, C, B), first_d.reshape(nt, Sb, B),
                 n2.reshape(nt, Sb, 1), g2.reshape(nt, Sb, 1))

        def fold(carry, xs):
            dd_t, fd_t, n_t, g_t = xs
            contrib, okf = hist_tile_contrib(
                fn, window_ms, interval_ms, B,
                decodereg.decode_hist(dd_t, fd_t), fd_t, n_t,
                band, plo, lo, hi, rel)
            psum, pcnt = _hist_fold(Sb, G, g_t, contrib, okf)
            return (carry[0] + psum, carry[1] + pcnt), None

        init = (jnp.zeros((G, Tp * B), f32), jnp.zeros((G, Tp * B), f32))
        outs, _ = jax.lax.scan(fold, init, tiles)
        return outs

    return call


def _hist_operands(C: int, Tp: int, out_ts: np.ndarray, window_ms: int,
                   base_ts: int, interval_ms: int):
    """Host operand build for the hist tier: open band for window deltas,
    prefix band selecting v at the lo cells (cells [1..l0] — needs every
    column from 0, hence no active-column slicing here), padded edges."""
    T = len(out_ts)
    lo, hi = gridfns.grid_edges(out_ts, window_ms, base_ts, interval_ms)
    rel = out_ts - base_ts
    lo_p, hi_p, rel_p = fusedgrid.pad_edges(lo, hi, rel, window_ms, Tp)
    band = np.zeros((C, Tp), np.float32)
    band[:, :T] = gridfns.band_matrix(C, lo, hi, True, np.float32)
    l0 = np.maximum(lo, 0)
    plo = np.zeros((C, Tp), np.float32)
    plo[:, :T] = gridfns.band_matrix(C, np.zeros(T, np.int64),
                                     np.minimum(l0, C - 1), True, np.float32)
    return (band, plo, lo_p, hi_p, rel_p)


@functools.lru_cache(maxsize=32)
def _hist_device_operands(C: int, Tp: int, out_ts_key: bytes, window_ms: int,
                          base_ts: int, interval_ms: int):
    out_ts = np.frombuffer(out_ts_key, np.int64)
    return tuple(jnp.asarray(a) for a in _hist_operands(
        C, Tp, out_ts, window_ms, base_ts, interval_ms))


def _hist_map_program(variant: str, fn: str, window_ms: int, interval_ms: int,
                      S: int, Sb: int, C: int, Tp: int, B: int, G: int,
                      dd_dtype: str):
    """The cached map-phase program (variant is part of the key: the two
    backends are distinct compiled kernels). Wrapped so dtype casts and
    [S] -> [S, 1] reshapes ride the one dispatch."""
    from ..query.plancache import plan_cache

    def build():
        if variant == "xla":
            call = build_hist_xla_tiles(fn, window_ms, interval_ms,
                                        S, Sb, C, Tp, B, G)
        else:
            call = build_hist_pallas(fn, window_ms, interval_ms, S, Sb, C,
                                     Tp, B, G,
                                     jax.default_backend() != "tpu")

        def wrapped(dd, first_d, n, gids, band, plo, lo, hi, rel):
            return call(dd, first_d,
                        n.astype(jnp.int32).reshape(S, 1),
                        gids.astype(jnp.int32).reshape(S, 1),
                        band, plo, lo, hi, rel)
        return wrapped

    return plan_cache.program(
        "fusedres-hist",
        (variant, fn, window_ms, interval_ms, S, Sb, C, Tp, B, G, dd_dtype),
        build)


def _hist_finish_program(G: int, T: int, Tp: int, B: int, has_corr: bool,
                         nles: int):
    """The shared finish: slice the padded [G, Tp*B] partials to the true
    steps, fold the cohort-pool correction partials in, mask empty groups,
    and run the f64 Prometheus quantile — numerically identical to the
    composed narrow path's finish (same histogram_quantile program)."""
    from ..query.plancache import plan_cache

    def build():
        def fin(q, les, psum, pcnt, corr_sum, corr_cnt):
            ps = psum.reshape(G, Tp, B)[:, :T, :].reshape(G, T * B)
            pc = pcnt.reshape(G, Tp, B)[:, :T, :].reshape(G, T * B)
            if has_corr:
                ps = ps + corr_sum
                pc = pc + corr_cnt
            summed = jnp.where(pc == 0, jnp.nan, ps)
            return gridfns.histogram_quantile(q, les,
                                              summed.reshape(G, T, B))
        return fin

    return plan_cache.program("fusedres-hist-finish",
                              (G, T, Tp, B, has_corr, nles), build)


def fused_hist_quantile_resident(q: float, les, dd, first_d, n, gids,
                                 num_groups: int, out_ts: np.ndarray,
                                 window_ms: int, fn: str, base_ts: int,
                                 interval_ms: int, corr=None,
                                 variant: str | None = None):
    """histogram_quantile(q, sum by(...)(fn(m[w]))) over a hist-resident
    store, map phase per the active mode: per-bucket window deltas, group
    fold, and quantile with the [S, C, B] f32 decode never materialized.
    ``corr=(sum, cnt)`` carries cohort-pool rows' partials ([G, T*B], those
    rows' gids excluded here). Returns the [G, T] device array."""
    assert fn in HIST_FUSED_FNS
    S, C, B = dd.shape
    T = len(out_ts)
    G = _roundup(max(num_groups, 8), 8)
    assert hist_fusable(S, C, T, B, G), (S, C, T, B, G)
    Tp = _roundup(max(T, 1), 128)
    Sb = 512 if S % 512 == 0 else S
    variant = variant or _mode
    assert variant in ("xla", "pallas")

    band, plo, lo_d, hi_d, rel_d = _hist_device_operands(
        C, Tp, np.ascontiguousarray(np.asarray(out_ts, np.int64)).tobytes(),
        int(window_ms), int(base_ts), int(interval_ms))
    prog = _hist_map_program(variant, fn, int(window_ms), int(interval_ms),
                             S, Sb, C, Tp, B, G, str(dd.dtype))
    # x64 tracing injects i64 scalars Mosaic rejects (grid index maps); the
    # map phase is pure f32/i32 — trace it with x64 off, exactly like the
    # scalar fused tier. The f64 quantile finish traces under default x64.
    from ..utils import enable_x64
    with enable_x64(False):
        psum, pcnt = prog(dd, first_d, jnp.asarray(n), jnp.asarray(gids),
                          band, plo, lo_d, hi_d, rel_d)
    if corr is None:
        z = jnp.zeros((G, T * B), jnp.float32)
        corr_sum = corr_cnt = z
        has_corr = False
    else:
        corr_sum, corr_cnt = corr
        if corr_sum.shape[0] != G:
            # the engine builds corr partials at its pow2 group bucket,
            # which sits below this kernel's 8-aligned G for small group
            # counts — pad with empty groups (they are masked by pc == 0
            # and sliced off by the caller's [:num_groups_true])
            pad = ((0, G - corr_sum.shape[0]), (0, 0))
            corr_sum = jnp.pad(corr_sum, pad)
            corr_cnt = jnp.pad(corr_cnt, pad)
        has_corr = True
    fin = _hist_finish_program(G, T, Tp, B, has_corr, int(les.shape[0]))
    return fin(jnp.float64(q), jnp.asarray(les), psum, pcnt,
               corr_sum, corr_cnt)
