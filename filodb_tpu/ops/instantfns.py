"""Instant functions applied element-wise to [P, T] matrices.

Reference: query/.../exec/rangefn/InstantFunction.scala (abs..year; date functions
interpret the sample value as epoch *seconds*, matching Prometheus).
"""

from __future__ import annotations

import jax.numpy as jnp


def _civil_from_days(z):
    """days since epoch -> (year, month [1-12], day [1-31]); Howard Hinnant's
    civil_from_days algorithm in integer arithmetic (jit-friendly)."""
    z = z + 719468
    era = jnp.floor_divide(jnp.where(z >= 0, z, z - 146096), 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def _ymd(values):
    secs = values.astype(jnp.int64)
    days = jnp.floor_divide(secs, 86400)
    return _civil_from_days(days), secs


def days_in_month(y, m):
    feb = jnp.where((y % 4 == 0) & ((y % 100 != 0) | (y % 400 == 0)), 29, 28)
    lengths = jnp.array([31, 0, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31])
    return jnp.where(m == 2, feb, lengths[m - 1])


def apply(fn: str, values, args: tuple[float, ...] = ()):
    """values: [P, T] float64 (NaN = missing, propagates through every fn)."""
    nanmask = jnp.isnan(values)

    def keep_nan(r):
        return jnp.where(nanmask, jnp.nan, r.astype(jnp.float64))

    if fn == "abs":
        return jnp.abs(values)
    if fn == "ceil":
        return jnp.ceil(values)
    if fn == "floor":
        return jnp.floor(values)
    if fn == "exp":
        return jnp.exp(values)
    if fn == "ln":
        return jnp.log(values)
    if fn == "log10":
        return jnp.log10(values)
    if fn == "log2":
        return jnp.log2(values)
    if fn == "sqrt":
        return jnp.sqrt(values)
    if fn == "round":
        nearest = args[0] if args else 1.0
        # Prometheus: floor(v/nearest + 0.5) * nearest (round half up)
        return jnp.floor(values / nearest + 0.5) * nearest
    if fn == "clamp_max":
        return jnp.minimum(values, args[0])
    if fn == "clamp_min":
        return jnp.maximum(values, args[0])
    if fn in ("days_in_month", "day_of_month", "day_of_week", "hour", "minute",
              "month", "year"):
        vals = jnp.where(nanmask, 0.0, values)
        (y, m, d), secs = _ymd(vals)
        if fn == "year":
            return keep_nan(y)
        if fn == "month":
            return keep_nan(m)
        if fn == "day_of_month":
            return keep_nan(d)
        if fn == "day_of_week":
            days = jnp.floor_divide(secs, 86400)
            return keep_nan((days + 4) % 7)  # 1970-01-01 was a Thursday
        if fn == "hour":
            return keep_nan((secs % 86400) // 3600)
        if fn == "minute":
            return keep_nan((secs % 3600) // 60)
        if fn == "days_in_month":
            return keep_nan(days_in_month(y, m))
    raise ValueError(f"unknown instant function {fn}")
