"""Grid fast path: range functions as static band matmuls on the MXU.

Why: TPU microbenchmarks (scripts/profile_kernels.py) show per-row binary search
and data-dependent [S, T] gathers are 20-2000x slower than streaming compares and
matmuls. Prometheus-style series are scrape-interval regular, so the store tracks
a per-shard *grid* (base_ts, interval, uniform start): when every live series has
sample k at timestamp base + k*interval, window edges are closed-form grid
indices and window reductions become [S, C] x [C, T] matmuls with STATIC 0/1
band matrices — the MXU-shaped formulation:

  - count:            closed form from per-series sample count n
  - sum/avg:          val @ band
  - rate/increase/delta: per-cell increments inc[s,c] (elementwise; counter
    correction folds in as relu — a reset cell's corrected increment is 0), then
    window delta over (lo_t, hi_t] is ONE matmul inc @ band_open; first-sample
    values ride a static one-hot matmul
  - last_over_time/last_sample: static one-hot matmul + per-row tail value

Shards that drift off the grid (irregular intervals, mid-series gaps,
heterogeneous starts) fall back to the general path (ops/rangefns.py).
Mixed start cohorts are a known TODO: bucket rows by start cell and shift bands
per cohort. Semantics match the general kernels exactly on aligned data
(reference behavior: query/.../exec/rangefn/ + RateFunctions.scala).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

GRID_FNS = {"rate", "increase", "delta", "sum_over_time", "count_over_time",
            "avg_over_time", "last_sample", "last_over_time"}


def grid_edges(out_ts: np.ndarray, window_ms: int, base_ts: int, interval_ms: int):
    """Host-side closed-form window edges in grid cells: cells with timestamps
    in [t - window, t] are [lo_t, hi_t] inclusive (empty when hi < lo)."""
    lo = np.ceil((out_ts - window_ms - base_ts) / interval_ms).astype(np.int64)
    hi = np.floor((out_ts - base_ts) / interval_ms).astype(np.int64)
    return lo, hi


def band_matrix(C: int, lo: np.ndarray, hi: np.ndarray, open_left: bool,
                dtype=np.float32) -> np.ndarray:
    """Static [C, T] 0/1 band: cell c contributes to step t iff
    lo_t < c <= hi_t (open_left) or lo_t <= c <= hi_t."""
    c = np.arange(C)[:, None]
    lo_ = lo[None, :] + (1 if open_left else 0)
    return ((c >= lo_) & (c <= hi[None, :])).astype(dtype)


def onehot_matrix(C: int, pos: np.ndarray, dtype=np.float32) -> np.ndarray:
    """[C, T] one-hot of clipped positions per step."""
    m = np.zeros((C, len(pos)), dtype)
    m[np.clip(pos, 0, C - 1), np.arange(len(pos))] = 1
    return m


def _plan(kernel: str, key: tuple, build):
    """Compiled program via the explicit plan cache (query/plancache.py):
    every grid entry point below keys on (fn, padded shape, dtype) — the
    variant kernels (hist / narrow) ARE the residency axis of the key."""
    from ..query.plancache import plan_cache
    return plan_cache.program(kernel, key, build)


def _grid_kernel(fn, val, n, band, band_open, onehot_lo, onehot_hi, lo, hi,
                 rel_out, window_ms, interval_ms, stale_ms):
    """val [S, C]: sample k of each series at column k == grid cell k.

    All device-side time arithmetic is int32 *grid-relative* milliseconds
    (rel_out = out_ts - base_ts): no int64 emulation on TPU. The wrapper
    guarantees the relative range fits i32 (falls back to the general path
    otherwise).
    """
    S, C = val.shape
    acc = val.dtype
    valid = jnp.arange(C, dtype=jnp.int32)[None, :] < n[:, None]
    v = jnp.where(valid, val, 0).astype(acc)

    last_cell = n[:, None] - 1                                    # [S, 1] i32
    f_idx = jnp.maximum(lo, 0)[None, :]                           # [1, T] i32
    l_idx = jnp.minimum(hi[None, :], last_cell)
    cnt = jnp.maximum(l_idx - f_idx + 1, 0)
    cnt_f = cnt.astype(acc)

    if fn == "count_over_time":
        return jnp.where(cnt >= 1, cnt_f, jnp.nan)

    if fn in ("sum_over_time", "avg_over_time"):
        s = v @ band                                              # MXU
        if fn == "avg_over_time":
            s = s / cnt_f
        return jnp.where(cnt >= 1, s, jnp.nan)

    if fn in ("last_sample", "last_over_time"):
        static_v = v @ onehot_hi                                  # value at cell hi_t
        row_last = jnp.take_along_axis(
            v, jnp.clip(last_cell, 0, C - 1), axis=1)             # [S, 1]
        l_v = jnp.where(hi[None, :] <= last_cell, static_v, row_last)
        ok = cnt >= 1
        if fn == "last_sample":
            l_rel = l_idx * interval_ms                           # i32 [S, T]
            ok = ok & ((rel_out[None, :] - l_rel) <= stale_ms)
        return jnp.where(ok, l_v, jnp.nan)

    if fn in ("rate", "increase", "delta"):
        is_counter = fn != "delta"
        prev = jnp.concatenate([v[:, :1], v[:, :-1]], axis=1)
        pair = valid & jnp.concatenate([jnp.zeros_like(valid[:, :1]), valid[:, :-1]], 1)
        raw_inc = jnp.where(pair, v - prev, 0.0)
        # counter: corrected increment = relu(diff); a reset cell contributes 0
        inc = jnp.maximum(raw_inc, 0.0) if is_counter else raw_inc
        delta = inc @ band_open                                   # MXU, (lo_t, hi_t]
        f_v = v @ onehot_lo                                       # raw first value
        f_rel = f_idx * interval_ms                               # [1, T] i32
        l_rel = l_idx * interval_ms                               # [S, T] i32
        win_start = rel_out[None, :] - window_ms
        win_end = rel_out[None, :]
        dur_start = (f_rel - win_start).astype(acc) / 1000.0
        dur_end = (win_end - l_rel).astype(acc) / 1000.0
        sampled = (l_rel - f_rel).astype(acc) / 1000.0
        avg_dur = sampled / (cnt_f - 1.0)
        if is_counter:
            dur_zero = jnp.where(delta > 0, sampled * (f_v / delta), jnp.inf)
            dur_start = jnp.where((delta > 0) & (f_v >= 0) & (dur_zero < dur_start),
                                  dur_zero, dur_start)
        thresh = avg_dur * 1.1
        extrap = sampled
        extrap = extrap + jnp.where(dur_start < thresh, dur_start, avg_dur / 2)
        extrap = extrap + jnp.where(dur_end < thresh, dur_end, avg_dur / 2)
        scaled = delta * (extrap / sampled)
        if fn == "rate":
            scaled = scaled * (1000.0 / window_ms.astype(acc))
        return jnp.where(cnt >= 2, scaled, jnp.nan)

    raise ValueError(fn)  # pragma: no cover


def grid_operands(C: int, out_ts: np.ndarray, window_ms: int, fn: str,
                  base_ts: int, interval_ms: int, dtype=np.float32):
    """Device-resident static operands for _grid_kernel (bands, one-hots,
    edges), cached per query shape: rebuilding AND re-uploading four [C, T]
    matrices per query costs tens of ms over a tunneled device link —
    measured 91 ms/dispatch (f64) for a histogram query whose actual device
    work is sub-millisecond. Same rationale as fusedgrid._device_operands."""
    key = np.ascontiguousarray(np.asarray(out_ts, np.int64)).tobytes()
    dtype = np.dtype(dtype)
    # bound retained HBM: four [C, T] matrices per entry x 32 entries; large
    # shapes (long dashboards on f64 stores) stay transient as before the
    # cache existed (fusedgrid's cache is bounded the same way by its shape
    # gates)
    if 4 * C * len(out_ts) * dtype.itemsize > 16 << 20:
        return _grid_operands_build(C, key, int(window_ms), int(base_ts),
                                    int(interval_ms), dtype.str)
    return _grid_operands_cached(C, key, int(window_ms), int(base_ts),
                                 int(interval_ms), dtype.str)


@functools.lru_cache(maxsize=32)
def _grid_operands_cached(C: int, out_ts_key: bytes, window_ms: int,
                          base_ts: int, interval_ms: int, dtype_str: str):
    return _grid_operands_build(C, out_ts_key, window_ms, base_ts,
                                interval_ms, dtype_str)


def _grid_operands_build(C: int, out_ts_key: bytes, window_ms: int,
                         base_ts: int, interval_ms: int, dtype_str: str):
    out_ts = np.frombuffer(out_ts_key, np.int64)
    dtype = np.dtype(dtype_str)
    lo, hi = grid_edges(out_ts, window_ms, base_ts, interval_ms)
    rel = out_ts - base_ts
    assert abs(rel).max() < 2**31 and window_ms < 2**31, "grid range exceeds i32"
    return dict(
        band=jnp.asarray(band_matrix(C, lo, hi, False, dtype)),
        band_open=jnp.asarray(band_matrix(C, lo, hi, True, dtype)),
        onehot_lo=jnp.asarray(onehot_matrix(C, np.maximum(lo, 0), dtype)),
        onehot_hi=jnp.asarray(onehot_matrix(C, hi, dtype)),
        lo=jnp.asarray(lo.astype(np.int32)), hi=jnp.asarray(hi.astype(np.int32)),
        rel_out=jnp.asarray(rel.astype(np.int32)),
        window_ms=jnp.int32(window_ms), interval_ms=jnp.int32(interval_ms),
    )


# ---- histograms -------------------------------------------------------------

HIST_GRID_FNS = {"rate", "increase", "delta", "sum_over_time", "last_sample",
                 "last_over_time"}


def _grid_hist_kernel(fn, val, n, band, band_open, onehot_lo, onehot_hi, lo, hi,
                      rel_out, window_ms, interval_ms, stale_ms):
    """Histogram variant: val [S, C, B] cumulative bucket counts; outputs
    [S, T, B]. Buckets share the series' sample times, so window edges and the
    extrapolation factor are computed once and broadcast over B; the per-bucket
    delta rides one einsum (ref: ChunkedRateFunction on HistogramVector —
    rate/increase apply per bucket)."""
    S, C, B = val.shape
    acc = val.dtype
    valid = jnp.arange(C, dtype=jnp.int32)[None, :] < n[:, None]
    v = jnp.where(valid[:, :, None], val, 0).astype(acc)

    last_cell = n[:, None] - 1
    f_idx = jnp.maximum(lo, 0)[None, :]
    l_idx = jnp.minimum(hi[None, :], last_cell)
    cnt = jnp.maximum(l_idx - f_idx + 1, 0)                       # [S, T]
    cnt_f = cnt.astype(acc)

    if fn == "sum_over_time":
        s = jnp.einsum("scb,ct->stb", v, band)
        return jnp.where((cnt >= 1)[:, :, None], s, jnp.nan)

    if fn in ("last_sample", "last_over_time"):
        static_v = jnp.einsum("scb,ct->stb", v, onehot_hi)
        row_last = jnp.take_along_axis(
            v, jnp.clip(last_cell, 0, C - 1)[:, :, None], axis=1)  # [S, 1, B]
        l_v = jnp.where((hi[None, :] <= last_cell)[:, :, None], static_v, row_last)
        ok = cnt >= 1
        if fn == "last_sample":
            l_rel = l_idx * interval_ms
            ok = ok & ((rel_out[None, :] - l_rel) <= stale_ms)
        return jnp.where(ok[:, :, None], l_v, jnp.nan)

    if fn in ("rate", "increase", "delta"):
        is_counter = fn != "delta"
        prev = jnp.concatenate([v[:, :1], v[:, :-1]], axis=1)
        pair = valid & jnp.concatenate([jnp.zeros_like(valid[:, :1]), valid[:, :-1]], 1)
        raw_inc = jnp.where(pair[:, :, None], v - prev, 0.0)
        inc = jnp.maximum(raw_inc, 0.0) if is_counter else raw_inc
        delta = jnp.einsum("scb,ct->stb", inc, band_open)          # [S, T, B]
        f_v = jnp.einsum("scb,ct->stb", v, onehot_lo)
        f_rel = f_idx * interval_ms
        l_rel = l_idx * interval_ms
        win_end = rel_out[None, :]
        dur_start = (f_rel - (win_end - window_ms)).astype(acc) / 1000.0   # [.., T]
        dur_end = (win_end - l_rel).astype(acc) / 1000.0
        sampled = (l_rel - f_rel).astype(acc) / 1000.0
        avg_dur = sampled / (cnt_f - 1.0)
        thresh = avg_dur * 1.1
        extrap = sampled
        extrap = extrap + jnp.where(dur_start < thresh, dur_start, avg_dur / 2)
        extrap = extrap + jnp.where(dur_end < thresh, dur_end, avg_dur / 2)
        factor = (extrap / sampled)[:, :, None]                    # [S, T, 1]
        if is_counter:
            dur_zero = jnp.where(delta > 0, sampled[:, :, None] * (f_v / delta), jnp.inf)
            # per-bucket zero clamp (matches per-bucket extrapolatedRate)
            ds = jnp.broadcast_to(dur_start[:, :, None], delta.shape)
            ds = jnp.where((delta > 0) & (f_v >= 0) & (dur_zero < ds), dur_zero, ds)
            extrap_b = sampled[:, :, None] + \
                jnp.where(ds < thresh[:, :, None], ds, avg_dur[:, :, None] / 2) + \
                jnp.where(dur_end[:, :, None] < thresh[:, :, None],
                          dur_end[:, :, None], avg_dur[:, :, None] / 2)
            factor = extrap_b / sampled[:, :, None]
        scaled = delta * factor
        if fn == "rate":
            scaled = scaled * (1000.0 / window_ms.astype(acc))
        return jnp.where((cnt >= 2)[:, :, None], scaled, jnp.nan)

    raise ValueError(fn)  # pragma: no cover


# ---- narrow (2D-delta resident) histograms ----------------------------------
#
# The hist-resident store keeps dd[s,c,b] = (bucket-delta of frame c) minus
# (bucket-delta of frame c-1) as i8/i16 plus first_d[s,b] f32 (ops/narrow.py
# build_narrow_hist). Every time-axis reduction the grid kernels need is
# LINEAR in the frames, so it commutes with the bucket cumsum:
#
#   inc[s,c,:]   = v[s,c,:] - v[s,c-1,:]        = cumsum_b dd[s,c,:]
#   window delta = einsum(inc, band)            = cumsum_b einsum(dd, band)
#   v_ext[s,c,:] = F[s,:] + sum_{c'<=c} inc     (F = cumsum_b first_d,
#                                                constant past the last frame)
#
# so the kernels below matmul the NARROW dd block and run one [S, T, B]
# bucket cumsum on the output — the whole-store f32 temp never exists, and
# results are bit-identical to the raw kernel on rows the encoder verified
# (integer components stay exact in f32 through both summation orders).

def grid_operands_hist_narrow(C: int, out_ts: np.ndarray, window_ms: int,
                              base_ts: int, interval_ms: int):
    """Static operands for the narrow hist kernel, cached per query shape
    (same rationale as :func:`grid_operands`): the open band for window
    deltas, prefix bands selecting v_ext at the lo/hi cells, the weighted
    band W[c, t] = #{window-t cells >= c} for sum_over_time, and the static
    (unmasked) per-step cell count."""
    key = np.ascontiguousarray(np.asarray(out_ts, np.int64)).tobytes()
    if 4 * C * len(out_ts) * 4 > 16 << 20:
        return _hist_narrow_operands_build(C, key, int(window_ms),
                                           int(base_ts), int(interval_ms))
    return _hist_narrow_operands_cached(C, key, int(window_ms), int(base_ts),
                                        int(interval_ms))


@functools.lru_cache(maxsize=32)
def _hist_narrow_operands_cached(C, out_ts_key, window_ms, base_ts, interval_ms):
    return _hist_narrow_operands_build(C, out_ts_key, window_ms, base_ts,
                                       interval_ms)


def _hist_narrow_operands_build(C, out_ts_key, window_ms, base_ts, interval_ms):
    out_ts = np.frombuffer(out_ts_key, np.int64)
    lo, hi = grid_edges(out_ts, window_ms, base_ts, interval_ms)
    rel = out_ts - base_ts
    assert abs(rel).max() < 2**31 and window_ms < 2**31, "grid range exceeds i32"
    T = len(out_ts)
    zeros = np.zeros(T, np.int64)
    l0 = np.maximum(lo, 0)
    h0 = np.minimum(hi, C - 1)
    # W[c, t] = #{cells in [l0_t, h0_t] >= c}; rows past h0 (and empty
    # windows) are 0. Cell 0's weight multiplies a zero dd frame — harmless.
    c = np.arange(C)[:, None]
    wband = np.maximum(h0[None, :] - np.maximum(c, l0[None, :]) + 1, 0) \
        .astype(np.float32)
    wband[:, h0 < l0] = 0.0
    return dict(
        band_open=jnp.asarray(band_matrix(C, lo, hi, True, np.float32)),
        prefix_lo=jnp.asarray(band_matrix(C, zeros,
                                          np.minimum(l0, C - 1), True,
                                          np.float32)),
        prefix_hi=jnp.asarray(band_matrix(C, zeros, np.clip(hi, 0, C - 1),
                                          True, np.float32)),
        wband=jnp.asarray(wband),
        cnt_static=jnp.asarray(np.maximum(h0 - l0 + 1, 0).astype(np.int32)),
        lo=jnp.asarray(lo.astype(np.int32)), hi=jnp.asarray(hi.astype(np.int32)),
        rel_out=jnp.asarray(rel.astype(np.int32)),
        window_ms=jnp.int32(window_ms), interval_ms=jnp.int32(interval_ms),
    )


def _grid_hist_kernel_narrow(fn, dd, first_d, n, band_open, prefix_lo,
                             prefix_hi, wband, cnt_static, lo, hi, rel_out,
                             window_ms, interval_ms, stale_ms):
    """Narrow variant of :func:`_grid_hist_kernel`: streams the i8/i16 dd
    block through the static matmuls and finishes with one bucket cumsum on
    the [S, T, B] output — numerics match the raw kernel bit-for-bit on rows
    the encoder verified (same masks, same extrapolation algebra)."""
    f32 = jnp.float32
    ddf = dd.astype(f32)
    F = jnp.cumsum(first_d, axis=1)                               # [S, B]
    last_cell = n[:, None] - 1
    f_idx = jnp.maximum(lo, 0)[None, :]
    l_idx = jnp.minimum(hi[None, :], last_cell)
    cnt = jnp.maximum(l_idx - f_idx + 1, 0)                       # [S, T]
    cnt_f = cnt.astype(f32)

    if fn == "sum_over_time":
        ext = jnp.cumsum(jnp.einsum("scb,ct->stb", ddf, wband), axis=2) \
            + cnt_static[None, :, None].astype(f32) * F[:, None, :]
        # v_ext extends the last frame past each row's valid count: subtract
        # the overhang cells' worth of it to match the raw masked sum
        v_last = F + jnp.cumsum(jnp.sum(ddf, axis=1), axis=1)     # [S, B]
        over = (cnt_static[None, :] - cnt).astype(f32)
        s = ext - over[:, :, None] * v_last[:, None, :]
        return jnp.where((cnt >= 1)[:, :, None], s, jnp.nan)

    if fn in ("last_sample", "last_over_time"):
        l_v = F[:, None, :] + jnp.cumsum(
            jnp.einsum("scb,ct->stb", ddf, prefix_hi), axis=2)
        # v_ext at cell clip(hi): v[hi] when hi is valid, the row's last
        # frame beyond it — exactly the raw kernel's static/row_last select
        ok = cnt >= 1
        if fn == "last_sample":
            l_rel = l_idx * interval_ms
            ok = ok & ((rel_out[None, :] - l_rel) <= stale_ms)
        return jnp.where(ok[:, :, None], l_v, jnp.nan)

    if fn in ("rate", "increase", "delta"):
        is_counter = fn != "delta"
        delta = jnp.cumsum(jnp.einsum("scb,ct->stb", ddf, band_open), axis=2)
        f_v = F[:, None, :] + jnp.cumsum(
            jnp.einsum("scb,ct->stb", ddf, prefix_lo), axis=2)
        f_rel = f_idx * interval_ms
        l_rel = l_idx * interval_ms
        win_end = rel_out[None, :]
        dur_start = (f_rel - (win_end - window_ms)).astype(f32) / 1000.0
        dur_end = (win_end - l_rel).astype(f32) / 1000.0
        sampled = (l_rel - f_rel).astype(f32) / 1000.0
        avg_dur = sampled / (cnt_f - 1.0)
        thresh = avg_dur * 1.1
        extrap = sampled
        extrap = extrap + jnp.where(dur_start < thresh, dur_start, avg_dur / 2)
        extrap = extrap + jnp.where(dur_end < thresh, dur_end, avg_dur / 2)
        factor = (extrap / sampled)[:, :, None]
        if is_counter:
            dur_zero = jnp.where(delta > 0,
                                 sampled[:, :, None] * (f_v / delta), jnp.inf)
            ds = jnp.broadcast_to(dur_start[:, :, None], delta.shape)
            ds = jnp.where((delta > 0) & (f_v >= 0) & (dur_zero < ds),
                           dur_zero, ds)
            extrap_b = sampled[:, :, None] + \
                jnp.where(ds < thresh[:, :, None], ds, avg_dur[:, :, None] / 2) + \
                jnp.where(dur_end[:, :, None] < thresh[:, :, None],
                          dur_end[:, :, None], avg_dur[:, :, None] / 2)
            factor = extrap_b / sampled[:, :, None]
        scaled = delta * factor
        if fn == "rate":
            scaled = scaled * (1000.0 / window_ms.astype(f32))
        return jnp.where((cnt >= 2)[:, :, None], scaled, jnp.nan)

    raise ValueError(fn)  # pragma: no cover


def periodic_samples_grid_hist_narrow(dd, first_d, n, out_ts: np.ndarray,
                                      window_ms: int, fn: str, base_ts: int,
                                      interval_ms: int,
                                      stale_ms: int = 300_000):
    """Narrow hist grid path: [S, T, B] output streamed off the dd block."""
    C = dd.shape[1]
    ops = grid_operands_hist_narrow(C, out_ts, window_ms, base_ts, interval_ms)
    k = _plan("grid-hist-narrow",
              (fn,) + tuple(dd.shape) + (len(out_ts), str(dd.dtype)),
              lambda: functools.partial(_grid_hist_kernel_narrow, fn))
    return k(dd, first_d, jnp.asarray(n, jnp.int32), ops["band_open"],
             ops["prefix_lo"], ops["prefix_hi"], ops["wband"],
             ops["cnt_static"], ops["lo"], ops["hi"], ops["rel_out"],
             ops["window_ms"], ops["interval_ms"],
             jnp.int32(min(stale_ms, 2**31 - 1)))


def _fused_hist_quantile_narrow_kernel(q, les, dd, first_d, n, gids, fn,
                                       num_groups, has_corr, corr_sum,
                                       corr_cnt, band_open, prefix_lo,
                                       prefix_hi, wband, cnt_static, lo, hi,
                                       rel_out, window_ms, interval_ms,
                                       stale_ms):
    """Narrow twin of :func:`_fused_hist_quantile_kernel`: per-bucket range
    function off the dd block + bucket-wise group sum + quantile, one device
    program. ``corr_sum``/``corr_cnt`` carry the cohort-pool rows' partial
    state (computed row-wise by the caller; those rows' gids are excluded
    here) — zero-shaped placeholders when ``has_corr`` is False."""
    from . import aggregators
    hist = _grid_hist_kernel_narrow(fn, dd, first_d, n, band_open, prefix_lo,
                                    prefix_hi, wband, cnt_static, lo, hi,
                                    rel_out, window_ms, interval_ms, stale_ms)
    S, T, B = hist.shape
    parts = aggregators.partial_aggregate("sum", hist.reshape(S, T * B),
                                          gids, num_groups)
    psum, pcnt = parts["sum"], parts["count"]
    if has_corr:
        psum = psum + corr_sum
        pcnt = pcnt + corr_cnt
    summed = jnp.where(pcnt == 0, jnp.nan, psum)
    return histogram_quantile(q, les, summed.reshape(num_groups, T, B))


def fused_hist_quantile_grid_narrow(q: float, les, dd, first_d, n, gids,
                                    num_groups: int, out_ts: np.ndarray,
                                    window_ms: int, fn: str, base_ts: int,
                                    interval_ms: int, stale_ms: int = 300_000,
                                    corr=None):
    """Entry for the fused narrow path (hist-resident stores): builds/caches
    the narrow operands and runs the one-program kernel; returns [G, T]."""
    C = dd.shape[1]
    ops = grid_operands_hist_narrow(C, out_ts, window_ms, base_ts, interval_ms)
    T = len(out_ts)
    B = dd.shape[2]
    if corr is None:
        z = jnp.zeros((num_groups, T * B), jnp.float32)
        corr_sum = corr_cnt = z
        has_corr = False
    else:
        corr_sum, corr_cnt = corr
        has_corr = True
    def build(fn=fn, num_groups=num_groups, has_corr=has_corr):
        def run(q, les, dd, first_d, n, gids, corr_sum, corr_cnt, *ops_t):
            return _fused_hist_quantile_narrow_kernel(
                q, les, dd, first_d, n, gids, fn, num_groups, has_corr,
                corr_sum, corr_cnt, *ops_t)
        return run

    k = _plan("fused-hist-narrow",
              (fn, num_groups, has_corr) + tuple(dd.shape)
              + (T, len(les), str(dd.dtype)), build)
    return k(
        jnp.float64(q), jnp.asarray(les), dd, first_d,
        jnp.asarray(n, jnp.int32), jnp.asarray(gids, jnp.int32),
        corr_sum, corr_cnt,
        ops["band_open"], ops["prefix_lo"], ops["prefix_hi"], ops["wband"],
        ops["cnt_static"], ops["lo"], ops["hi"], ops["rel_out"],
        ops["window_ms"], ops["interval_ms"],
        jnp.int32(min(stale_ms, 2**31 - 1)))


def _fused_hist_quantile_kernel(q, les, val, n, gids, fn, num_groups,
                                band, band_open, onehot_lo, onehot_hi, lo, hi,
                                rel_out, window_ms, interval_ms, stale_ms):
    """ONE device program for histogram_quantile(q, sum by(...) (fn(m[w])))
    on a grid-aligned histogram shard: per-bucket range function + bucket-wise
    group sum + Prometheus quantile, fetched with a single sync. Each stage
    dispatched separately costs a host->device submission round trip (~10ms
    on a tunneled link, and all dispatches serialize under the shard lock) —
    fusing them is the difference between 4 round trips per query and one
    (ref: HistogramQueryBenchmark.scala is the bar; the reference streams
    bucket rates through one iterator chain for the same reason)."""
    from . import aggregators
    hist = _grid_hist_kernel(fn, val, n, band, band_open, onehot_lo,
                             onehot_hi, lo, hi, rel_out, window_ms,
                             interval_ms, stale_ms)
    S, T, B = hist.shape
    parts = aggregators.partial_aggregate("sum", hist.reshape(S, T * B),
                                          gids, num_groups)
    summed = jnp.where(parts["count"] == 0, jnp.nan, parts["sum"])
    return histogram_quantile(q, les, summed.reshape(num_groups, T, B))


def fused_hist_quantile_grid(q: float, les, val, n, gids, num_groups: int,
                             out_ts: np.ndarray, window_ms: int, fn: str,
                             base_ts: int, interval_ms: int,
                             stale_ms: int = 300_000):
    """Entry for the fused path: builds/caches the grid operands and runs
    :func:`_fused_hist_quantile_kernel`; returns the [G, T] device array."""
    C = val.shape[1]
    dtype = np.float64 if val.dtype == jnp.float64 else np.float32
    ops = grid_operands(C, out_ts, window_ms, fn, base_ts, interval_ms, dtype)

    def build(fn=fn, num_groups=num_groups):
        def run(q, les, val, n, gids, *ops_t):
            return _fused_hist_quantile_kernel(q, les, val, n, gids, fn,
                                               num_groups, *ops_t)
        return run

    k = _plan("fused-hist",
              (fn, num_groups) + tuple(val.shape)
              + (len(out_ts), str(val.dtype)), build)
    return k(
        jnp.float64(q), jnp.asarray(les), val, jnp.asarray(n, jnp.int32),
        jnp.asarray(gids, jnp.int32),
        ops["band"], ops["band_open"], ops["onehot_lo"],
        ops["onehot_hi"], ops["lo"], ops["hi"], ops["rel_out"],
        ops["window_ms"], ops["interval_ms"],
        jnp.int32(min(stale_ms, 2**31 - 1)))


def periodic_samples_grid_hist(val, n, out_ts: np.ndarray, window_ms: int, fn: str,
                               base_ts: int, interval_ms: int,
                               stale_ms: int = 300_000):
    """Histogram grid path: [S, T, B] output."""
    C = val.shape[1]
    dtype = np.float64 if val.dtype == jnp.float64 else np.float32
    ops = grid_operands(C, out_ts, window_ms, fn, base_ts, interval_ms, dtype)
    k = _plan("grid-hist",
              (fn,) + tuple(val.shape) + (len(out_ts), str(val.dtype)),
              lambda: functools.partial(_grid_hist_kernel, fn))
    return k(val, jnp.asarray(n, jnp.int32), ops["band"],
             ops["band_open"], ops["onehot_lo"], ops["onehot_hi"],
             ops["lo"], ops["hi"], ops["rel_out"], ops["window_ms"],
             ops["interval_ms"], jnp.int32(min(stale_ms, 2**31 - 1)))


def _hist_quantile(q, les, counts, xp):
    """One shared body for the device (xp=jnp) and host (xp=np) entry points
    below: the classic-le and native-histogram paths answer identically by
    construction, not by keeping two copies in sync."""
    import contextlib
    guard = (np.errstate(invalid="ignore", divide="ignore")
             if xp is np else contextlib.nullcontext())
    B = les.shape[0]
    total = counts[..., -1]
    rank = q * total
    # first bucket with cumulative >= rank
    b = (counts < rank[..., None]).sum(axis=-1)
    b = xp.clip(b, 0, B - 1)
    lo_le = xp.where(b > 0, les[xp.maximum(b - 1, 0)], 0.0)
    hi_le = les[b]
    lo_cnt = xp.where(b > 0, xp.take_along_axis(
        counts, xp.maximum(b - 1, 0)[..., None], axis=-1)[..., 0], 0.0)
    hi_cnt = xp.take_along_axis(counts, b[..., None], axis=-1)[..., 0]
    with guard:
        frac = xp.where(hi_cnt > lo_cnt, (rank - lo_cnt) / (hi_cnt - lo_cnt), 1.0)
        res = lo_le + (hi_le - lo_le) * frac
    # +Inf top bucket: clamp to the highest finite bound
    res = xp.where(xp.isinf(hi_le),
                   xp.where(b > 0, les[xp.maximum(b - 1, 0)], xp.nan), res)
    res = xp.where((total > 0) & ~xp.isnan(total), res, xp.nan)
    res = xp.where(q < 0, -xp.inf, res)
    res = xp.where(q > 1, xp.inf, res)
    return res


@jax.jit
def histogram_quantile(q, les, counts):
    """Prometheus histogram_quantile, vectorized: les [B], counts [..., B]
    cumulative -> [...] (ref: Histogram.scala quantile :288; device mirror of
    memory/hist.py host reference)."""
    return _hist_quantile(q, les, counts, jnp)


def histogram_quantile_np(q, les, counts):
    """Host-numpy evaluation of the identical algebra — the classic
    le-labeled path (query/exec.py _classic_le_quantile) finishes tiny
    ragged per-group matrices here without a device round trip."""
    return _hist_quantile(q, les, counts, np)


def periodic_samples_grid(val, n, out_ts: np.ndarray, window_ms: int, fn: str,
                          base_ts: int, interval_ms: int, stale_ms: int = 300_000):
    """Grid-path periodic samples over a uniform-start shard: [S, T] output."""
    C = val.shape[1]
    dtype = np.float64 if val.dtype == jnp.float64 else np.float32
    ops = grid_operands(C, out_ts, window_ms, fn, base_ts, interval_ms, dtype)
    k = _plan("grid",
              (fn,) + tuple(val.shape) + (len(out_ts), str(val.dtype)),
              lambda: functools.partial(_grid_kernel, fn))
    return k(val, jnp.asarray(n, jnp.int32), ops["band"],
             ops["band_open"], ops["onehot_lo"], ops["onehot_hi"],
             ops["lo"], ops["hi"], ops["rel_out"], ops["window_ms"],
             ops["interval_ms"], jnp.int32(min(stale_ms, 2**31 - 1)))
