"""Fused single-pass grid kernel: rate + cross-series aggregation in one read.

The north-star query ``sum(rate(metric[5m]))`` over a grid-aligned shard is
HBM-bound: the value store ([S, C] f32, gigabytes) dwarfs every other operand.
The two-step path (ops/gridfns.py ``_grid_kernel`` then
ops/aggregators.partial_aggregate) costs ~2.3 passes over HBM because XLA
materializes the per-cell increments and the [S, T] rate matrix between the
elementwise stage and the band matmuls.

This Pallas kernel streams the store once: for each [Sb, C] row tile it
  1. computes counter-corrected increments in VMEM (relu of adjacent diffs —
     a reset cell contributes 0, ref RateFunctions.scala extrapolatedRate),
  2. runs BOTH band products on the MXU while the tile is resident
     (``inc @ band_open`` for window deltas, ``v @ onehot_lo`` for the raw
     first-sample values needed by the counter zero-clamp),
  3. applies the Prometheus extrapolation algebra elementwise [Sb, T],
  4. folds the tile straight into per-group partial state ([G, T] sum/count
     via a one-hot MXU matmul) accumulated across the sequential row grid —
     the [S, T] rate matrix never exists in HBM.

Partial-state layout matches ops.aggregators.partial_aggregate so results
combine across shards/batches with combine_partials / the mesh psum path.

Numerics are identical to the two-step f32 path: same masks, same band
operands, same extrapolation expressions, f32 accumulation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import decodereg, gridfns

FUSED_FNS = {"rate", "increase", "delta"}
# window-aggregation shapes of the fused tier (ISSUE 9): the same one-pass
# select+decode+window+fold plan serves avg_over_time/sum_over_time-into-
# reduce dashboards — closed band instead of the open one, cnt >= 1 presence
FUSED_WINDOW_FNS = {"sum_over_time", "avg_over_time", "count_over_time"}
FUSED_OPS = {"sum", "avg", "count", "group", "stddev", "stdvar"}


def _roundup(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def tile_contrib(fn: str, window_ms: int, interval_ms: int, c0: int,
                 v, n, band, ohlo, lo, hi, rel, roll):
    """Shared per-tile window math of the fused tier: decoded values
    ``v [Sb, Ca]`` -> ``(contrib [Sb, Tp]`` with absent cells zeroed,
    ``okf [Sb, Tp]`` presence as f32). ONE definition per tiling plan for
    BOTH backends: the Pallas kernel body reads its VMEM refs and calls
    this; the XLA-fused twin (ops/fusedresident.py) scans the same row
    tiles through it — variant parity is by construction, not discipline.
    ``roll`` abstracts the backend's shift primitive (pltpu.roll in-kernel,
    jnp.roll in the scan); the wrapped column's garbage is masked either
    way. ``band`` is the OPEN band for the rate family and the CLOSED band
    for the window-aggregation fns (host_operands builds the right one)."""
    f32 = jnp.float32
    Sb, Ca = v.shape
    lcol = jax.lax.broadcasted_iota(jnp.int32, (Sb, Ca), 1)
    col = lcol + c0                                           # global cell
    valid = col < n
    v = jnp.where(valid, v, 0.0)

    last_cell = n - 1                                         # [Sb, 1]
    f_idx = jnp.maximum(lo, 0)                                # [1, Tp]
    l_idx = jnp.minimum(hi, last_cell)                        # [Sb, Tp]
    cnt = jnp.maximum(l_idx - f_idx + 1, 0)
    cnt_f = cnt.astype(f32)

    if fn in FUSED_WINDOW_FNS:
        ok = cnt >= 1
        if fn == "count_over_time":
            return jnp.where(ok, cnt_f, 0.0), ok.astype(f32)
        s = jnp.dot(v, band, preferred_element_type=f32)      # closed band
        if fn == "avg_over_time":
            s = s / cnt_f
        return jnp.where(ok, s, 0.0), ok.astype(f32)

    is_counter = fn != "delta"
    # increments: valid cells are a prefix of each row, so cell c has a valid
    # predecessor exactly when c > 0 and c is valid; roll's column-0 wraparound
    # is masked out by that same condition. With a column offset the local
    # column 0 wraps to the slice's LAST column — its increment is garbage but
    # never consumed (band rows at/below the first window edge are zero);
    # zero it anyway so no value-dependent surprise can leak
    prev = roll(v)
    raw = v - prev
    inc = jnp.maximum(raw, 0.0) if is_counter else raw
    mask = valid & (col > 0)
    if c0:
        mask &= lcol > 0
    inc = jnp.where(mask, inc, 0.0)

    delta = jnp.dot(inc, band, preferred_element_type=f32)    # [Sb, Tp]
    f_v = jnp.dot(v, ohlo, preferred_element_type=f32)

    relf = rel.astype(f32)                                    # [1, Tp]
    f_rel = (f_idx * interval_ms).astype(f32)
    l_rel = (l_idx * interval_ms).astype(f32)
    dur_start = (f_rel - (relf - window_ms)) / 1000.0
    dur_end = (relf - l_rel) / 1000.0
    sampled = (l_rel - f_rel) / 1000.0
    avg_dur = sampled / (cnt_f - 1.0)
    if is_counter:
        safe = jnp.where(delta > 0, delta, 1.0)
        dur_zero = jnp.where(delta > 0, sampled * (f_v / safe), jnp.inf)
        dur_start = jnp.where((delta > 0) & (f_v >= 0) & (dur_zero < dur_start),
                              dur_zero, dur_start)
    thresh = avg_dur * 1.1
    extrap = sampled
    extrap = extrap + jnp.where(dur_start < thresh, dur_start, avg_dur / 2)
    extrap = extrap + jnp.where(dur_end < thresh, dur_end, avg_dur / 2)
    scaled = delta * (extrap / sampled)
    if fn == "rate":
        scaled = scaled * (1000.0 / window_ms)

    ok = cnt >= 2
    return jnp.where(ok, scaled, 0.0), ok.astype(f32)


# back-compat alias: the quant16 decode now lives in the shared decode-
# variant registry (ops/decodereg.py) next to its delta/hist siblings
decode_narrow_tile = decodereg.decode_quant16


def _kernel_body(fn: str, needs_sumsq: bool, window_ms: int, interval_ms: int,
                 Sb: int, Ca: int, Tp: int, G: int, residency: str, c0: int,
                 *refs):
    """``Ca`` is the streamed column width and ``c0`` its global offset into
    the store: a sub-range query streams (and matmuls) only its active
    columns (see active_columns); full-range queries have c0=0, Ca=C.
    ``residency`` names the decode variant (ops/decodereg.py) — the value
    block plus its per-row operands decode to f32 in VMEM per tile."""
    var = decodereg.variant(residency)
    R = var.row_operands
    val_ref = refs[0]
    rowrefs = refs[1:1 + R]
    (n_ref, gid_ref, band_ref, ohlo_ref,
     lo_ref, hi_ref, rel_ref, sum_ref, cnt_ref, *maybe_sumsq) = refs[1 + R:]
    i = pl.program_id(0)
    f32 = jnp.float32

    # decode in VMEM: the registered pallas twin of the residency variant
    v = var.pallas(val_ref[:], *(r[:] for r in rowrefs))      # [Sb, Ca]
    n = n_ref[:]                                              # [Sb, 1] i32
    # i32 shift: x64 mode would lower an i64 operand, which
    # tpu.dynamic_rotate rejects
    contrib, okf = tile_contrib(
        fn, window_ms, interval_ms, c0, v, n, band_ref[:], ohlo_ref[:],
        lo_ref[:], hi_ref[:], rel_ref[:],
        roll=lambda x: pltpu.roll(x, jnp.int32(1), 1))

    # per-group fold on the MXU: [G, Sb] one-hot x [Sb, Tp]
    gid = gid_ref[:]                                          # [Sb, 1] i32
    gcol = jax.lax.broadcasted_iota(jnp.int32, (Sb, G), 1)
    oh = (gcol == gid).astype(f32)                            # [Sb, G]
    dn = (((0,), (0,)), ((), ()))
    psum = jax.lax.dot_general(oh, contrib, dn, preferred_element_type=f32)
    pcnt = jax.lax.dot_general(oh, okf, dn, preferred_element_type=f32)

    @pl.when(i == 0)
    def _():
        sum_ref[:] = jnp.zeros_like(sum_ref)
        cnt_ref[:] = jnp.zeros_like(cnt_ref)
        if needs_sumsq:
            maybe_sumsq[0][:] = jnp.zeros_like(maybe_sumsq[0])

    sum_ref[:] += psum
    cnt_ref[:] += pcnt
    if needs_sumsq:
        psq = jax.lax.dot_general(oh, contrib * contrib, dn,
                                  preferred_element_type=f32)
        maybe_sumsq[0][:] += psq


@functools.lru_cache(maxsize=64)
def build_pallas(fn: str, needs_sumsq: bool, window_ms: int, interval_ms: int,
                 S: int, Sb: int, C: int, Tp: int, G: int, interpret: bool,
                 residency: str = "raw", c0: int = 0, Ck: int = 0):
    """The raw (traceable) fused-kernel pallas_call — also invoked inside
    ``shard_map`` by the mesh executor (parallel/distributed.py), where each
    shard runs this same map phase on its resident block and the partial
    state crosses the ICI collective (ref: AggrOverRangeVectors.scala:62 —
    the identical map phase runs on every data node). ``residency`` names
    the decode variant (ops/decodereg.py): the value operand is that
    variant's narrow block plus its per-row operands (quant16: vmin/scale;
    delta16/delta8: anchor), decoded to f32 in VMEM per tile.

    ``(c0, Ca)`` describe the active column range (see active_columns): when
    it covers less than the full store, the kernel's value block starts at
    column ``c0`` and spans only ``Ca`` columns — HBM bytes and MXU MACs
    scale with the query's range, not the store's retention — and the band
    operands arrive pre-sliced to [Ca, Tp]. full_columns variants (the
    delta cumsum telescopes from cell 0) require c0=0."""
    var = decodereg.variant(residency)
    assert not var.full_columns or c0 == 0, (residency, c0)
    n_out = 3 if needs_sumsq else 2
    Ca = Ck if Ck else C
    out_shape = tuple(jax.ShapeDtypeStruct((G, Tp), jnp.float32)
                      for _ in range(n_out))
    body = functools.partial(_kernel_body, fn, needs_sumsq, window_ms,
                             interval_ms, Sb, Ca, Tp, G, residency, c0)
    acc_spec = pl.BlockSpec((G, Tp), lambda i: (0, 0), memory_space=pltpu.VMEM)
    const = functools.partial(pl.BlockSpec, index_map=lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
    row = lambda shape: pl.BlockSpec(shape, lambda i: (i, 0),  # noqa: E731
                                     memory_space=pltpu.VMEM)
    kcol = c0 // Ca                       # active_columns guarantees c0 % Ca == 0
    in_specs = [pl.BlockSpec((Sb, Ca), lambda i: (i, kcol),
                             memory_space=pltpu.VMEM)]
    in_specs += [row((Sb, 1))] * var.row_operands   # vmin/scale or anchor
    in_specs += [
        row((Sb, 1)), row((Sb, 1)),
        const((Ca, Tp)), const((Ca, Tp)),
        const((1, Tp)), const((1, Tp)), const((1, Tp)),
    ]
    return pl.pallas_call(
        body,
        grid=(S // Sb,),
        in_specs=in_specs,
        out_specs=tuple(acc_spec for _ in range(n_out)),
        out_shape=out_shape,
        interpret=interpret,
    )


def active_columns(C: int, lo: np.ndarray, hi: np.ndarray) -> tuple[int, int]:
    """(c0, Ca): the aligned store-column range the query actually reads —
    first-sample selects need cell max(0, lo.min()); window sums need cells
    (lo, hi]. Everything outside contributes nothing, so a sub-range query
    (a "last 30m" dashboard panel over hours of retention) streams and
    matmuls only its own columns. Constraint: the value block's offset must
    be a multiple of its width (Pallas block indexing), so Ca grows in
    128-steps until an aligned start covers the range — worst case the full
    store (c0=0, Ca=C), typical dashboards a small suffix of it. C must be
    a multiple of 128; callers get (0, C) otherwise."""
    if C % 128 != 0 or len(lo) == 0:
        return 0, C
    first = max(0, int(lo.min()))
    last = min(C - 1, int(hi.max()))
    if last < first:                      # empty windows: minimal block
        last = first
    c1 = _roundup(last + 1, 128)
    Ca = c1 - (first // 128) * 128
    while Ca < C:
        c0 = (first // Ca) * Ca
        # the block must cover [c0, c1) AND stay inside the store: for a
        # non-power-of-two C the last aligned block start can overhang the
        # store edge (e.g. C=640, Ca=384 -> c0=384, c0+Ca=768), which would
        # under-slice the band operand and read value columns past C
        if c0 + Ca >= c1 and c0 + Ca <= C:
            return c0, Ca
        Ca += 128
    return 0, C


def build_xla_tiles(fn: str, needs_sumsq: bool, window_ms: int,
                    interval_ms: int, S: int, Sb: int, C: int, Tp: int,
                    G: int, residency: str = "raw", c0: int = 0, Ck: int = 0):
    """XLA-fused twin of :func:`build_pallas`, built from the SAME tiling
    plan: one ``lax.scan`` walks the identical [Sb, Ca] row tiles through
    the identical :func:`tile_contrib` math and accumulates the same [G, Tp]
    partial state — one compiled program, intermediates bounded by one tile,
    the [S, T] matrix never materializes in HBM. Selected per
    ``query.fused_kernels`` (ops/fusedresident.py); signature-compatible
    with build_pallas's returned call so the mesh route swaps them freely.
    ``residency`` picks the registered xla decode twin (ops/decodereg.py)
    applied per tile — the full [S, C] f32 block never materializes on
    this variant either."""
    f32 = jnp.float32
    var = decodereg.variant(residency)
    assert not var.full_columns or c0 == 0, (residency, c0)
    R = var.row_operands
    Ca = Ck if Ck else C
    nt = S // Sb
    dn = (((0,), (0,)), ((), ()))
    roll = lambda x: jnp.roll(x, 1, axis=1)  # noqa: E731 — tile-local wrap,
    # masked in tile_contrib exactly like pltpu.roll's

    def fold(carry, xs, band, ohlo, lo, hi, rel):
        blk_t, *rest = xs
        v = var.xla(blk_t, *rest[:R])
        n_t, g_t = rest[R], rest[R + 1]
        contrib, okf = tile_contrib(fn, window_ms, interval_ms, c0,
                                    v, n_t, band, ohlo, lo, hi, rel, roll)
        gcol = jax.lax.broadcasted_iota(jnp.int32, (Sb, G), 1)
        oh = (gcol == g_t).astype(f32)
        out = (carry[0] + jax.lax.dot_general(oh, contrib, dn,
                                              preferred_element_type=f32),
               carry[1] + jax.lax.dot_general(oh, okf, dn,
                                              preferred_element_type=f32))
        if needs_sumsq:
            out += (carry[2] + jax.lax.dot_general(
                oh, contrib * contrib, dn, preferred_element_type=f32),)
        return out, None

    def run_tiles(tiles, band, ohlo, lo, hi, rel):
        init = tuple(jnp.zeros((G, Tp), f32)
                     for _ in range(3 if needs_sumsq else 2))
        outs, _ = jax.lax.scan(
            lambda c, xs: fold(c, xs, band, ohlo, lo, hi, rel), init, tiles)
        return outs

    def call(blk, *rest):
        # rest: R per-row decode operands, n2, g2, then the 5 band/edge ops;
        # active columns sliced like the pallas block index map
        rows, n2, g2 = rest[:R], rest[R], rest[R + 1]
        tiles = ((blk[:, c0:c0 + Ca].reshape(nt, Sb, Ca),)
                 + tuple(r.reshape(nt, Sb, 1) for r in rows)
                 + (n2.reshape(nt, Sb, 1), g2.reshape(nt, Sb, 1)))
        return run_tiles(tiles, *rest[R + 2:])
    return call


def _build_call(fn: str, needs_sumsq: bool, window_ms: int, interval_ms: int,
                S: int, Sb: int, C: int, Tp: int, G: int, interpret: bool,
                residency: str = "raw", c0: int = 0, Ck: int = 0,
                variant: str = "pallas"):
    """The compiled fused program via the explicit plan cache (query/
    plancache.py) — its key IS this signature: fn/op statics, the padded
    [S, C, Tp, G] shape buckets, the ``residency`` decode variant
    ("raw" | "quant16" | "delta16" | "delta8"), and the backend ``variant``
    ("pallas" | "xla") — every (residency, backend) pair is a distinct
    compiled program and caches as a distinct kernel variant."""
    from ..query.plancache import plan_cache
    R = decodereg.variant(residency).row_operands

    def build():
        if variant == "xla":
            call = build_xla_tiles(fn, needs_sumsq, window_ms, interval_ms,
                                   S, Sb, C, Tp, G, residency, c0, Ck)
        else:
            call = build_pallas(fn, needs_sumsq, window_ms, interval_ms,
                                S, Sb, C, Tp, G, interpret, residency,
                                c0, Ck)

        # one dispatch per query: dtype casts and [S] -> [S, 1] reshapes live
        # inside the jit — on a tunneled device every extra dispatch is a
        # round-trip (~0.1s measured), dwarfing the kernel itself
        if residency != "raw":
            def wrapped(blk, *rest):
                rows = tuple(r.reshape(S, 1) for r in rest[:R])
                n, gids = rest[R], rest[R + 1]
                return call(blk, *rows,
                            n.astype(jnp.int32).reshape(S, 1),
                            gids.astype(jnp.int32).reshape(S, 1),
                            *rest[R + 2:])
        else:
            def wrapped(val, n, gids, *ops):
                return call(val.astype(jnp.float32),
                            n.astype(jnp.int32).reshape(S, 1),
                            gids.astype(jnp.int32).reshape(S, 1), *ops)
        return wrapped

    return plan_cache.program(
        "fused-grid",
        (fn, needs_sumsq, window_ms, interval_ms, S, Sb, C, Tp, G,
         interpret, residency, c0, Ck, variant), build)


def pad_edges(lo: np.ndarray, hi: np.ndarray, rel: np.ndarray,
              window_ms: int, Tp: int):
    """Step-edge operands padded to the kernel's Tp grid as [1, Tp] i32:
    lo zero-padded, hi padded with -1 (an empty window — cnt clamps to 0
    so padded steps contribute nothing), rel zero-padded. One definition
    for every fused tier (scalar here, hist in ops/fusedresident.py) —
    the sentinel values are kernel contracts, not formatting."""
    T = len(rel)
    assert abs(rel).max(initial=0) < 2**31 and window_ms < 2**31
    lo_p = np.zeros(Tp, np.int32); lo_p[:T] = lo
    hi_p = np.full(Tp, -1, np.int32); hi_p[:T] = hi
    rel_p = np.zeros(Tp, np.int32); rel_p[:T] = rel
    return (lo_p.reshape(1, Tp), hi_p.reshape(1, Tp), rel_p.reshape(1, Tp))


def host_operands(C: int, Tp: int, out_ts: np.ndarray, window_ms: int,
                  base_ts: int, interval_ms: int, fn_kind: str = "rate",
                  full_cols: bool = False):
    """Band/one-hot/edge operands as host arrays + active column range:
    (band, ohlo, lo[1,Tp], hi[1,Tp], rel[1,Tp], c0, Ck) — shared by the
    single-chip upload cache below and the mesh path (which replicates them
    across shard devices). For a sub-range query the band/ohlo rows are
    sliced to the active [c0, c0+Ck) columns (the tiled kernel streams
    only those store tiles); full-range queries keep [C, Tp] operands.
    ``fn_kind`` picks the band form: "rate" builds the OPEN band the
    increment matmul needs, "window" the CLOSED band of the *_over_time
    fns (tile_contrib consumes whichever matches its fn). ``full_cols``
    bypasses active-column slicing — required by full_columns decode
    variants whose per-tile decode telescopes from cell 0."""
    T = len(out_ts)
    lo, hi = gridfns.grid_edges(out_ts, window_ms, base_ts, interval_ms)
    rel = out_ts - base_ts
    lo_p, hi_p, rel_p = pad_edges(lo, hi, rel, window_ms, Tp)
    band = np.zeros((C, Tp), np.float32)
    band[:, :T] = gridfns.band_matrix(C, lo, hi, fn_kind == "rate",
                                      np.float32)
    ohlo = np.zeros((C, Tp), np.float32)
    ohlo[:, :T] = gridfns.onehot_matrix(C, np.maximum(lo, 0), np.float32)
    c0, Ca = (0, C) if full_cols else active_columns(C, lo, hi)
    if Ca < C:
        band = np.ascontiguousarray(band[c0:c0 + Ca])
        ohlo = np.ascontiguousarray(ohlo[c0:c0 + Ca])
    return (band, ohlo, lo_p, hi_p, rel_p, c0, Ca)


@functools.lru_cache(maxsize=32)
def _device_operands(C: int, Tp: int, out_ts_key: bytes, window_ms: int,
                     base_ts: int, interval_ms: int, fn_kind: str = "rate",
                     full_cols: bool = False):
    """Band/one-hot/edge operands on device, cached per query shape — the
    upload matters: repeated host->device transfers of the [C, Tp] bands per
    row-batch would dominate over a tunneled device link."""
    out_ts = np.frombuffer(out_ts_key, np.int64)
    *arrs, c0, Ck = host_operands(C, Tp, out_ts, window_ms, base_ts,
                                  interval_ms, fn_kind, full_cols)
    return tuple(jnp.asarray(a) for a in arrs) + (c0, Ck)


# conservative VMEM-driven caps for the fused path; beyond them callers must
# take the two-step route (which switches to segment_sum for large G)
MAX_GROUPS = 64          # matches aggregators.MATMUL_GROUP_LIMIT
MAX_STEPS = 512          # Tp cap: resident [C, Tp] bands + [Sb, Tp] tiles
MAX_CAPACITY = 1024      # C cap: [Sb, C] row tile + bands


def fusable(S: int, C: int, T: int, num_groups: int) -> bool:
    """Shape gate: the kernel keeps its operands resident in VMEM."""
    return (C <= MAX_CAPACITY
            and _roundup(max(T, 1), 128) <= MAX_STEPS
            and num_groups <= MAX_GROUPS
            and (S % 512 == 0 or (S <= 512 and S % 8 == 0)))


class PaddedPartials:
    """Device-resident padded kernel outputs, fetched lazily: the leaf holds
    the shard lock while dispatching — blocking there on a device_get would
    stall every ingest/query thread for the whole streaming pass. resolve()
    runs at present/merge time, outside the lock."""

    def __init__(self, outs, op: str, num_groups: int, T: int):
        self._outs = outs
        self._op = op
        self._ng = num_groups
        self._T = T

    def parts_of(self, outs) -> dict:
        """Partial dict from ALREADY-FETCHED outputs (callers batching many
        bundles into one device_get use this instead of resolve())."""
        s, c = outs[0][:self._ng, :self._T], outs[1][:self._ng, :self._T]
        if self._op in ("count", "group"):
            return {"count": c}
        parts = {"sum": s, "count": c}
        if len(outs) > 2:
            parts["sumsq"] = outs[2][:self._ng, :self._T]
        return parts

    def resolve(self) -> dict:
        return self.parts_of(jax.device_get(self._outs))


def fused_grid_aggregate(op: str, fn: str, val, n, gids, num_groups: int,
                         out_ts: np.ndarray, window_ms: int,
                         base_ts: int, interval_ms: int, fetch: bool = True,
                         narrow=None, variant: str = "pallas"):
    """One-pass ``op(fn(metric[window]))`` partials over a grid-aligned block.

    val [S, C] f32 (S a multiple of 512 or a power of two), n [S] i32 valid
    counts, gids [S] i32 dense group ids (< num_groups). Returns the same
    partial-state dict as ``aggregators.partial_aggregate(op, ...)`` with
    [num_groups, T] arrays, combinable via ``combine_partials`` / psum.
    With ``fetch=False`` returns a :class:`PaddedPartials` whose ``resolve()``
    does the (blocking) host fetch later. ``narrow=(kind, operands)`` streams
    a registered narrow block (ops/decodereg.py) instead of ``val``: kind
    names the decode variant ("quant16" | "delta16" | "delta8") and
    ``operands = (block, *row_operands)`` its device arrays — 1/4 to 1/2 the
    HBM bytes; the caller must already have zeroed ``n`` for rows whose
    narrow encoding is not bit-exact.
    """
    assert fn in FUSED_FNS | FUSED_WINDOW_FNS and op in FUSED_OPS
    if narrow is not None:
        kind, nops = narrow
        S, C = nops[0].shape
    else:
        kind, nops = "raw", None
        S, C = val.shape
    T = len(out_ts)
    assert fusable(S, C, T, num_groups), (S, C, T, num_groups)
    Tp = _roundup(max(T, 1), 128)
    Sb = 512 if S % 512 == 0 else (S if S <= 512 else None)
    G = _roundup(max(num_groups, 8), 8)

    band, ohlo, lo_d, hi_d, rel_d, c0, Ck = _device_operands(
        C, Tp, np.ascontiguousarray(np.asarray(out_ts, np.int64)).tobytes(),
        int(window_ms), int(base_ts), int(interval_ms),
        "window" if fn in FUSED_WINDOW_FNS else "rate",
        decodereg.variant(kind).full_columns)

    needs_sumsq = op in ("stddev", "stdvar")
    interpret = jax.default_backend() != "tpu"
    call = _build_call(fn, needs_sumsq, int(window_ms), int(interval_ms),
                       S, Sb, C, Tp, G, interpret, kind, c0, Ck, variant)
    # the framework runs with x64 on (int64 timestamps); Mosaic rejects the
    # i64 scalars x64 tracing injects (grid index maps, roll shifts), and the
    # kernel itself is pure f32/i32 — so trace the call with x64 off
    from ..utils import enable_x64
    with enable_x64(False):
        if nops is not None:
            outs = call(*nops, jnp.asarray(n), jnp.asarray(gids),
                        band, ohlo, lo_d, hi_d, rel_d)
        else:
            outs = call(val, jnp.asarray(n), jnp.asarray(gids),
                        band, ohlo, lo_d, hi_d, rel_d)
    # partial state is tiny ([G, Tp]): ONE host fetch finishes the query — the
    # slice/present/combine chain as device ops would cost a round-trip each
    padded = PaddedPartials(outs, op, num_groups, T)
    return padded.resolve() if fetch else padded


@functools.lru_cache(maxsize=8)
def zero_gids(S: int):
    """Cached device zeros for single-group (global) aggregation — uploading
    a fresh [S] int32 per query costs ~0.15s for 1M series on a tunneled
    device link."""
    return jnp.zeros(S, jnp.int32)
