"""Shared decode-variant registry for the fused compressed-resident tier.

Reference role: the reference FiloDB reads every chunk through ONE codec
dispatch table (format/vectors/*.scala — each vector type names its reader
and the iterator chain decodes on access). This module is the TPU analog:
every narrow-resident block format the fused kernels can stream is a
registered :class:`DecodeVariant` naming BOTH backend decode twins — the
``pallas`` one the kernel body calls on its VMEM refs and the ``xla`` one
the scan twin calls on its tile slices. Both are built from the same jnp
expressions, so variant parity is by construction; filolint's
``surface-decode-variant-twin`` rule makes one-sided additions (a variant
registered with only one backend) fail tier-1.

Variants registered here:

  name     block dtype  row operands      decode
  -------  -----------  ----------------  ---------------------------------
  raw      f32 [S,C]    —                 identity
  quant16  i16 [S,C]    vmin, scale       vmin + (q + 32768) * scale
  delta16  i16 [S,C]    anchor            anchor + cumsum(dv)  (full cols)
  delta8   i8  [S,C]    anchor            anchor + cumsum(dv)  (full cols)
  hist16   i16 [S,C,B]  first_d           dd -> f32 (cumsums in tile math)
  hist8    i8  [S,C,B]  first_d           dd -> f32 (cumsums in tile math)

``full_columns`` marks variants whose decode needs the whole column prefix
(the delta cumsum telescopes from cell 0), so the active-column slicing of
ops/fusedgrid.active_columns must be bypassed — same constraint the hist
tier documents in hist_fusable. ``value_bytes`` is the per-sample block
cost the residency accounting and the bench suite report.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DecodeVariant:
    """One narrow block format both fused backends can stream.

    ``pallas``/``xla`` map (block, *row_operands) -> decoded f32 values;
    the kernel body calls ``pallas`` on materialized VMEM refs, the scan
    twin calls ``xla`` on its per-tile slices. ``row_operands`` counts the
    per-row f32 side arrays ([S] -> [Sb, 1] tiles) the decode consumes
    beyond the block itself."""

    name: str
    pallas: Callable
    xla: Callable
    row_operands: int
    block_dtype: str
    full_columns: bool
    value_bytes: int


DECODE_VARIANTS: dict[str, DecodeVariant] = {}

# scalar variants eligible on 2-D [S, C] stores (fusedgrid tier); hist
# variants ride the [S, C, B] tier in ops/fusedresident.py
SCALAR_VARIANTS = ("quant16", "delta16", "delta8")


def register_variant(name: str, *, pallas: Callable, xla: Callable,
                     row_operands: int, block_dtype: str,
                     full_columns: bool, value_bytes: int) -> DecodeVariant:
    """Register a decode variant. BOTH backend twins are required — a
    variant that only one backend can serve would silently fall back when
    ``query.fused_kernels`` selects the other, breaking the variant-parity
    contract (and filolint's surface-decode-variant-twin rule enforces the
    call-site shape statically)."""
    if pallas is None or xla is None:
        raise ValueError(f"decode variant {name!r} must declare both a "
                         "pallas and an xla twin")
    if name in DECODE_VARIANTS:
        raise ValueError(f"decode variant {name!r} already registered")
    v = DecodeVariant(name, pallas, xla, row_operands, block_dtype,
                      full_columns, value_bytes)
    DECODE_VARIANTS[name] = v
    return v


def variant(name: str) -> DecodeVariant:
    return DECODE_VARIANTS[name]


# ---------------------------------------------------------------------------
# decode twins — plain jnp expressions valid both inside a Pallas body (on
# values read from VMEM refs) and inside the XLA scan (on tile slices)
# ---------------------------------------------------------------------------

def decode_raw(v):
    """Raw f32 block: identity."""
    return v


def decode_quant16(q, vmin, scale):
    """u16 quantized mirror decode (ops/narrow.build_narrow): the biased
    i16 block stores x = q - 32768 for q = round((v - vmin)/2^e) in
    [0, 65535]; q * 2^e is exact (q < 2^16, power-of-two scale) and
    vmin + q * 2^e reproduces the f32 value bit-exactly for rows the
    encoder verified — HALF the HBM bytes of the raw f32 stream (ref: the
    reference decompresses NibblePack chunks on access for the same
    bandwidth reason). Integers <= 65535 are exact in f32."""
    return vmin + (q.astype(jnp.float32) + 32768.0) * scale


def decode_delta(dv, anchor):
    """Scalar delta decode (ops/narrow.build_narrow_delta): each row is a
    f32 anchor plus i16/i8 per-step value deltas; the prefix sum rebuilds
    the exact value sequence in VMEM (encoder verified |prefix| <= 2^23 so
    every partial sum is integer-exact in f32). Needs the FULL column
    prefix — variants using this are registered full_columns and bypass
    active-column slicing. 1-2 bytes/sample vs the raw 4."""
    return anchor + jnp.cumsum(dv.astype(jnp.float32), axis=1)


def decode_hist(dd, first_d):
    """Hist 2D-delta widen: the tile math (hist_tile_contrib) consumes the
    narrow dd frames directly — its band matmuls and bucket cumsums ARE the
    decode — so the per-tile step is just the i8/i16 -> f32 cast. first_d
    rides as a row operand into the same tile math."""
    return dd.astype(jnp.float32)


register_variant("raw", pallas=decode_raw, xla=decode_raw,
                 row_operands=0, block_dtype="float32",
                 full_columns=False, value_bytes=4)
register_variant("quant16", pallas=decode_quant16, xla=decode_quant16,
                 row_operands=2, block_dtype="int16",
                 full_columns=False, value_bytes=2)
register_variant("delta16", pallas=decode_delta, xla=decode_delta,
                 row_operands=1, block_dtype="int16",
                 full_columns=True, value_bytes=2)
register_variant("delta8", pallas=decode_delta, xla=decode_delta,
                 row_operands=1, block_dtype="int8",
                 full_columns=True, value_bytes=1)
register_variant("hist16", pallas=decode_hist, xla=decode_hist,
                 row_operands=1, block_dtype="int16",
                 full_columns=True, value_bytes=2)
register_variant("hist8", pallas=decode_hist, xla=decode_hist,
                 row_operands=1, block_dtype="int8",
                 full_columns=True, value_bytes=1)
