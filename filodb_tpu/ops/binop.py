"""Binary operators (scalar-vector and vector-vector element math).

Reference: query/.../exec/binaryOp/BinaryOperatorFunction.scala (math + comparison
incl. _bool variants), exec/ScalarOperationMapper.scala.

Prometheus semantics: comparison ops without ``bool`` act as filters — failing
elements disappear (represented here as NaN in the [P, T] matrix, dropped by the
presenter); with ``bool`` they yield 1.0/0.0. ``%`` is fmod, ``^`` is pow.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

MATH_OPS = {"+", "-", "*", "/", "%", "^"}
COMPARISON_OPS = {"==", "!=", "<", "<=", ">", ">="}


def _math(op, a, b):
    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        return a / b
    if op == "%":
        return jnp.fmod(a, b) if not isinstance(a, float) or not isinstance(b, float) else math.fmod(a, b)
    if op == "^":
        return a ** b
    raise ValueError(op)


def _compare(op, a, b):
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    raise ValueError(op)


def scalar_binop(op: str, a: float, b: float, bool_modifier: bool = False) -> float:
    """Pure-scalar fold (both operands literal)."""
    op = op.removesuffix("_bool")
    if op in MATH_OPS:
        if op == "%":
            return math.fmod(a, b) if b != 0 else math.nan
        if op == "/" and b == 0:
            return math.inf if a > 0 else (-math.inf if a < 0 else math.nan)
        return float(_math(op, a, b))
    ok = _compare(op, a, b)
    if bool_modifier:
        return 1.0 if ok else 0.0
    # scalar comparisons without bool are only legal via filter semantics
    return a if ok else math.nan


def apply_scalar_op(op: str, scalar: float, values, scalar_is_lhs: bool):
    """values: [P, T] matrix; returns same shape. NaN propagates (missing stays missing)."""
    bool_mod = op.endswith("_bool")
    op = op.removesuffix("_bool")
    a, b = (scalar, values) if scalar_is_lhs else (values, scalar)
    if op in MATH_OPS:
        return _math(op, a, b).astype(values.dtype)
    ok = _compare(op, a, b)
    if bool_mod:
        return jnp.where(jnp.isnan(values), jnp.nan, jnp.where(ok, 1.0, 0.0))
    return jnp.where(ok, values, jnp.nan)


def apply_vector_op(op: str, lhs, rhs):
    """Aligned [P, T] matrices (join alignment done by the exec layer).
    Comparison keeps the LHS value where true (Prometheus filter semantics)."""
    bool_mod = op.endswith("_bool")
    op = op.removesuffix("_bool")
    if op in MATH_OPS:
        return _math(op, lhs, rhs)
    ok = _compare(op, lhs, rhs)
    if bool_mod:
        missing = jnp.isnan(lhs) | jnp.isnan(rhs)
        return jnp.where(missing, jnp.nan, jnp.where(ok, 1.0, 0.0))
    return jnp.where(ok, lhs, jnp.nan)
