"""Ingestion gateway: Influx line protocol -> shard-routed record containers.

Reference: gateway/src/main/scala/filodb/gateway/GatewayServer.scala:37-60 (Netty
TCP server), conversion/InfluxProtocolParser.scala (line protocol), InputRecord
(field mapping), KafkaContainerSink (shard-hashed container publishing).

TPU-native shape: the gateway is pure host-side; it parses lines, batches per
shard with RecordBuilders (shard = ShardMapper(shard-key-hash, part-key-hash)),
and publishes containers to the per-shard bus.

Throughput shape (the ingest-plane pipeline): each connection parses and
batches into its OWN RecordBuilders — no global lock on the line hot path —
and a shared route memo keyed on the line's measurement+tag prefix caches the
series -> (shard, labels, canonical key) resolution, so a repeated series
costs one dict probe instead of two FNV-1a passes over its key bytes. Only
the publish of a BUILT container serializes — per shard, and per connection
(build + publish under one state-lock hold) so a connection's containers
reach the bus in build order and the store never sees its own samples as
out-of-order. Flush is driven by size (``flush_lines``) OR a time bound
(``flush_interval_ms``) so low-rate shards still land promptly.
"""

from __future__ import annotations

import logging
import select
import socket
import socketserver
import threading
import time

from ..core.cardinality import SeriesQuotaExceeded
from ..core.record import RecordBuilder, fnv1a64
from ..core.schemas import GAUGE, Schema, part_key_of, shard_key_of
from ..parallel.shardmapper import ShardMapper
from ..rules.spec import RULE_LABEL
from ..utils.metrics import (FILODB_GATEWAY_INGESTED_ROWS,
                             FILODB_GATEWAY_PARSE_ERRORS,
                             FILODB_RULES_SPOOF_REJECTS,
                             FILODB_SWALLOWED_ERRORS, registry)
from ..utils.tracing import SPAN_GATEWAY_PUBLISH, span

log = logging.getLogger("filodb_tpu.gateway")


class InfluxParseError(ValueError):
    pass


def _split_unescaped(s: str, sep: str) -> list[str]:
    out, cur, i = [], [], 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            cur.append(s[i + 1])
            i += 2
            continue
        if c == sep:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def _parse_head_fast(head: str) -> tuple[str, dict[str, str]]:
    """``measurement,tag=v,...`` -> (measurement, tags) for escape-free
    lines (shared by parse_influx_line's fast path and the gateway's
    route-memo miss path — one implementation, no drift)."""
    parts = head.split(",")
    tags = {}
    for t in parts[1:]:
        k, eq, v = t.partition("=")
        if not eq:
            raise InfluxParseError(f"bad tag {t!r}")
        tags[k] = v
    return parts[0], tags


def _parse_fields_fast(seg: str) -> dict[str, float]:
    """``k=1.5,k2=3i`` -> field dict for escape-free lines."""
    fields = {}
    for fkv in seg.split(","):
        k, eq, v = fkv.partition("=")
        if not eq:
            raise InfluxParseError(f"bad field {fkv!r}")
        try:
            fields[k] = float(v)
        except ValueError:
            try:
                fields[k] = float(v.rstrip("iu"))
            except ValueError:
                raise InfluxParseError(f"bad field value {v!r}") from None
    return fields


def parse_influx_line(line: str) -> tuple[str, dict[str, str], dict[str, float], int]:
    """``measurement,tag=v,... field=1.5,... timestamp_ns`` -> parts
    (ref: InfluxProtocolParser.parse)."""
    line = line.strip()
    if not line or line.startswith("#"):
        raise InfluxParseError("empty/comment line")
    if "\\" not in line and '"' not in line:
        # fast path (the overwhelmingly common shape): no escapes, no string
        # fields — C-speed str.split instead of the per-character scanner
        segs = line.split(" ")
        if len(segs) < 2 or len(segs) > 3 or not segs[1]:
            raise InfluxParseError(f"bad line: {line!r}")
        measurement, tags = _parse_head_fast(segs[0])
        fields = _parse_fields_fast(segs[1])
        try:
            ts_ns = int(segs[2]) if len(segs) > 2 and segs[2] else 0
        except ValueError:
            raise InfluxParseError(f"bad timestamp {segs[2]!r}") from None
        return measurement, tags, fields, ts_ns
    # escaped/quoted general path
    segs = []
    cur, i = [], 0
    while i < len(line):
        c = line[i]
        if c == "\\" and i + 1 < len(line):
            cur.append(line[i]); cur.append(line[i + 1]); i += 2; continue
        if c == " ":
            segs.append("".join(cur)); cur = []
        else:
            cur.append(c)
        i += 1
    segs.append("".join(cur))
    if len(segs) < 2:
        raise InfluxParseError(f"bad line: {line!r}")
    head = _split_unescaped(segs[0], ",")
    measurement = head[0]
    tags = {}
    for t in head[1:]:
        if "=" not in t:
            raise InfluxParseError(f"bad tag {t!r}")
        k, v = t.split("=", 1)
        tags[k] = v
    fields = {}
    for fkv in _split_unescaped(segs[1], ","):
        if "=" not in fkv:
            raise InfluxParseError(f"bad field {fkv!r}")
        k, v = fkv.split("=", 1)
        v = v.rstrip("iu")
        if v.startswith('"'):
            continue  # string fields are not time series samples
        fields[k] = float(v)
    try:
        ts_ns = int(segs[2]) if len(segs) > 2 and segs[2] else 0
    except ValueError:
        raise InfluxParseError(f"bad timestamp {segs[2]!r}") from None
    return measurement, tags, fields, ts_ns


class _ConnState:
    """Per-connection parse/batch state: builders never contend across
    connections, and each builder's hash-memo stays hot for the connection's
    lifetime. ``lock`` serializes the handler thread against the timed
    flusher (the only other toucher)."""

    __slots__ = ("builders", "counts", "first_add", "lock")

    def __init__(self):
        self.builders: dict[int, RecordBuilder] = {}
        self.counts: dict[int, int] = {}
        self.first_add: dict[int, float | None] = {}
        self.lock = threading.Lock()


class GatewayServer:
    """TCP line-protocol listener publishing shard-batched containers."""

    def __init__(self, publish, num_shards: int = 4, spread: int = 0,
                 schema: Schema = GAUGE, host="127.0.0.1", port=0,
                 flush_lines: int = 1000, flush_interval_ms: int = 500,
                 strict: bool = False, route_memo_max: int = 1 << 18,
                 governor=None, series_known=None):
        """``publish(shard, container)`` delivers a built container (e.g. to a
        FileBus per shard or straight into a memstore). ``flush_lines`` is the
        size bound per (connection, shard) batch; ``flush_interval_ms`` the
        time bound (0 disables the timed flusher). ``strict`` re-raises
        malformed lines instead of counting them (tests); the default counts
        drops in ``filodb_gateway_parse_errors`` and keeps the latest offender
        in ``last_parse_error``.

        ``governor``/``series_known(shard, key) -> bool``: the cardinality
        fast-shed edge (core/cardinality.py). A line that would BIRTH a new
        series for an over-quota tenant sheds here with the typed
        SeriesQuotaExceeded RETRY (strict mode) or a counted drop — but only
        when ``series_known`` proves the series is new; an unprovable case
        passes through and the shard-level limiter stays authoritative, so
        the edge can never drop samples for an existing series."""
        self.publish = publish
        self.mapper = ShardMapper(num_shards, spread)
        self.schema = schema
        self.flush_lines = flush_lines
        self.flush_interval_ms = flush_interval_ms
        self.strict = strict
        self._governor = governor
        self._series_known = series_known
        # optional shutdown hook: stop() calls it after the final builder
        # flush so windowed bus publishers drain their sub-window remainder
        # (no acked-but-unflushed lines on shutdown); owners wire it to
        # e.g. ``lambda: [b.flush_publishes() for b in buses]``
        self.bus_drain = None
        # (measurement+tags line prefix) -> {field name -> (shard, labels,
        # canonical key tuple)}: the hash/dict work dominates the per-line
        # cost, and real scrape traffic repeats series — bounded, reset
        # wholesale under pathological unique-tag floods
        self._routes: dict[str, dict] = {}
        self._memo_lock = threading.Lock()
        self._memo_max = route_memo_max
        self._publish_locks = [threading.Lock() for _ in range(num_shards)]
        self._state = _ConnState()          # direct ingest_line() callers
        self._conn_states: set[_ConnState] = set()
        self._conns: set = set()            # live client sockets (stop sever)
        self._states_lock = threading.Lock()
        self._stop_ev = threading.Event()
        self._flusher: threading.Thread | None = None
        self._serve_thread: threading.Thread | None = None
        self._parse_errors = registry.counter(FILODB_GATEWAY_PARSE_ERRORS)
        # rows, not lines: a line with F fields contributes F samples
        self._rows = registry.counter(FILODB_GATEWAY_INGESTED_ROWS)
        self.last_parse_error: str | None = None
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                st = _ConnState()
                with outer._states_lock:
                    outer._conn_states.add(st)
                    outer._conns.add(self.request)
                try:
                    # chunked reads + ONE decode per block: per-line
                    # readline/decode overhead is measurable at 100k lines/s
                    pending = b""
                    while True:
                        chunk = self.rfile.read1(1 << 16)
                        if not chunk:
                            break
                        pending += chunk
                        if b"\n" not in chunk:
                            continue
                        block, _, pending = pending.rpartition(b"\n")
                        for line in block.decode(errors="replace").split("\n"):
                            if line:
                                outer.ingest_line(line, st)
                    if pending.strip():
                        outer.ingest_line(pending.decode(errors="replace"), st)
                except (InfluxParseError, SeriesQuotaExceeded):
                    # strict mode: the bad line drops the connection — count
                    # the severed connection so operators see the drop rate
                    registry.counter(FILODB_SWALLOWED_ERRORS,
                                     {"site": "gateway-strict-abort"}) \
                        .increment()
                finally:
                    with outer._states_lock:
                        outer._conn_states.discard(st)
                        outer._conns.discard(self.request)
                    outer.flush_state(st)

        self._server = socketserver.ThreadingTCPServer((host, port), Handler)
        self._server.daemon_threads = True

    @property
    def port(self):
        return self._server.server_address[1]

    def start(self):
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="gw-serve")
        self._serve_thread.start()
        if self.flush_interval_ms and self._flusher is None:
            self._flusher = threading.Thread(target=self._flush_loop,
                                             daemon=True, name="gw-flusher")
            self._flusher.start()
        return self

    def stop(self):
        """Deterministic teardown: stop accepting, release the listening
        socket, JOIN both threads (bounded) so a caller that restarts a
        gateway on the same port never races the old acceptor, then FLUSH —
        every connection's pending builders publish, and ``bus_drain``
        drains the windowed publisher — so a stopped gateway holds no
        accepted-but-unpublished lines."""
        self._stop_ev.set()
        self._server.shutdown()
        # the accept-backlog race: a client can connect, send, and close
        # entirely between two serve_forever polls — its lines are TCP-ACKed
        # (accepted, from the client's view) but no handler ever ran, and
        # closing the listener now would drop them. Drain the backlog
        # synchronously: each pending connection runs its handler inline
        # under a bounded read timeout, so a still-open straggler cannot
        # wedge shutdown while fully-sent lines always land.
        while True:
            try:
                ready, _, _ = select.select([self._server.socket], [], [],
                                            0.05)
            except (OSError, ValueError):
                break           # listener already unusable: nothing pending
            if not ready:
                break
            try:
                request, addr = self._server.socket.accept()
            except OSError:
                break
            request.settimeout(1.0)
            try:
                self._server.finish_request(request, addr)
            except Exception:  # noqa: BLE001 — a straggler's read timeout or
                # reset must not abort shutdown; whatever it sent in time
                # already flushed via the handler's exit path
                log.warning("gateway backlog drain handler failed",
                            exc_info=True)
            finally:
                self._server.shutdown_request(request)
        self._server.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=3)
            self._serve_thread = None
        if self._flusher is not None:
            self._flusher.join(timeout=3)
            self._flusher = None
        # connection handlers flush their own state on exit: give in-flight
        # bursts a short grace, then SEVER lingering client sockets (an
        # idle keep-alive connection would otherwise hold its handler in
        # read() forever — nothing may ingest after stop() returns) and
        # wait for the unblocked handlers to run their exit flush
        self._wait_states_drained(1.0)
        with self._states_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass    # racing close: the connection is already gone
        self._wait_states_drained(3.0)
        self.flush()
        if self.bus_drain is not None:
            try:
                self.bus_drain()
            except Exception:  # noqa: BLE001 — shutdown must complete; the
                # drain fault is logged, not fatal (the bus owner's own
                # close path retries)
                log.warning("gateway bus drain failed on stop", exc_info=True)

    def _wait_states_drained(self, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._states_lock:
                if not self._conn_states:
                    return
            time.sleep(0.01)

    def _all_states(self) -> list[_ConnState]:
        with self._states_lock:
            return [self._state, *self._conn_states]

    def _flush_loop(self) -> None:
        """Time-bound flush: a low-rate shard's rows land within roughly one
        interval instead of waiting out ``flush_lines``."""
        iv = self.flush_interval_ms / 1000.0
        while not self._stop_ev.wait(iv / 2):
            now = time.monotonic()
            for st in self._all_states():
                try:
                    self._flush_ripe(st, now, iv)
                except Exception:  # noqa: BLE001 — ANY publish-callback fault
                    # must not kill the timed flusher for the gateway's
                    # lifetime; the size bound and the next tick still flush
                    log.warning("gateway timed flush failed", exc_info=True)

    def _flush_ripe(self, st: _ConnState, now: float = 0.0,
                    min_age_s: float = 0.0) -> None:
        """Build + publish pending shards (all when ``min_age_s`` <= 0, else
        only those whose oldest pending row is at least that old). Build and
        publish stay under ONE state lock hold: a built container must reach
        the bus before the state's next build for the same shard, or the
        store drops the older container's rows as out-of-order."""
        with st.lock:
            for shard, b in st.builders.items():
                if not st.counts.get(shard):
                    continue
                if min_age_s > 0:
                    t0 = st.first_add.get(shard)
                    if t0 is None or now - t0 < min_age_s:
                        continue
                container = b.build()
                # reset BEFORE publish: a publish fault must not leave a
                # stale count over the drained builder (the next flush would
                # emit an empty container); the fault drops this container's
                # rows — the gateway edge is lossy on publish failure
                st.counts[shard] = 0
                st.first_add[shard] = None
                self._publish(shard, container)

    def _publish(self, shard: int, container) -> None:
        # publish serializes per shard (and per connection via the caller's
        # state lock) — parse/batch of other connections proceeds
        # concurrently. The span is per built CONTAINER (≤ flush_lines
        # rows), never per line: it roots the ingest trace that the
        # windowed broker publish continues over PUBLISH_BATCH when the
        # window fills inside this call
        with span(SPAN_GATEWAY_PUBLISH, shard=shard, rows=len(container)):
            with self._publish_locks[shard]:
                self.publish(shard, container)
        self._rows.increment(len(container))

    def _resolve_route(self, head: str | None, measurement: str | None,
                       tags: dict | None, fname: str):
        """(shard, labels, canonical-key) for one (series, field) — the slow
        path behind the route memo."""
        if measurement is None:
            measurement, tags = _parse_head_fast(head)
        metric = measurement if fname == "value" else f"{measurement}_{fname}"
        labels = dict(tags)
        labels["_metric_"] = metric
        labels.setdefault("_ws_", "default")
        labels.setdefault("_ns_", "default")
        opts = self.schema.options
        shard = self.mapper.shard_of(
            fnv1a64(shard_key_of(labels, opts)) & 0xFFFFFFFF,
            fnv1a64(part_key_of(labels, opts)))
        if self._governor is not None:
            # a memo miss is the only place a NEW series can first appear:
            # shed it typed (RETRY) when the tenant is over quota AND the
            # series is provably unknown — never on an unprovable probe
            tenant = self._governor.tenant_of(labels)
            if self._governor.over_limit(tenant) \
                    and self._series_known is not None \
                    and not self._series_known(shard, labels):
                self._governor.count_shed("gateway", tenant)
                raise SeriesQuotaExceeded(
                    tenant, retry_after_s=self._governor.retry_after_s)
        route = (shard, labels, tuple(sorted(labels.items())))
        if head is not None:
            with self._memo_lock:
                if len(self._routes) >= self._memo_max \
                        and head not in self._routes:
                    self._routes.clear()
                self._routes.setdefault(head, {})[fname] = route
        return route

    def ingest_line(self, line: str, state: _ConnState | None = None) -> None:
        st = state if state is not None else self._state
        line = line.strip()
        if not line:
            return
        head = routes = None
        if "\\" not in line and '"' not in line:
            sp = line.find(" ")
            if sp > 0:
                head = line[:sp]
                routes = self._routes.get(head)
        try:
            if routes is not None:
                # memo hit: only fields + timestamp still need parsing —
                # slices off the already-located head, no split list
                rest = line[sp + 1:]
                sp2 = rest.find(" ")
                if sp2 < 0:
                    fseg, tseg = rest, ""
                else:
                    fseg, tseg = rest[:sp2], rest[sp2 + 1:]
                    if not fseg or " " in tseg:
                        raise InfluxParseError(f"bad line: {line!r}")
                fields = _parse_fields_fast(fseg)
                try:
                    ts_ns = int(tseg) if tseg else 0
                except ValueError:
                    raise InfluxParseError(f"bad timestamp {tseg!r}") from None
                measurement = tags = None
            else:
                measurement, tags, fields, ts_ns = parse_influx_line(line)
                if RULE_LABEL in tags:
                    # reserved provenance tag: only the rules subsystem's
                    # deterministic-pub-id publisher may write it (strict
                    # re-raises; otherwise a counted drop like any bad
                    # line). A spoofed head never reaches the route memo,
                    # so every such line funnels through this parse path.
                    registry.counter(FILODB_RULES_SPOOF_REJECTS,
                                     {"site": "gateway"}).increment()
                    raise InfluxParseError(
                        f"tag {RULE_LABEL!r} is reserved for "
                        "recording-rule output and cannot be ingested "
                        "externally")
        except InfluxParseError:
            if self.strict:
                raise
            self._parse_errors.increment()
            self.last_parse_error = line[:256]   # one sampled offender
            return
        ts_ms = ts_ns // 1_000_000 if ts_ns else 0
        with st.lock:
            for fname, fval in fields.items():
                route = None if routes is None else routes.get(fname)
                if route is None:
                    try:
                        route = self._resolve_route(head, measurement, tags,
                                                    fname)
                    except SeriesQuotaExceeded:
                        if self.strict:
                            raise       # typed RETRY to the caller
                        continue        # counted; only the NEW series drops
                shard, labels, key = route
                b = st.builders.get(shard)
                if b is None:
                    b = st.builders[shard] = RecordBuilder(self.schema)
                    st.counts[shard] = 0
                b.add_interned(key, labels, ts_ms, fval)
                n = st.counts[shard] + 1
                if n == 1:
                    st.first_add[shard] = time.monotonic()
                if n >= self.flush_lines:
                    container = b.build()
                    # reset before publish (see _flush_ripe), then publish
                    # INSIDE the state lock: per-series publish order must
                    # match build order
                    n = 0
                    st.counts[shard] = 0
                    st.first_add[shard] = None
                    self._publish(shard, container)
                st.counts[shard] = n

    def flush_state(self, st: _ConnState) -> None:
        self._flush_ripe(st)

    def flush(self) -> None:
        """Flush every connection's pending batches (and the direct-call
        state) — shutdown / test barrier."""
        for st in self._all_states():
            self._flush_ripe(st)
