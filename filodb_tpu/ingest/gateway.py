"""Ingestion gateway: Influx line protocol -> shard-routed record containers.

Reference: gateway/src/main/scala/filodb/gateway/GatewayServer.scala:37-60 (Netty
TCP server), conversion/InfluxProtocolParser.scala (line protocol), InputRecord
(field mapping), KafkaContainerSink (shard-hashed container publishing).

TPU-native shape: the gateway is pure host-side; it parses lines, batches per
shard with RecordBuilders (shard = ShardMapper(shard-key-hash, part-key-hash)),
and publishes containers to the per-shard bus.
"""

from __future__ import annotations

import socketserver
import threading

from ..core.record import RecordBuilder, fnv1a64
from ..core.schemas import GAUGE, Schema, part_key_of, shard_key_of
from ..parallel.shardmapper import ShardMapper


class InfluxParseError(ValueError):
    pass


def _split_unescaped(s: str, sep: str) -> list[str]:
    out, cur, i = [], [], 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            cur.append(s[i + 1])
            i += 2
            continue
        if c == sep:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def parse_influx_line(line: str) -> tuple[str, dict[str, str], dict[str, float], int]:
    """``measurement,tag=v,... field=1.5,... timestamp_ns`` -> parts
    (ref: InfluxProtocolParser.parse)."""
    line = line.strip()
    if not line or line.startswith("#"):
        raise InfluxParseError("empty/comment line")
    if "\\" not in line and '"' not in line:
        # fast path (the overwhelmingly common shape): no escapes, no string
        # fields — C-speed str.split instead of the per-character scanner
        segs = line.split(" ")
        if len(segs) < 2 or len(segs) > 3 or not segs[1]:
            raise InfluxParseError(f"bad line: {line!r}")
        head = segs[0].split(",")
        measurement = head[0]
        tags = {}
        for t in head[1:]:
            k, eq, v = t.partition("=")
            if not eq:
                raise InfluxParseError(f"bad tag {t!r}")
            tags[k] = v
        fields = {}
        for fkv in segs[1].split(","):
            k, eq, v = fkv.partition("=")
            if not eq:
                raise InfluxParseError(f"bad field {fkv!r}")
            try:
                fields[k] = float(v)
            except ValueError:
                try:
                    fields[k] = float(v.rstrip("iu"))
                except ValueError:
                    raise InfluxParseError(f"bad field value {v!r}") from None
        try:
            ts_ns = int(segs[2]) if len(segs) > 2 and segs[2] else 0
        except ValueError:
            raise InfluxParseError(f"bad timestamp {segs[2]!r}") from None
        return measurement, tags, fields, ts_ns
    # escaped/quoted general path
    segs = []
    cur, i = [], 0
    while i < len(line):
        c = line[i]
        if c == "\\" and i + 1 < len(line):
            cur.append(line[i]); cur.append(line[i + 1]); i += 2; continue
        if c == " ":
            segs.append("".join(cur)); cur = []
        else:
            cur.append(c)
        i += 1
    segs.append("".join(cur))
    if len(segs) < 2:
        raise InfluxParseError(f"bad line: {line!r}")
    head = _split_unescaped(segs[0], ",")
    measurement = head[0]
    tags = {}
    for t in head[1:]:
        if "=" not in t:
            raise InfluxParseError(f"bad tag {t!r}")
        k, v = t.split("=", 1)
        tags[k] = v
    fields = {}
    for fkv in _split_unescaped(segs[1], ","):
        if "=" not in fkv:
            raise InfluxParseError(f"bad field {fkv!r}")
        k, v = fkv.split("=", 1)
        v = v.rstrip("iu")
        if v.startswith('"'):
            continue  # string fields are not time series samples
        fields[k] = float(v)
    try:
        ts_ns = int(segs[2]) if len(segs) > 2 and segs[2] else 0
    except ValueError:
        raise InfluxParseError(f"bad timestamp {segs[2]!r}") from None
    return measurement, tags, fields, ts_ns


class GatewayServer:
    """TCP line-protocol listener publishing shard-batched containers."""

    def __init__(self, publish, num_shards: int = 4, spread: int = 0,
                 schema: Schema = GAUGE, host="127.0.0.1", port=0,
                 flush_lines: int = 1000):
        """``publish(shard, container)`` delivers a built container (e.g. to a
        FileBus per shard or straight into a memstore)."""
        self.publish = publish
        self.mapper = ShardMapper(num_shards, spread)
        self.schema = schema
        self.flush_lines = flush_lines
        self._builders = {}
        self._counts = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for raw in self.rfile:
                    line = raw.decode(errors="replace")
                    if line.strip():
                        try:
                            outer.ingest_line(line)
                        except InfluxParseError:
                            pass
                outer.flush()

        self._server = socketserver.ThreadingTCPServer((host, port), Handler)
        self._server.daemon_threads = True

    @property
    def port(self):
        return self._server.server_address[1]

    def start(self):
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self

    def stop(self):
        self._server.shutdown()

    def ingest_line(self, line: str) -> None:
        measurement, tags, fields, ts_ns = parse_influx_line(line)
        ts_ms = ts_ns // 1_000_000 if ts_ns else 0
        with self._lock:
            for fname, fval in fields.items():
                metric = measurement if fname == "value" else f"{measurement}_{fname}"
                labels = dict(tags)
                labels["_metric_"] = metric
                labels.setdefault("_ws_", "default")
                labels.setdefault("_ns_", "default")
                opts = self.schema.options
                shard = self.mapper.shard_of(
                    fnv1a64(shard_key_of(labels, opts)) & 0xFFFFFFFF,
                    fnv1a64(part_key_of(labels, opts)))
                b = self._builders.get(shard)
                if b is None:
                    b = self._builders[shard] = RecordBuilder(self.schema)
                    self._counts[shard] = 0
                b.add(labels, ts_ms, fval)
                self._counts[shard] += 1
                if self._counts[shard] >= self.flush_lines:
                    self.publish(shard, b.build())
                    self._counts[shard] = 0

    def flush(self) -> None:
        with self._lock:
            for shard, b in self._builders.items():
                if self._counts.get(shard):
                    self.publish(shard, b.build())
                    self._counts[shard] = 0
