"""TCP log broker — the Kafka-broker-equivalent data plane.

Reference: kafka/src/main/scala/filodb/kafka/KafkaIngestionStream.scala (one
shard == one partition; consumers seek to the checkpointed offset and replay)
and PartitionStrategy (shard -> partition routing). The reference outsources
the broker to Kafka; here the broker itself is part of the framework: a
threaded TCP server fronting one durable append-only log per partition (the
same offset-addressed frame format as FileBus, so logs are interchangeable
between in-process and brokered deployments).

Wire protocol (all little-endian, one request/response per round trip):

  request  = op:u8  partition:u32  offset:u64  payload_len:u32  payload
  response = status:u8  offset:u64  payload_len:u32  payload

  ops: PUBLISH (payload=container bytes; the offset field carries a random
                nonzero publish id — the broker remembers recent ids per
                partition and returns the original offset on a retry instead
                of appending a duplicate; returns assigned offset)
       FETCH   (offset=from_offset; payload_len field carries max_frames;
                returns concatenated [offset:u64 len:u32 bytes] entries)
       END     (returns the partition's end offset)

`BrokerBus` is a drop-in for FileBus (publish/consume/end_offset), so the
standalone server's IngestionConsumer works unchanged against a remote broker.
"""

from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading
from typing import Iterator

from ..core.record import RecordContainer
from .bus import FileBus

_REQ = struct.Struct("<B I Q I")
_RESP = struct.Struct("<B Q I")
_ENTRY = struct.Struct("<Q I")

OP_PUBLISH, OP_FETCH, OP_END = 1, 2, 3
ST_OK, ST_ERR = 0, 1

_MAX_PAYLOAD = 64 << 20     # refuse absurd frames instead of OOMing


from ..utils.netio import recv_exact as _recv_exact  # noqa: E402 - shared framing helper


class BrokerServer:
    """Hosts partitions 0..num_partitions-1, each a durable FileBus log."""

    def __init__(self, data_dir: str, num_partitions: int,
                 host: str = "127.0.0.1", port: int = 0):
        os.makedirs(data_dir, exist_ok=True)
        self._parts = [FileBus(os.path.join(data_dir, f"partition{p}.log"))
                       for p in range(num_partitions)]
        # publish idempotence: recent publish-id -> offset per partition, so a
        # client retry after a lost response doesn't append a duplicate frame
        self._recent_ids: list[dict[int, int]] = [{} for _ in range(num_partitions)]
        self._publish_locks = [threading.Lock() for _ in range(num_partitions)]
        # live client connections, so stop() actually severs them (handler
        # threads would otherwise keep serving a "stopped" broker)
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with outer._conns_lock:
                    outer._conns.add(self.request)
                try:
                    while True:
                        hdr = _recv_exact(self.request, _REQ.size)
                        op, part, offset, plen = _REQ.unpack(hdr)
                        if plen > _MAX_PAYLOAD:
                            raise ValueError(f"frame too large: {plen}")
                        payload = _recv_exact(self.request, plen) \
                            if op == OP_PUBLISH and plen else b""
                        self.request.sendall(outer._serve(op, part, offset,
                                                          plen, payload))
                except (ConnectionError, OSError):
                    pass    # client went away or the broker is stopping
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(self.request)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self._thread: threading.Thread | None = None

    def _serve(self, op: int, part: int, offset: int, plen: int,
               payload: bytes) -> bytes:
        try:
            if not 0 <= part < len(self._parts):
                raise ValueError(f"no partition {part}")
            bus = self._parts[part]
            if op == OP_PUBLISH:
                pub_id = offset                 # request offset field = publish id
                with self._publish_locks[part]:
                    recent = self._recent_ids[part]
                    if pub_id and pub_id in recent:
                        return _RESP.pack(ST_OK, recent[pub_id], 0)
                    off = bus.publish_bytes(payload)
                    if pub_id:
                        recent[pub_id] = off
                        if len(recent) > 4096:  # bounded window of retry-able ids
                            for k in list(recent)[:2048]:
                                del recent[k]
                return _RESP.pack(ST_OK, off, 0)
            if op == OP_FETCH:
                max_frames = plen or 1024
                out = bytearray()
                n = 0
                for off, frame in bus.frames_from(offset):
                    out += _ENTRY.pack(off, len(frame))
                    out += frame
                    n += 1
                    if n >= max_frames:
                        break
                return _RESP.pack(ST_OK, bus.end_offset, len(out)) + bytes(out)
            if op == OP_END:
                return _RESP.pack(ST_OK, bus.end_offset, 0)
            raise ValueError(f"unknown op {op}")
        except Exception as e:  # noqa: BLE001 — delivered to the client
            msg = str(e).encode()[:1024]
            return _RESP.pack(ST_ERR, 0, len(msg)) + msg

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def num_partitions(self) -> int:
        return len(self._parts)

    def start(self) -> "BrokerServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="filo-broker")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            c.close()


class BrokerBus:
    """Client for one broker partition; drop-in for FileBus."""

    def __init__(self, addr: str, partition: int):
        host, _, port = addr.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.partition = partition
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()   # one in-flight request per client

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self._addr, timeout=30)
        return self._sock

    def _request(self, op: int, offset: int = 0, plen: int = 0,
                 payload: bytes = b"") -> tuple[int, bytes]:
        with self._lock:
            for attempt in (0, 1):      # one reconnect on a stale connection
                try:
                    s = self._conn()
                    s.sendall(_REQ.pack(op, self.partition, offset, plen) + payload)
                    st, off, rlen = _RESP.unpack(_recv_exact(s, _RESP.size))
                    body = _recv_exact(s, rlen) if rlen else b""
                    break
                except (ConnectionError, OSError):
                    self.close()
                    if attempt:
                        raise
        if st == ST_ERR:
            raise RuntimeError(f"broker error: {body.decode(errors='replace')}")
        return off, body

    def publish(self, container: RecordContainer) -> int:
        payload = container.to_bytes()
        # stable random id across the internal reconnect retry: if the broker
        # committed the append but the response was lost, the retry is a no-op
        pub_id = int.from_bytes(os.urandom(8), "little") | 1
        off, _ = self._request(OP_PUBLISH, offset=pub_id,
                               plen=len(payload), payload=payload)
        return off

    def consume(self, schemas, from_offset: int = 0) -> Iterator[tuple[int, RecordContainer]]:
        """Replay containers from ``from_offset`` up to the end offset observed
        on the FIRST fetch (ref: Kafka seek + poll). The snapshot matters: a
        poll-loop consumer must regain control between polls to flush/
        checkpoint/purge, so under sustained publish load this terminates
        instead of chasing the moving end forever (FileBus.consume contract)."""
        next_off = from_offset
        end: int | None = None
        while True:
            resp_end, body = self._request(OP_FETCH, offset=next_off)
            if end is None:
                end = resp_end
            pos = 0
            got = 0
            while pos < len(body):
                off, ln = _ENTRY.unpack_from(body, pos)
                pos += _ENTRY.size
                if off >= end:
                    return
                yield off, RecordContainer.from_bytes(body[pos:pos + ln], schemas)
                pos += ln
                next_off = off + 1
                got += 1
            if not got or next_off >= end:
                return

    @property
    def end_offset(self) -> int:
        off, _ = self._request(OP_END)
        return off

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
