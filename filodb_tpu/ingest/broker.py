"""TCP log broker — the Kafka-broker-equivalent data plane.

Reference: kafka/src/main/scala/filodb/kafka/KafkaIngestionStream.scala (one
shard == one partition; consumers seek to the checkpointed offset and replay)
and PartitionStrategy (shard -> partition routing). The reference outsources
the broker to Kafka; here the broker itself is part of the framework: a
threaded TCP server fronting one durable append-only log per partition (the
same offset-addressed frame format as FileBus, so logs are interchangeable
between in-process and brokered deployments).

Wire protocol (all little-endian, one request/response per round trip):

  request  = op:u8  partition:u32  offset:u64  payload_len:u32  payload
  response = status:u8  offset:u64  payload_len:u32  payload

  ops: PUBLISH (payload=container bytes; the offset field carries a random
                nonzero publish id — the broker remembers recent ids per
                partition and returns the original offset on a retry instead
                of appending a duplicate; returns assigned offset)
       FETCH   (offset=from_offset; payload_len field carries max_frames;
                returns concatenated [offset:u64 len:u32 bytes] entries)
       END     (returns the partition's end offset)
       PUBLISH_BATCH (payload=concatenated [pub_id:u64 len:u32 bytes] frames —
                MANY publishes per round trip, the publish-side mirror of
                FETCH's response batching; the offset field carries the frame
                count. Per-frame publish ids keep retries duplicate-free
                exactly like PUBLISH. Response payload: one u64 assigned
                offset per frame, in request order)
       ops >= 16 are the replication stream (ingest/replication.py:
                OP_REPLICATE — leader->follower CRC-checked frame batches).

  statuses: OK, ERR (payload = error message), RETRY (backpressure shed:
  quorum stall or per-partition queue overload; the offset field carries a
  retry-after hint in ms that clients honor as their backoff floor).

`BrokerBus` is a drop-in for FileBus (publish/consume/end_offset), so the
standalone server's IngestionConsumer works unchanged against a remote broker.
Its windowed publisher (`publish_async`/`publish_batch`/`flush_publishes`)
pipelines PUBLISH_BATCH requests: F frames with window W cost at most
ceil(F/W) round trips, and all of a drain's requests are on the wire before
the first response is read.

Replication + failure handling (ingest/replication.py): a BrokerServer
given a ``peers`` list replicates each partition to R nodes and acks
publishes only when >= ``min_insync`` replicas hold the frames (ST_RETRY
sheds otherwise — quorum-stall backpressure; the per-partition admission
cap sheds overload the same way). BrokerBus accepts the whole replica
address LIST: on a dead leader it re-ranks survivors by replication
watermark (highest wins, lowest index breaks ties — every publisher picks
the same survivor) and replays the unacked window with the SAME publish
ids, which the new leader resolves from its replicated id journal — the
handoff is duplicate-free end to end. Client retries use jittered
exponential backoff (``filodb_ingest_retries``); a persistently-dead
partition trips the PR-2 PeerBreaker so publishers shed fast instead of
paying connect timeouts forever.
"""

from __future__ import annotations

import logging
import os
import random
import socket
import socketserver
import struct
import threading
import time
from typing import Iterator

from ..core.record import RecordContainer
from ..utils.metrics import (FILODB_CLUSTER_FENCED_REJECTS,
                             FILODB_CLUSTER_REJOIN_TRUNCATED,
                             FILODB_INGEST_FAILOVERS, FILODB_INGEST_RETRIES,
                             FILODB_INGEST_PUBLISH_LATENCY_MS,
                             FILODB_INGEST_PUBLISH_SHED, registry)
from ..utils.tracing import (SPAN_BROKER_APPEND, SPAN_CLUSTER_REJOIN,
                             SPAN_INGEST_PUBLISH, span, tracer)
from .bus import FileBus

log = logging.getLogger("filodb_tpu.broker")

_REQ = struct.Struct("<B I Q I")
_RESP = struct.Struct("<B Q I")
_ENTRY = struct.Struct("<Q I")

OP_PUBLISH, OP_FETCH, OP_END, OP_PUBLISH_BATCH = 1, 2, 3, 4
ST_OK, ST_ERR, ST_RETRY = 0, 1, 2

# trace-context block riding PUBLISH_BATCH (and OP_REPLICATE) payloads:
# ``u16 len + JSON context``, stripped server-side BEFORE frame parsing —
# durable log frames never carry it. pack/unpack are the one encode/decode
# pair; filolint's wire-trace-parity rule fails tier-1 when either the
# BrokerBus sender or the _serve receiver stops calling its side.
_TRACE_HDR = struct.Struct("<H")


def pack_trace_hdr(ctx: dict | None) -> bytes:
    import json
    blob = json.dumps(ctx, separators=(",", ":")).encode() if ctx else b""
    return _TRACE_HDR.pack(len(blob)) + blob


def unpack_trace_hdr(payload: bytes) -> tuple[dict | None, bytes]:
    """(context or None, payload with the block stripped). Malformed blocks
    degrade to no-context — a trace must never fail a publish."""
    import json
    try:
        (ln,) = _TRACE_HDR.unpack_from(payload, 0)
        body = payload[_TRACE_HDR.size:]
        if ln > len(body):
            return None, payload        # not a trace block: pass through
        ctx = json.loads(body[:ln]) if ln else None
        return (ctx if isinstance(ctx, dict) else None), body[ln:]
    except (struct.error, ValueError):
        return None, payload


class BrokerRetry(RuntimeError):
    """The broker shed the publish (quorum stall or queue-depth overload)
    and the client exhausted its backoff budget. Carries the server's
    retry-after hint; the HTTP layer maps this to 429 + Retry-After."""

    def __init__(self, retry_after_s: float = 1.0):
        super().__init__(
            f"broker backpressure: retry after {retry_after_s:.3f}s")
        self.retry_after_s = float(retry_after_s)

_MAX_PAYLOAD = 64 << 20     # refuse absurd frames instead of OOMing
_RECENT_IDS_MAX = 4096      # retry-able publish ids remembered per partition
_MAX_BATCH_BYTES = 8 << 20  # per-PUBLISH_BATCH payload bound (well under
                            # _MAX_PAYLOAD, so the broker never severs a
                            # batched connection for size)
# unacked frames per pipelined group: half the broker's id window, so a full
# group replay after a lost connection still resolves every id (no silent
# duplicates), and the unread-response backlog stays far below socket buffers
_MAX_UNACKED_FRAMES = _RECENT_IDS_MAX // 2


def _remember_id(recent: dict[int, int], pub_id: int, off: int,
                 limit: int) -> None:
    """Record a publish id -> offset (caller holds the partition's publish
    lock). Eviction is strictly oldest-first, one at a time — dicts iterate
    in insertion order and retry hits re-insert, so a recently retried id is
    never the one evicted."""
    recent[pub_id] = off
    while len(recent) > limit:
        recent.pop(next(iter(recent)))


def _recall_id(recent: dict[int, int], pub_id: int) -> int | None:
    """Offset of an already-seen publish id, refreshing its recency (caller
    holds the partition's publish lock)."""
    off = recent.pop(pub_id, None)
    if off is not None:
        recent[pub_id] = off
    return off


from ..utils.netio import recv_exact as _recv_exact  # noqa: E402 - shared framing helper


class BrokerServer:
    """Hosts partitions 0..num_partitions-1, each a durable FileBus log."""

    def __init__(self, data_dir: str, num_partitions: int,
                 host: str = "127.0.0.1", port: int = 0,
                 recent_ids_max: int = _RECENT_IDS_MAX,
                 peers: list[str] | None = None, node_index: int = 0,
                 replication: int = 1, min_insync: int = 1,
                 max_queue: int = 256, fault_plan=None,
                 epoch_fencing: bool = False):
        """``recent_ids_max`` below the default weakens the windowed
        publisher's replay idempotence: BrokerBus bounds a pipelined group to
        ``_RECENT_IDS_MAX // 2`` unacked frames on the assumption the server
        remembers at least the module default — shrink it only in tests that
        exercise eviction itself.

        ``peers``/``node_index``/``replication``/``min_insync`` enable the
        replicated tier (ingest/replication.py): partitions replicate to R
        of the peer nodes and publishes ack only at >= min_insync in-sync
        replicas. ``max_queue`` caps concurrent in-flight publishes per
        partition (overload sheds ST_RETRY). ``fault_plan`` wires the
        deterministic fault-injection hooks (ingest/faults.py).

        ``epoch_fencing`` enables monotonic leadership epochs
        (cluster/epoch.py, persisted in ``data_dir``): a publish or
        replication batch below the partition's current epoch is refused,
        so a deposed leader can never ack after deposition, and
        ``start()`` runs the REJOIN repair (truncate a divergent tail,
        catch up from the current leader) before serving."""
        from .replication import PubIdJournal, Replicator
        os.makedirs(data_dir, exist_ok=True)
        self.peers = list(peers or [])
        self.node_index = int(node_index)
        self.epochs = None
        if epoch_fencing:
            from ..cluster.epoch import PartitionEpochs
            self.epochs = PartitionEpochs(os.path.join(data_dir,
                                                       "epochs.json"))
        self._parts = [FileBus(os.path.join(data_dir, f"partition{p}.log"))
                       for p in range(num_partitions)]
        # publish idempotence: recent publish-id -> offset per partition, so a
        # client retry after a lost response doesn't append a duplicate frame
        self._recent_ids: list[dict[int, int]] = [{} for _ in range(num_partitions)]
        self._recent_ids_max = int(recent_ids_max)
        self._publish_locks = [threading.Lock() for _ in range(num_partitions)]
        # durable offset -> pub-id journal per partition: restart-proof
        # idempotence, replication id carry-over, and the soak audit surface
        self._journals = [PubIdJournal(os.path.join(data_dir,
                                                    f"partition{p}.pubids"))
                          for p in range(num_partitions)]
        for p in range(num_partitions):
            self._journals[p].seed_recent(self._recent_ids[p],
                                          self._recent_ids_max)
        self.fault_plan = fault_plan
        self._repl: Replicator | None = None
        if peers and len(peers) > 1 and replication > 1:
            self._repl = Replicator(self, peers, node_index, replication,
                                    min_insync=min_insync,
                                    fault_plan=fault_plan)
        # per-partition admission: concurrent in-flight publishes above
        # max_queue shed with ST_RETRY instead of queueing unboundedly
        self._max_queue = max(1, int(max_queue))
        self._inflight = [0] * num_partitions
        self._admit_lock = threading.Lock()
        self._shed = registry.counter(FILODB_INGEST_PUBLISH_SHED)
        self._stopped = False
        # live client connections, so stop() actually severs them (handler
        # threads would otherwise keep serving a "stopped" broker)
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with outer._conns_lock:
                    outer._conns.add(self.request)
                try:
                    while True:
                        hdr = _recv_exact(self.request, _REQ.size)
                        op, part, offset, plen = _REQ.unpack(hdr)
                        # replication ops (>= 16) get header headroom: a
                        # max-size accepted publish frame must still fit
                        # its OP_REPLICATE envelope (24B/frame; batches
                        # are byte-chunked leader-side)
                        limit = _MAX_PAYLOAD + (64 << 10) if op >= 16 \
                            else _MAX_PAYLOAD
                        if plen > limit:
                            raise ValueError(f"frame too large: {plen}")
                        # FETCH/END overload the length field as a count —
                        # every other op carries a real payload
                        payload = _recv_exact(self.request, plen) \
                            if op not in (OP_FETCH, OP_END) and plen \
                            else b""
                        resp = outer._serve(op, part, offset, plen, payload)
                        if resp is None:
                            break       # fault injection: sever, no reply
                        self.request.sendall(resp)
                except (ConnectionError, OSError):
                    pass    # client went away or the broker is stopping
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(self.request)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self._thread: threading.Thread | None = None

    def _serve(self, op: int, part: int, offset: int, plen: int,
               payload: bytes) -> bytes | None:
        from ..cluster.gossip import CLUSTER_OPS, serve_cluster
        from .replication import OP_REPLICATE, serve_replication
        try:
            if op in CLUSTER_OPS:
                # membership/epoch/sync control plane (cluster/gossip.py);
                # partition bounds are checked per-op there (OP_EPOCH_* may
                # address partitions this node only replicates)
                return serve_cluster(self, op, part, payload)
            if not 0 <= part < len(self._parts):
                raise ValueError(f"no partition {part}")
            bus = self._parts[part]
            if op in (OP_PUBLISH, OP_PUBLISH_BATCH):
                tctx = None
                if op == OP_PUBLISH_BATCH:
                    # trace block stripped BEFORE frame parsing: the spans
                    # this append records join the publisher's trace, and
                    # the durable log never sees the block
                    tctx, payload = unpack_trace_hdr(payload)
                if not self._admit(part):
                    self._shed.increment()
                    return _RESP.pack(ST_RETRY, 100, 0)   # retry hint (ms)
                try:
                    with tracer.activate(tctx), \
                            span(SPAN_BROKER_APPEND, partition=part,
                                 broker=self.port):
                        resp = self._serve_publish(op, part, offset,
                                                   payload, bus)
                    # fault hook INSIDE the admission slot: a delayed
                    # response occupies partition capacity exactly like a
                    # slow disk/replica would
                    return self._fault_response(op, part, resp)
                finally:
                    self._release(part)
            if op == OP_REPLICATE:
                return serve_replication(self, op, part, payload)
            if op == OP_FETCH:
                max_frames = plen or 1024
                out = bytearray()
                n = 0
                for off, frame in bus.frames_from(offset):
                    out += _ENTRY.pack(off, len(frame))
                    out += frame
                    n += 1
                    if n >= max_frames:
                        break
                return _RESP.pack(ST_OK, bus.end_offset, len(out)) + bytes(out)
            if op == OP_END:
                return _RESP.pack(ST_OK, bus.end_offset, 0)
            raise ValueError(f"unknown op {op}")
        except Exception as e:  # noqa: BLE001 — delivered to the client
            msg = str(e).encode()[:1024]
            return _RESP.pack(ST_ERR, 0, len(msg)) + msg

    def _serve_publish(self, op: int, part: int, offset: int,
                       payload: bytes, bus: FileBus) -> bytes:
        """PUBLISH / PUBLISH_BATCH under the partition publish lock:
        recall-or-append with idempotent ids, journal fresh pub-ids, then
        replicate to quorum before acking."""
        jrnl = self._journals[part]
        with self._publish_locks[part]:
            fenced = self._fence_resp(part)
            if fenced is not None:
                return fenced
            recent = self._recent_ids[part]
            if op == OP_PUBLISH:
                pub_id = offset             # request offset field = publish id
                off = _recall_id(recent, pub_id) if pub_id else None
                appended = []
                if off is None:
                    off = bus.publish_bytes(payload)
                    if pub_id:
                        jrnl.append(off, pub_id)
                        _remember_id(recent, pub_id, off,
                                     self._recent_ids_max)
                    appended = [(off, pub_id, payload)]
                resp = _RESP.pack(ST_OK, off, 0)
            else:
                entries = []                # (pub_id, frame bytes)
                pos = 0
                while pos < len(payload):
                    pid, ln = _ENTRY.unpack_from(payload, pos)
                    pos += _ENTRY.size
                    entries.append((pid, payload[pos:pos + ln]))
                    pos += ln
                offs = [0] * len(entries)
                fresh: list[int] = []       # indexes needing an append
                first_idx: dict[int, int] = {}
                alias: dict[int, int] = {}  # in-batch duplicate ids
                for i, (pid, _frame) in enumerate(entries):
                    off = _recall_id(recent, pid) if pid else None
                    if off is not None:
                        offs[i] = off
                    elif pid and pid in first_idx:
                        alias[i] = first_idx[pid]
                    else:
                        fresh.append(i)
                        if pid:
                            first_idx[pid] = i
                # one open+write for the whole batch — per-frame appends
                # would re-open the log file once per frame
                new_offs = bus.publish_many_bytes(
                    [entries[i][1] for i in fresh])
                appended = []
                for i, off in zip(fresh, new_offs):
                    offs[i] = off
                    pid = entries[i][0]
                    appended.append((off, pid, entries[i][1]))
                    if pid:
                        _remember_id(recent, pid, off, self._recent_ids_max)
                # one journal open+write per batch (hot-path parity with
                # publish_many_bytes)
                jrnl.append_many([(off, pid) for off, pid, _f in appended
                                  if pid])
                for i, j in alias.items():
                    offs[i] = offs[j]
                body = struct.pack(f"<{len(offs)}Q", *offs)
                resp = _RESP.pack(ST_OK, bus.end_offset, len(body)) + body
            # kill-at-offset fault (leader death mid-stream) fires BEFORE
            # the ack: the client never learns the frames' offsets and must
            # replay them at the survivor
            if self.fault_plan is not None and appended:
                act = self.fault_plan.decide("append", partition=part,
                                             offset=bus.end_offset)
                if act is not None and act.action == "kill_server":
                    self._kill_async()
                    return None
            # quorum: ack only once >= min_insync replicas hold the log up
            # to end (the just-appended frames ride along so the steady
            # state skips the log re-read); on a stall the frames stay
            # appended and the client's idempotent replay re-drives this
            if self._repl is not None:
                ok, hint = self._repl.ensure(part, bus.end_offset,
                                             fresh=appended or None)
                # a follower may have fenced us DURING ensure (we adopted
                # its higher epoch and stepped down): the ack must be
                # refused, not retried — the client fails over and replays
                # with the same pub-ids at the real leader
                fenced = self._fence_resp(part)
                if fenced is not None:
                    return fenced
                if not ok:
                    self._shed.increment()
                    return _RESP.pack(ST_RETRY, hint, 0)
            return resp

    def _fence_resp(self, part: int) -> bytes | None:
        """ST_ERR fenced refusal when this node is not the partition's
        current epoch owner (epoch 0 = unclaimed: legacy convention
        leadership still applies). Caller holds the publish lock."""
        if self.epochs is None:
            return None
        e, owner = self.epochs.get(part)
        if e == 0 or owner == self.self_addr:
            return None
        from ..cluster.gossip import fence_message
        registry.counter(FILODB_CLUSTER_FENCED_REJECTS,
                         {"site": "publish"}).increment()
        msg = fence_message(part, e, owner)
        return _RESP.pack(ST_ERR, 0, len(msg)) + msg.encode()

    def _admit(self, part: int) -> bool:
        with self._admit_lock:
            if self._inflight[part] >= self._max_queue:
                return False
            self._inflight[part] += 1
            return True

    def _release(self, part: int) -> None:
        with self._admit_lock:
            self._inflight[part] -= 1

    def _fault_response(self, op: int, part: int,
                        resp: bytes | None) -> bytes | None:
        """serve-site fault hook: drop_response severs without replying
        (the lost-response shape); delay holds the ack."""
        if resp is None or self.fault_plan is None:
            return resp
        act = self.fault_plan.decide("serve", partition=part, op=op)
        if act is None:
            return resp
        if act.action == "drop_response":
            return None
        if act.action == "delay" and act.delay_s > 0:
            time.sleep(act.delay_s)
        return resp

    def _frames_with_ids(self, part: int, lo: int, hi: int,
                         max_bytes: int) -> list[tuple[int, int, bytes]]:
        """Log tail [lo, hi) with journaled pub-ids — the replication
        catch-up read (caller holds the partition's publish lock)."""
        out: list[tuple[int, int, bytes]] = []
        total = 0
        jrnl = self._journals[part]
        for off, frame in self._parts[part].frames_from(lo):
            if off >= hi:
                break
            out.append((off, jrnl.get(off), frame))
            total += len(frame)
            if total >= max_bytes:
                break
        return out

    def _kill_async(self) -> None:
        """Fault injection: die like a crashed node — sever every client
        and stop serving, from a side thread (stop() joins the serve
        thread, so it cannot run on the handler thread itself)."""
        def die():
            try:
                self.stop()
            except Exception:  # noqa: BLE001 — a fault-injected death must
                # still tear the server down visibly, not hang half-dead
                log.exception("fault-injected broker kill failed")

        threading.Thread(target=die, daemon=True,
                         name="filo-broker-kill").start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def self_addr(self) -> str:
        """This node's cluster identity: its entry in the shared peers
        list (epoch owners are recorded by this address)."""
        if self.peers and 0 <= self.node_index < len(self.peers):
            return self.peers[self.node_index]
        return f"127.0.0.1:{self.port}"

    def cluster_peers(self, part: int) -> list[str]:
        """Replica addresses of ``part`` (the epoch claim/announce set)."""
        if self._repl is not None:
            return [self.peers[i] for i in self._repl.replica_indexes(part)]
        return list(self.peers)

    @property
    def num_partitions(self) -> int:
        return len(self._parts)

    def start(self) -> "BrokerServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="filo-broker")
        self._thread.start()
        if self.epochs is not None and self.peers:
            self.rejoin_sync()
            self._claim_static_leaderships()
        return self

    # -- epoch-fenced lifecycle (cluster/: REJOIN + static claims) -----------

    def _claim_static_leaderships(self) -> None:
        """Bootstrap claims: the static leader of each still-unclaimed
        partition claims epoch 1 so fencing is live from the first publish
        (idempotent; a raced claim from elsewhere just wins by epoch)."""
        from ..cluster.gossip import ClusterError, lead_partition
        for part in range(len(self._parts)):
            if part % len(self.peers) != self.node_index:
                continue
            e, _owner = self.epochs.get(part)
            if e == 0:
                try:
                    lead_partition(self, part)
                except (ConnectionError, OSError, ClusterError):
                    log.warning("startup epoch claim failed for partition "
                                "%d; a client failover will claim instead",
                                part, exc_info=True)

    def rejoin_sync(self) -> dict[int, dict]:
        """REJOIN after divergence (the PR 6 known-limit repair): for each
        partition whose current epoch owner is another node, find the
        first offset where our log diverges from the leader's, truncate
        our tail there (a dead leader's unreplicated appends), and catch
        up from the leader's journaled log over OP_SYNC. Returns
        {partition: {"truncated": n, "appended": m}}."""
        from ..cluster.gossip import ClusterError, ClusterLink
        out: dict[int, dict] = {}
        for part in range(len(self._parts)):
            if self.node_index not in (
                    self._repl.replica_indexes(part) if self._repl is not None
                    else range(len(self.peers))):
                continue
            best: tuple[int, str] | None = None
            for addr in self.cluster_peers(part):
                if addr == self.self_addr:
                    continue
                try:
                    e, owner = ClusterLink(addr).epoch_read(part)
                except (ConnectionError, OSError, ClusterError):
                    continue
                if e and (best is None or e > best[0]):
                    best = (e, owner)
            if best is None:
                continue
            self.epochs.adopt(part, *best)
            e, owner = self.epochs.get(part)
            if e == 0 or owner == self.self_addr or owner == "":
                continue
            with span(SPAN_CLUSTER_REJOIN, partition=part, owner=owner):
                try:
                    out[part] = self._repair_from(part, owner)
                except (ConnectionError, OSError, ClusterError) as e:
                    log.warning("REJOIN repair of partition %d from %s "
                                "failed: %s", part, owner, e)
        return out

    def _repair_from(self, part: int, owner: str) -> dict:
        """Truncate-and-catch-up against the current leader: stream its
        journaled log (bounded OP_SYNC chunks), find the first offset
        where our frames differ byte-for-byte (or where our log runs past
        the leader's end), truncate there, then append the leader's
        frames with their pub-ids."""
        from ..cluster.gossip import ClusterLink
        link = ClusterLink(owner, timeout_s=5.0)
        bus = self._parts[part]
        jrnl = self._journals[part]
        with self._publish_locks[part]:
            my_end = bus.end_offset
            # walk the leader's log against a streaming local cursor (both
            # are offset-ordered and contiguous, so one pass holds one
            # bounded sync chunk + one local frame — never the whole log);
            # divergence = first byte mismatch
            mine = bus.frames_from(0)
            div = None
            off = 0
            leader_end, entries = link.sync(part, 0)
            while True:
                for loff, _pid, lframe in entries:
                    if loff >= my_end:
                        break
                    _moff, mframe = next(mine, (None, None))
                    if mframe != lframe:    # mismatch (or torn local tail)
                        div = loff
                        break
                off = entries[-1][0] + 1 if entries else leader_end
                if div is not None or not entries \
                        or off >= min(my_end, leader_end):
                    break
                leader_end, entries = link.sync(part, off)
            if div is None and my_end > leader_end:
                div = leader_end        # our extra tail: the leader never
                # saw it, so it is the diverged unreplicated remainder
            truncated = 0
            if div is not None and div < my_end:
                truncated = bus.truncate(div)
                jrnl.truncate_from(div)
                recent = self._recent_ids[part]
                for pid, r_off in list(recent.items()):
                    if r_off >= div:
                        del recent[pid]
                registry.counter(FILODB_CLUSTER_REJOIN_TRUNCATED,
                                 {"partition": str(part)}).increment(
                    float(truncated))
                log.warning("REJOIN: truncated %d divergent frames of "
                            "partition %d at offset %d", truncated, part,
                            div)
            # catch up [our end, leader end)
            appended = 0
            while bus.end_offset < leader_end:
                leader_end, entries = link.sync(part, bus.end_offset)
                fresh = [(o, p, f) for o, p, f in entries
                         if o >= bus.end_offset]
                if not fresh:
                    break
                bus.publish_many_bytes([f for _o, _p, f in fresh])
                jrnl.append_many([(o, p) for o, p, _f in fresh if p])
                recent = self._recent_ids[part]
                for o, p, _f in fresh:
                    if p:
                        _remember_id(recent, p, o, self._recent_ids_max)
                appended += len(fresh)
        return {"truncated": truncated, "appended": appended}

    def stop(self) -> None:
        """Deterministic teardown: stop the acceptor, release the listening
        socket, sever live client connections (handler threads would
        otherwise keep serving a 'stopped' broker), and join the serve
        thread with a timeout. Idempotent — the fault-injection kill path
        and a test's finally may both call it."""
        with self._conns_lock:
            if self._stopped:
                return
            self._stopped = True
        if self._repl is not None:
            self._repl.close()
        self._server.shutdown()
        self._server.server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass    # racing close: the connection is already gone
            c.close()
        if self._thread is not None:
            self._thread.join(timeout=3)
            self._thread = None


class BrokerBus:
    """Client for one broker partition; drop-in for FileBus.

    ``publish`` is the one-frame-per-round-trip op. The windowed publisher
    (``publish_async``/``publish_batch``/``flush_publishes``) buffers frames
    and ships them as pipelined PUBLISH_BATCH requests of up to
    ``publish_window`` frames each — F frames cost at most ceil(F/W) round
    trips, and every frame keeps its own idempotent publish id so a replay
    after a lost response (or a reconnect) never appends duplicates.
    ``requests`` counts round trips for tests/benchmarks."""

    def __init__(self, addr, partition: int, publish_window: int = 64,
                 retry_backoff_ms: float = 50.0, max_retries: int = 8,
                 seed: int | None = None, track_acks: bool = False,
                 fault_plan=None, epoch_fencing: bool = False):
        """``addr``: one ``host:port`` string, or the partition's whole
        replica address list — with >1 address the bus fails over to the
        most-caught-up survivor on connection loss. ``retry_backoff_ms`` /
        ``max_retries`` bound the jittered exponential backoff after
        RETRY sheds and reconnects (``seed`` pins the jitter for tests).
        ``track_acks=True`` records every acked publish id in
        ``acked_ids`` — the soak audit's client-side ledger.

        ``epoch_fencing=True`` makes the bus honor fenced refusals from
        epoch-enabled brokers: a refusal naming a reachable owner reroutes
        there (closing a spurious failover), one naming a dead owner
        triggers an OP_EPOCH_LEAD claim at the ranked survivor before the
        replay."""
        addrs = [addr] if isinstance(addr, str) else list(addr)
        self.epoch_fencing = bool(epoch_fencing)
        self._addr_strs = list(addrs)
        self._addrs = []
        for a in addrs:
            host, _, port = a.rpartition(":")
            self._addrs.append((host or "127.0.0.1", int(port)))
        self.partition = partition
        # static leader: peers[p % N] — matches the server's replica map
        self._cur = partition % len(self._addrs)
        self.publish_window = max(1, int(publish_window))
        self.retry_backoff_ms = float(retry_backoff_ms)
        self.max_retries = max(1, int(max_retries))
        self.track_acks = bool(track_acks)
        self.acked_ids: list[int] = []
        self.fault_plan = fault_plan
        self._rng = random.Random(
            seed if seed is not None
            else int.from_bytes(os.urandom(8), "little"))
        self._sleep = time.sleep        # injectable: tests run sleep-free
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()   # one in-flight exchange per client
        self._pending: list[tuple[int, bytes]] = []   # (pub_id, frame)
        self.requests = 0               # round-trip count (instrumentation)
        self._ok_since_rank = 0         # successes since the last re-rank
        self._retries = registry.counter(FILODB_INGEST_RETRIES)
        self._failovers = registry.counter(FILODB_INGEST_FAILOVERS)
        self._publish_hist = registry.histogram(
            FILODB_INGEST_PUBLISH_LATENCY_MS,
            {"partition": str(partition)})
        self.failover_count = 0         # this bus only (span failover tag)
        # persistently-dead partition -> shed fast (PR 2 breaker machinery)
        from ..query.wire import PeerBreaker
        self._breaker = PeerBreaker(threshold=3, cooldown_s=5.0)

    def _conn_locked(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self._addrs[self._cur],
                                                  timeout=30)
        return self._sock

    def _transport_attempts(self) -> int:
        # single-address buses keep the historical fast-fail shape (one
        # reconnect); replicated buses spend the retry budget on failover
        return 2 if len(self._addrs) == 1 else max(2, self.max_retries)

    def _backoff_ms(self, k: int, floor_ms: float = 0.0) -> float:
        """Jittered exponential backoff for the k-th retry (k=0 -> no
        wait: the first replay is immediate, like the PR-4 reconnect)."""
        if k <= 0 and floor_ms <= 0:
            return 0.0
        base = self.retry_backoff_ms * (2 ** max(0, k - 1)) if k > 0 else 0.0
        base = min(base, self.retry_backoff_ms * 32)
        jittered = base * (0.5 + self._rng.random())
        return max(floor_ms, jittered)

    def _note_retry_locked(self, k: int, floor_ms: float = 0.0) -> None:
        self._retries.increment()
        wait = self._backoff_ms(k, floor_ms)
        if wait > 0:
            self._sleep(wait / 1000.0)

    def _failover_locked(self) -> None:
        """Re-rank the replica set by replication watermark (OP_END over a
        transient probe connection): highest watermark wins; ties prefer
        the STATIC leader, then the lowest index. The key is GLOBALLY
        shared — no term depends on this client's own state — so every
        publisher lands on the same survivor (one writer per partition),
        and once a recovered static leader has caught up the tie-break
        converges everyone back onto it instead of leaving the fleet
        split across writers forever. Probe connects are bounded well
        below the stream timeout: ranking runs under the bus lock."""
        if len(self._addrs) == 1:
            return
        static = self.partition % len(self._addrs)
        best: tuple[int, int, int] | None = None
        for i, a in enumerate(self._addrs):
            try:
                with socket.create_connection(a, timeout=0.75) as s:
                    s.sendall(_REQ.pack(OP_END, self.partition, 0, 0))
                    st, off, rlen = _RESP.unpack(_recv_exact(s, _RESP.size))
                    if rlen:
                        _recv_exact(s, rlen)
                if st != ST_OK:
                    continue
                cand = (-off, 0 if i == static else 1, i)
                if best is None or cand < best:
                    best = cand
            except (ConnectionError, OSError):
                continue
        if best is not None and best[2] != self._cur:
            self._cur = best[2]
            self.failover_count += 1
            self._failovers.increment()

    _RERANK_EVERY = 256

    def _maybe_rerank_locked(self) -> None:
        """Failed-over clients re-rank every _RERANK_EVERY successful
        exchanges: when the static leader returns AND catches up, the
        tie-break moves everyone home — without this, a transient outage
        would split publishers across writers permanently."""
        if self._cur == self.partition % len(self._addrs):
            return
        self._ok_since_rank += 1
        if self._ok_since_rank >= self._RERANK_EVERY:
            self._ok_since_rank = 0
            was = self._cur
            self._failover_locked()
            if self._cur != was:
                self._close_locked()    # next exchange dials the new pick

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _read_resp_locked(self, s: socket.socket) -> tuple[int, int, bytes]:
        st, off, rlen = _RESP.unpack(_recv_exact(s, _RESP.size))
        return st, off, _recv_exact(s, rlen) if rlen else b""

    def _exchange_locked(self, op: int, offset: int, plen: int,
                         payload: bytes) -> tuple[int, int, bytes]:
        if not self._breaker.admit():
            raise ConnectionError(
                f"partition {self.partition} breaker open (replica set down)")
        attempts = self._transport_attempts()
        last: Exception | None = None
        for attempt in range(attempts):
            if attempt:
                self._note_retry_locked(attempt - 1)
                self._failover_locked()     # dead leader -> ranked survivor
            try:
                s = self._conn_locked()
                s.sendall(_REQ.pack(op, self.partition, offset, plen) + payload)
                self.requests += 1
                if self.fault_plan is not None and self.fault_plan.decide(
                        "client_recv", partition=self.partition, op=op):
                    self._close_locked()
                    raise ConnectionError("fault: response dropped")
                st, off, body = self._read_resp_locked(s)
                self._breaker.record_success()
                self._maybe_rerank_locked()
                return st, off, body
            except (ConnectionError, OSError) as e:
                self._close_locked()
                last = e
        self._breaker.record_failure()
        raise last if last is not None else ConnectionError("unreachable")

    def _request(self, op: int, offset: int = 0, plen: int = 0,
                 payload: bytes = b"") -> tuple[int, bytes]:
        hint_ms = 0
        for k in range(self.max_retries + 1):
            with self._lock:
                if k:
                    self._note_retry_locked(k - 1, floor_ms=hint_ms)
                st, off, body = self._exchange_locked(op, offset, plen,
                                                      payload)
            if st == ST_ERR:
                if self.epoch_fencing and body.startswith(b"fenced:"):
                    # deposed/non-owner broker refused: follow the fence
                    # (reroute to the named owner, or claim a new epoch at
                    # the survivor) and replay with the SAME pub-ids
                    with self._lock:
                        self._handle_fenced_locked(body)
                    continue
                raise RuntimeError(
                    f"broker error: {body.decode(errors='replace')}")
            if st != ST_RETRY:
                return off, body
            hint_ms = off or 100    # RETRY carries the server's ms hint
        raise BrokerRetry(hint_ms / 1000.0)

    def _probe_end_ok(self, addr: tuple) -> bool:
        """Quick liveness probe (OP_END over a transient bounded connect)
        for fence-following: is the named epoch owner actually serving?"""
        try:
            with socket.create_connection(addr, timeout=0.75) as s:
                s.sendall(_REQ.pack(OP_END, self.partition, 0, 0))
                st, _off, rlen = _RESP.unpack(_recv_exact(s, _RESP.size))
                if rlen:
                    _recv_exact(s, rlen)
            return st == ST_OK
        except (ConnectionError, OSError):
            return False

    def _handle_fenced_locked(self, body: bytes) -> None:
        """React to a fenced refusal: (1) the named owner answers — it IS
        the leader, move there (a spurious failover snaps home); (2) the
        owner is provably dead — claim a new epoch at the ranked survivor
        so the replay lands on a fenced-in leader. An unparseable message
        or an ALIVE owner outside our address list (configuration skew)
        triggers a plain re-rank, never a claim — deposing a live leader
        on a string mismatch would ping-pong leadership forever."""
        from ..cluster.gossip import ClusterError, ClusterLink, parse_fenced
        parsed = parse_fenced(body.decode(errors="replace"))
        owner = parsed[2] if parsed else ""
        owner_alive = False
        if owner:
            host, _, port = owner.rpartition(":")
            try:
                owner_alive = self._probe_end_ok((host or "127.0.0.1",
                                                  int(port)))
            except ValueError:
                owner_alive = False     # malformed owner address
        if owner_alive:
            if owner in self._addr_strs:
                i = self._addr_strs.index(owner)
                if i != self._cur:
                    self._cur = i
                    self.failover_count += 1
                    self._failovers.increment()
            else:
                # live owner we cannot dial by our configured list: the
                # retry surfaces the fenced error instead of deposing it
                log.warning("fenced by live owner %s not in this bus's "
                            "address list for partition %d", owner,
                            self.partition)
            self._close_locked()
            return
        self._failover_locked()     # dead/unknown owner: rank survivors
        if parsed is not None:
            try:
                ClusterLink(self._addr_strs[self._cur]).epoch_lead(
                    self.partition)
            except (ConnectionError, OSError, ClusterError):
                # claim did not land (survivor flapping): the replay's next
                # fenced/transport error re-drives this handler
                log.warning("epoch claim at %s for partition %d failed",
                            self._addr_strs[self._cur], self.partition,
                            exc_info=True)
        self._close_locked()

    @staticmethod
    def _pub_id() -> int:
        # stable random id across the internal reconnect retry: if the broker
        # committed the append but the response was lost, the retry is a no-op
        return int.from_bytes(os.urandom(8), "little") | 1

    def publish(self, container: RecordContainer) -> int:
        return self.publish_with_id(container, self._pub_id())

    def publish_with_id(self, container: RecordContainer,
                        pub_id: int) -> int:
        """Publish one frame under a CALLER-SUPPLIED publish id (low bit
        forced — id 0 means 'no id' on the wire). The rules subsystem
        derives ids from (rule, eval_ts, shard), so re-publishing a
        re-evaluated tick resolves to the original offsets instead of
        appending duplicates — exactly-once derived writes ride the same
        journaled idempotence as retry replays."""
        payload = container.to_bytes()
        pid = int(pub_id) | 1
        off, _ = self._request(OP_PUBLISH, offset=pid,
                               plen=len(payload), payload=payload)
        if self.track_acks:
            with self._lock:
                self.acked_ids.append(pid)
        return off

    def publish_async(self, container: RecordContainer) -> None:
        """Queue one frame; a full window drains automatically (one
        PUBLISH_BATCH round trip). Call ``flush_publishes`` to drain the
        remainder — assigned offsets surface there."""
        payload = container.to_bytes()
        with self._lock:
            self._pending.append((self._pub_id(), payload))
            if len(self._pending) >= self.publish_window:
                self._drain_pending_locked()

    def publish_batch(self, containers) -> list[int]:
        """Publish many containers in ceil(n/window) pipelined round trips;
        returns their assigned offsets (plus any earlier async remainder's,
        in queue order)."""
        with self._lock:
            for c in containers:
                self._pending.append((self._pub_id(), c.to_bytes()))
            return self._drain_pending_locked()

    def flush_publishes(self) -> list[int]:
        """Drain queued async publishes; returns their assigned offsets."""
        with self._lock:
            return self._drain_pending_locked()

    def _next_group_locked(self) -> tuple[list[list], int]:
        """Head of the pending queue as PUBLISH_BATCH chunks: each chunk at
        most ``publish_window`` frames AND ``_MAX_BATCH_BYTES`` of payload;
        the group at most ``_MAX_UNACKED_FRAMES`` frames total."""
        chunks: list[list] = []
        cur: list = []
        cur_bytes = taken = 0
        for pid, frame in self._pending:
            if taken >= _MAX_UNACKED_FRAMES:
                break
            entry = _ENTRY.size + len(frame)
            if cur and (len(cur) >= self.publish_window
                        or cur_bytes + entry > _MAX_BATCH_BYTES):
                chunks.append(cur)
                cur, cur_bytes = [], 0
            cur.append((pid, frame))
            cur_bytes += entry
            taken += 1
        if cur:
            chunks.append(cur)
        return chunks, taken

    def _drain_pending_locked(self) -> list[int]:
        offs: list[int] = []
        while self._pending:
            chunks, taken = self._next_group_locked()
            offs.extend(self._send_group_locked(chunks))
            del self._pending[:taken]   # commit per group: a later failure
            if self.track_acks:         # never replays acked frames
                self.acked_ids.extend(pid for ch in chunks for pid, _ in ch)
        return offs

    def _send_group_locked(self, chunks: list[list]) -> list[int]:
        # one span per pipelined group: the SAME trace context rides every
        # request of the group — including replays after a leader failover,
        # so the survivor's append spans join the original publish trace
        # and the failover itself is tagged on the client span
        with span(SPAN_INGEST_PUBLISH, partition=self.partition,
                  frames=sum(len(c) for c in chunks)) as tags:
            fo0 = self.failover_count
            t0 = time.perf_counter_ns()
            try:
                offs = self._send_group_traced_locked(chunks)
            finally:
                if self.failover_count > fo0:
                    tags["failovers"] = self.failover_count - fo0
            # SUCCESSFUL groups only: a breaker-open shed raises within
            # microseconds and a timed-out group never completed — either
            # would poison the round-trip histogram's percentiles. The
            # exemplar carries the id only for SAMPLED traces (an id
            # nothing recorded dead-ends at /api/v1/debug/traces).
            tctx = tracer.current_context()
            self._publish_hist.record(
                (time.perf_counter_ns() - t0) / 1e6,
                trace_id=(tctx["trace_id"]
                          if tctx and tctx.get("sampled") else None))
            return offs

    def _send_group_traced_locked(self, chunks: list[list]) -> list[int]:
        # pipeline WITHIN a bounded group: all of the group's requests go
        # on the wire before its first response is read (the broker
        # serves one connection serially, so responses arrive in order),
        # then the group commits and drops off the pending queue. A
        # replay after a lost connection OR a RETRY shed re-sends the SAME
        # publish ids, which the (possibly failed-over) broker resolves to
        # the original offsets — and a group never exceeds half the
        # broker's id window, so none of its ids can have been evicted by
        # its own replay.
        if not self._breaker.admit():
            raise ConnectionError(
                f"partition {self.partition} breaker open (replica set down)")
        transport = self._transport_attempts()
        # the trace block is identical across replays (same publish span):
        # a failed-over broker's spans join the original trace
        thdr = pack_trace_hdr(tracer.current_context())
        t_fail = r_shed = fenced_n = 0
        while True:
            try:
                s = self._conn_locked()
                for ch in chunks:
                    payload = thdr + b"".join(_ENTRY.pack(pid, len(f)) + f
                                              for pid, f in ch)
                    s.sendall(_REQ.pack(OP_PUBLISH_BATCH, self.partition,
                                        len(ch), len(payload)) + payload)
                    self.requests += 1
                if self.fault_plan is not None and self.fault_plan.decide(
                        "client_recv", partition=self.partition,
                        op=OP_PUBLISH_BATCH):
                    self._close_locked()
                    raise ConnectionError("fault: response dropped")
                group_offs: list[int] = []
                err: bytes | None = None
                retry_hint = 0
                for ch in chunks:   # drain EVERY response before raising
                    st, _end, body = self._read_resp_locked(s)
                    if st == ST_ERR:
                        err = err or body
                    elif st == ST_RETRY:
                        retry_hint = max(retry_hint, _end or 100)
                    else:
                        group_offs.extend(
                            struct.unpack(f"<{len(ch)}Q", body))
                if err is not None:
                    if self.epoch_fencing and err.startswith(b"fenced:"):
                        # the whole group replays at the fenced-in leader
                        # with the SAME pub-ids: chunks the new leader
                        # already replicated resolve by id, nothing dups
                        fenced_n += 1
                        if fenced_n > self.max_retries:
                            raise RuntimeError(
                                "broker error: "
                                f"{err.decode(errors='replace')}")
                        self._handle_fenced_locked(err)
                        continue
                    raise RuntimeError(
                        f"broker error: {err.decode(errors='replace')}")
                if retry_hint:
                    # backpressure shed: back off (honoring the server's
                    # hint) and replay the whole group — OK'd chunks
                    # resolve by id, shed chunks get their append
                    r_shed += 1
                    if r_shed > self.max_retries:
                        raise BrokerRetry(retry_hint / 1000.0)
                    self._note_retry_locked(r_shed - 1, floor_ms=retry_hint)
                    continue
                self._breaker.record_success()
                self._maybe_rerank_locked()
                return group_offs
            except (ConnectionError, OSError):
                self._close_locked()
                t_fail += 1
                if t_fail >= transport:
                    self._breaker.record_failure()
                    raise
                self._note_retry_locked(t_fail - 1)
                self._failover_locked()

    def consume(self, schemas, from_offset: int = 0) -> Iterator[tuple[int, RecordContainer]]:
        """Replay containers from ``from_offset`` up to the end offset observed
        on the FIRST fetch (ref: Kafka seek + poll). The snapshot matters: a
        poll-loop consumer must regain control between polls to flush/
        checkpoint/purge, so under sustained publish load this terminates
        instead of chasing the moving end forever (FileBus.consume contract)."""
        next_off = from_offset
        end: int | None = None
        while True:
            resp_end, body = self._request(OP_FETCH, offset=next_off)
            if end is None:
                end = resp_end
            pos = 0
            got = 0
            while pos < len(body):
                off, ln = _ENTRY.unpack_from(body, pos)
                pos += _ENTRY.size
                if off >= end:
                    return
                yield off, RecordContainer.from_bytes(body[pos:pos + ln], schemas)
                pos += ln
                next_off = off + 1
                got += 1
            if not got or next_off >= end:
                return

    @property
    def end_offset(self) -> int:
        off, _ = self._request(OP_END)
        return off

    def close(self) -> None:
        # sever FIRST, without the exchange lock: a consumer blocked in a
        # 30s recv HOLDS that lock, and closing the socket out from under
        # it is exactly what unblocks it (teardown would otherwise stall
        # behind the full socket timeout)
        s = self._sock
        if s is not None:
            try:
                s.close()
            except OSError:
                pass    # racing close: already severed
        with self._lock:
            self._close_locked()
