"""TCP log broker — the Kafka-broker-equivalent data plane.

Reference: kafka/src/main/scala/filodb/kafka/KafkaIngestionStream.scala (one
shard == one partition; consumers seek to the checkpointed offset and replay)
and PartitionStrategy (shard -> partition routing). The reference outsources
the broker to Kafka; here the broker itself is part of the framework: a
threaded TCP server fronting one durable append-only log per partition (the
same offset-addressed frame format as FileBus, so logs are interchangeable
between in-process and brokered deployments).

Wire protocol (all little-endian, one request/response per round trip):

  request  = op:u8  partition:u32  offset:u64  payload_len:u32  payload
  response = status:u8  offset:u64  payload_len:u32  payload

  ops: PUBLISH (payload=container bytes; the offset field carries a random
                nonzero publish id — the broker remembers recent ids per
                partition and returns the original offset on a retry instead
                of appending a duplicate; returns assigned offset)
       FETCH   (offset=from_offset; payload_len field carries max_frames;
                returns concatenated [offset:u64 len:u32 bytes] entries)
       END     (returns the partition's end offset)
       PUBLISH_BATCH (payload=concatenated [pub_id:u64 len:u32 bytes] frames —
                MANY publishes per round trip, the publish-side mirror of
                FETCH's response batching; the offset field carries the frame
                count. Per-frame publish ids keep retries duplicate-free
                exactly like PUBLISH. Response payload: one u64 assigned
                offset per frame, in request order)

`BrokerBus` is a drop-in for FileBus (publish/consume/end_offset), so the
standalone server's IngestionConsumer works unchanged against a remote broker.
Its windowed publisher (`publish_async`/`publish_batch`/`flush_publishes`)
pipelines PUBLISH_BATCH requests: F frames with window W cost at most
ceil(F/W) round trips, and all of a drain's requests are on the wire before
the first response is read.
"""

from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading
from typing import Iterator

from ..core.record import RecordContainer
from .bus import FileBus

_REQ = struct.Struct("<B I Q I")
_RESP = struct.Struct("<B Q I")
_ENTRY = struct.Struct("<Q I")

OP_PUBLISH, OP_FETCH, OP_END, OP_PUBLISH_BATCH = 1, 2, 3, 4
ST_OK, ST_ERR = 0, 1

_MAX_PAYLOAD = 64 << 20     # refuse absurd frames instead of OOMing
_RECENT_IDS_MAX = 4096      # retry-able publish ids remembered per partition
_MAX_BATCH_BYTES = 8 << 20  # per-PUBLISH_BATCH payload bound (well under
                            # _MAX_PAYLOAD, so the broker never severs a
                            # batched connection for size)
# unacked frames per pipelined group: half the broker's id window, so a full
# group replay after a lost connection still resolves every id (no silent
# duplicates), and the unread-response backlog stays far below socket buffers
_MAX_UNACKED_FRAMES = _RECENT_IDS_MAX // 2


def _remember_id(recent: dict[int, int], pub_id: int, off: int,
                 limit: int) -> None:
    """Record a publish id -> offset (caller holds the partition's publish
    lock). Eviction is strictly oldest-first, one at a time — dicts iterate
    in insertion order and retry hits re-insert, so a recently retried id is
    never the one evicted."""
    recent[pub_id] = off
    while len(recent) > limit:
        recent.pop(next(iter(recent)))


def _recall_id(recent: dict[int, int], pub_id: int) -> int | None:
    """Offset of an already-seen publish id, refreshing its recency (caller
    holds the partition's publish lock)."""
    off = recent.pop(pub_id, None)
    if off is not None:
        recent[pub_id] = off
    return off


from ..utils.netio import recv_exact as _recv_exact  # noqa: E402 - shared framing helper


class BrokerServer:
    """Hosts partitions 0..num_partitions-1, each a durable FileBus log."""

    def __init__(self, data_dir: str, num_partitions: int,
                 host: str = "127.0.0.1", port: int = 0,
                 recent_ids_max: int = _RECENT_IDS_MAX):
        """``recent_ids_max`` below the default weakens the windowed
        publisher's replay idempotence: BrokerBus bounds a pipelined group to
        ``_RECENT_IDS_MAX // 2`` unacked frames on the assumption the server
        remembers at least the module default — shrink it only in tests that
        exercise eviction itself."""
        os.makedirs(data_dir, exist_ok=True)
        self._parts = [FileBus(os.path.join(data_dir, f"partition{p}.log"))
                       for p in range(num_partitions)]
        # publish idempotence: recent publish-id -> offset per partition, so a
        # client retry after a lost response doesn't append a duplicate frame
        self._recent_ids: list[dict[int, int]] = [{} for _ in range(num_partitions)]
        self._recent_ids_max = int(recent_ids_max)
        self._publish_locks = [threading.Lock() for _ in range(num_partitions)]
        # live client connections, so stop() actually severs them (handler
        # threads would otherwise keep serving a "stopped" broker)
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with outer._conns_lock:
                    outer._conns.add(self.request)
                try:
                    while True:
                        hdr = _recv_exact(self.request, _REQ.size)
                        op, part, offset, plen = _REQ.unpack(hdr)
                        if plen > _MAX_PAYLOAD:
                            raise ValueError(f"frame too large: {plen}")
                        payload = _recv_exact(self.request, plen) \
                            if op in (OP_PUBLISH, OP_PUBLISH_BATCH) and plen \
                            else b""
                        self.request.sendall(outer._serve(op, part, offset,
                                                          plen, payload))
                except (ConnectionError, OSError):
                    pass    # client went away or the broker is stopping
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(self.request)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self._thread: threading.Thread | None = None

    def _serve(self, op: int, part: int, offset: int, plen: int,
               payload: bytes) -> bytes:
        try:
            if not 0 <= part < len(self._parts):
                raise ValueError(f"no partition {part}")
            bus = self._parts[part]
            if op == OP_PUBLISH:
                pub_id = offset                 # request offset field = publish id
                with self._publish_locks[part]:
                    recent = self._recent_ids[part]
                    off = _recall_id(recent, pub_id) if pub_id else None
                    if off is None:
                        off = bus.publish_bytes(payload)
                        if pub_id:
                            _remember_id(recent, pub_id, off,
                                         self._recent_ids_max)
                return _RESP.pack(ST_OK, off, 0)
            if op == OP_PUBLISH_BATCH:
                entries = []                    # (pub_id, frame bytes)
                pos = 0
                while pos < len(payload):
                    pid, ln = _ENTRY.unpack_from(payload, pos)
                    pos += _ENTRY.size
                    entries.append((pid, payload[pos:pos + ln]))
                    pos += ln
                offs = [0] * len(entries)
                with self._publish_locks[part]:
                    recent = self._recent_ids[part]
                    fresh: list[int] = []       # indexes needing an append
                    first_idx: dict[int, int] = {}
                    alias: dict[int, int] = {}  # in-batch duplicate ids
                    for i, (pid, _frame) in enumerate(entries):
                        off = _recall_id(recent, pid) if pid else None
                        if off is not None:
                            offs[i] = off
                        elif pid and pid in first_idx:
                            alias[i] = first_idx[pid]
                        else:
                            fresh.append(i)
                            if pid:
                                first_idx[pid] = i
                    # one open+write for the whole batch — per-frame appends
                    # would re-open the log file once per frame
                    new_offs = bus.publish_many_bytes(
                        [entries[i][1] for i in fresh])
                    for i, off in zip(fresh, new_offs):
                        offs[i] = off
                        pid = entries[i][0]
                        if pid:
                            _remember_id(recent, pid, off,
                                         self._recent_ids_max)
                    for i, j in alias.items():
                        offs[i] = offs[j]
                body = struct.pack(f"<{len(offs)}Q", *offs)
                return _RESP.pack(ST_OK, bus.end_offset, len(body)) + body
            if op == OP_FETCH:
                max_frames = plen or 1024
                out = bytearray()
                n = 0
                for off, frame in bus.frames_from(offset):
                    out += _ENTRY.pack(off, len(frame))
                    out += frame
                    n += 1
                    if n >= max_frames:
                        break
                return _RESP.pack(ST_OK, bus.end_offset, len(out)) + bytes(out)
            if op == OP_END:
                return _RESP.pack(ST_OK, bus.end_offset, 0)
            raise ValueError(f"unknown op {op}")
        except Exception as e:  # noqa: BLE001 — delivered to the client
            msg = str(e).encode()[:1024]
            return _RESP.pack(ST_ERR, 0, len(msg)) + msg

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def num_partitions(self) -> int:
        return len(self._parts)

    def start(self) -> "BrokerServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="filo-broker")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Deterministic teardown: stop the acceptor, release the listening
        socket, sever live client connections (handler threads would
        otherwise keep serving a 'stopped' broker), and join the serve
        thread with a timeout."""
        self._server.shutdown()
        self._server.server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass    # racing close: the connection is already gone
            c.close()
        if self._thread is not None:
            self._thread.join(timeout=3)
            self._thread = None


class BrokerBus:
    """Client for one broker partition; drop-in for FileBus.

    ``publish`` is the one-frame-per-round-trip op. The windowed publisher
    (``publish_async``/``publish_batch``/``flush_publishes``) buffers frames
    and ships them as pipelined PUBLISH_BATCH requests of up to
    ``publish_window`` frames each — F frames cost at most ceil(F/W) round
    trips, and every frame keeps its own idempotent publish id so a replay
    after a lost response (or a reconnect) never appends duplicates.
    ``requests`` counts round trips for tests/benchmarks."""

    def __init__(self, addr: str, partition: int, publish_window: int = 64):
        host, _, port = addr.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.partition = partition
        self.publish_window = max(1, int(publish_window))
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()   # one in-flight exchange per client
        self._pending: list[tuple[int, bytes]] = []   # (pub_id, frame)
        self.requests = 0               # round-trip count (instrumentation)

    def _conn_locked(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self._addr, timeout=30)
        return self._sock

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _read_resp_locked(self, s: socket.socket) -> tuple[int, int, bytes]:
        st, off, rlen = _RESP.unpack(_recv_exact(s, _RESP.size))
        return st, off, _recv_exact(s, rlen) if rlen else b""

    def _exchange_locked(self, op: int, offset: int, plen: int,
                         payload: bytes) -> tuple[int, int, bytes]:
        for attempt in (0, 1):          # one reconnect on a stale connection
            try:
                s = self._conn_locked()
                s.sendall(_REQ.pack(op, self.partition, offset, plen) + payload)
                self.requests += 1
                st, off, body = self._read_resp_locked(s)
                return st, off, body
            except (ConnectionError, OSError):
                self._close_locked()
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def _request(self, op: int, offset: int = 0, plen: int = 0,
                 payload: bytes = b"") -> tuple[int, bytes]:
        with self._lock:
            st, off, body = self._exchange_locked(op, offset, plen, payload)
        if st == ST_ERR:
            raise RuntimeError(f"broker error: {body.decode(errors='replace')}")
        return off, body

    @staticmethod
    def _pub_id() -> int:
        # stable random id across the internal reconnect retry: if the broker
        # committed the append but the response was lost, the retry is a no-op
        return int.from_bytes(os.urandom(8), "little") | 1

    def publish(self, container: RecordContainer) -> int:
        payload = container.to_bytes()
        off, _ = self._request(OP_PUBLISH, offset=self._pub_id(),
                               plen=len(payload), payload=payload)
        return off

    def publish_async(self, container: RecordContainer) -> None:
        """Queue one frame; a full window drains automatically (one
        PUBLISH_BATCH round trip). Call ``flush_publishes`` to drain the
        remainder — assigned offsets surface there."""
        payload = container.to_bytes()
        with self._lock:
            self._pending.append((self._pub_id(), payload))
            if len(self._pending) >= self.publish_window:
                self._drain_pending_locked()

    def publish_batch(self, containers) -> list[int]:
        """Publish many containers in ceil(n/window) pipelined round trips;
        returns their assigned offsets (plus any earlier async remainder's,
        in queue order)."""
        with self._lock:
            for c in containers:
                self._pending.append((self._pub_id(), c.to_bytes()))
            return self._drain_pending_locked()

    def flush_publishes(self) -> list[int]:
        """Drain queued async publishes; returns their assigned offsets."""
        with self._lock:
            return self._drain_pending_locked()

    def _next_group_locked(self) -> tuple[list[list], int]:
        """Head of the pending queue as PUBLISH_BATCH chunks: each chunk at
        most ``publish_window`` frames AND ``_MAX_BATCH_BYTES`` of payload;
        the group at most ``_MAX_UNACKED_FRAMES`` frames total."""
        chunks: list[list] = []
        cur: list = []
        cur_bytes = taken = 0
        for pid, frame in self._pending:
            if taken >= _MAX_UNACKED_FRAMES:
                break
            entry = _ENTRY.size + len(frame)
            if cur and (len(cur) >= self.publish_window
                        or cur_bytes + entry > _MAX_BATCH_BYTES):
                chunks.append(cur)
                cur, cur_bytes = [], 0
            cur.append((pid, frame))
            cur_bytes += entry
            taken += 1
        if cur:
            chunks.append(cur)
        return chunks, taken

    def _drain_pending_locked(self) -> list[int]:
        offs: list[int] = []
        while self._pending:
            chunks, taken = self._next_group_locked()
            # pipeline WITHIN a bounded group: all of the group's requests go
            # on the wire before its first response is read (the broker
            # serves one connection serially, so responses arrive in order),
            # then the group commits and drops off the pending queue. A
            # replay after a lost connection re-sends the SAME publish ids,
            # which the broker resolves to the original offsets — and a
            # group never exceeds half the broker's id window, so none of
            # its ids can have been evicted by its own replay.
            for attempt in (0, 1):
                try:
                    s = self._conn_locked()
                    for ch in chunks:
                        payload = b"".join(_ENTRY.pack(pid, len(f)) + f
                                           for pid, f in ch)
                        s.sendall(_REQ.pack(OP_PUBLISH_BATCH, self.partition,
                                            len(ch), len(payload)) + payload)
                        self.requests += 1
                    group_offs: list[int] = []
                    err: bytes | None = None
                    for ch in chunks:   # drain EVERY response before raising
                        st, _end, body = self._read_resp_locked(s)
                        if st == ST_ERR:
                            err = err or body
                        else:
                            group_offs.extend(
                                struct.unpack(f"<{len(ch)}Q", body))
                    if err is not None:
                        raise RuntimeError(
                            f"broker error: {err.decode(errors='replace')}")
                    break
                except (ConnectionError, OSError):
                    self._close_locked()
                    if attempt:
                        raise
            del self._pending[:taken]   # commit per group: a later failure
            offs.extend(group_offs)     # never replays acked frames
        return offs

    def consume(self, schemas, from_offset: int = 0) -> Iterator[tuple[int, RecordContainer]]:
        """Replay containers from ``from_offset`` up to the end offset observed
        on the FIRST fetch (ref: Kafka seek + poll). The snapshot matters: a
        poll-loop consumer must regain control between polls to flush/
        checkpoint/purge, so under sustained publish load this terminates
        instead of chasing the moving end forever (FileBus.consume contract)."""
        next_off = from_offset
        end: int | None = None
        while True:
            resp_end, body = self._request(OP_FETCH, offset=next_off)
            if end is None:
                end = resp_end
            pos = 0
            got = 0
            while pos < len(body):
                off, ln = _ENTRY.unpack_from(body, pos)
                pos += _ENTRY.size
                if off >= end:
                    return
                yield off, RecordContainer.from_bytes(body[pos:pos + ln], schemas)
                pos += ln
                next_off = off + 1
                got += 1
            if not got or next_off >= end:
                return

    @property
    def end_offset(self) -> int:
        off, _ = self._request(OP_END)
        return off

    def close(self) -> None:
        with self._lock:
            self._close_locked()
