"""Durable ingest bus — the Kafka-equivalent data plane.

Reference: kafka/src/main/scala/filodb/kafka/KafkaIngestionStream.scala
(1 shard == 1 partition, seek to checkpointed offset, replay). Here: one
append-only log file per (dataset, shard) of length-prefixed RecordContainer
frames; offsets are frame ordinals. The same interface can front a real broker.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Iterator

from ..core.record import RecordContainer

_FRAME = struct.Struct("<Q I")   # offset, payload length


class FileBus:
    """Append-only per-shard container log with offset-addressed replay."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._next_offset = 0
        self._publish_lock = threading.Lock()   # concurrent producers in-process
        if os.path.exists(path):
            for off, _ in self._frames():
                self._next_offset = off + 1

    def publish(self, container: RecordContainer) -> int:
        """Append a container; returns its offset."""
        payload = container.to_bytes()
        with self._publish_lock:
            off = self._next_offset
            with open(self.path, "ab") as f:
                f.write(_FRAME.pack(off, len(payload)))
                f.write(payload)
            self._next_offset = off + 1
        return off

    def _frames(self) -> Iterator[tuple[int, bytes]]:
        if not os.path.exists(self.path):
            return  # nothing published yet (another process may own the first write)
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(_FRAME.size)
                if len(hdr) < _FRAME.size:
                    return
                off, ln = _FRAME.unpack(hdr)
                payload = f.read(ln)
                if len(payload) < ln:
                    return  # truncated tail (torn write) — stop cleanly
                yield off, payload

    def consume(self, schemas, from_offset: int = 0) -> Iterator[tuple[int, RecordContainer]]:
        """Replay containers from ``from_offset`` (ref: Kafka seek-to-checkpoint)."""
        for off, payload in self._frames():
            if off >= from_offset:
                yield off, RecordContainer.from_bytes(payload, schemas)

    @property
    def end_offset(self) -> int:
        return self._next_offset
