"""Durable ingest bus — the Kafka-equivalent data plane.

Reference: kafka/src/main/scala/filodb/kafka/KafkaIngestionStream.scala
(1 shard == 1 partition, seek to checkpointed offset, replay). Here: one
append-only log file per (dataset, shard) of length-prefixed RecordContainer
frames; offsets are frame ordinals. A byte-position index (built on open,
maintained on append) makes seek-to-offset O(1), like a Kafka segment index.
The same interface can front a real broker — see ingest/broker.py for the
framework's own TCP broker speaking this log format.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Iterator

from ..core.record import RecordContainer

_FRAME = struct.Struct("<Q I")   # offset, payload length


class FileBus:
    """Append-only per-shard container log with offset-addressed replay."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._publish_lock = threading.Lock()   # concurrent producers in-process
        # offset -> byte position of its frame header (the seek index)
        self._positions: list[int] = []
        self.resync()

    def publish(self, container: RecordContainer) -> int:
        """Append a container; returns its offset."""
        return self.publish_bytes(container.to_bytes())

    def publish_bytes(self, payload: bytes) -> int:
        with self._publish_lock:
            off = len(self._positions)
            with open(self.path, "ab") as f:
                pos = f.tell()
                # one write call: keeps the frame contiguous even if another
                # appender (against the single-writer contract) interleaves
                f.write(_FRAME.pack(off, len(payload)) + payload)
            self._positions.append(pos)
        return off

    def publish_many_bytes(self, payloads) -> list[int]:
        """Append many frames with ONE open + ONE write; returns their
        offsets. The broker's PUBLISH_BATCH path: per-frame appends would
        re-open the log once per frame, which dominates small-frame batches.
        The index only adopts the frames after the write succeeds, so a torn
        batch is recovered by resync() exactly like a torn single frame."""
        if not payloads:
            return []
        with self._publish_lock:
            base = len(self._positions)
            blob = bytearray()
            for i, p in enumerate(payloads):
                blob += _FRAME.pack(base + i, len(p)) + p
            with open(self.path, "ab") as f:
                pos = f.tell()
                f.write(blob)
            for p in payloads:
                self._positions.append(pos)
                pos += _FRAME.size + len(p)
        return list(range(base, base + len(payloads)))

    def frames_from(self, from_offset: int = 0) -> Iterator[tuple[int, bytes]]:
        """Raw frames from ``from_offset``, seeking straight to its position."""
        end = len(self._positions)               # snapshot: stable under appends
        if from_offset >= end:
            return
        with open(self.path, "rb") as f:
            f.seek(self._positions[from_offset])
            for off in range(from_offset, end):
                hdr = f.read(_FRAME.size)
                if len(hdr) < _FRAME.size:
                    return
                stored_off, ln = _FRAME.unpack(hdr)
                payload = f.read(ln)
                if len(payload) < ln:
                    return                       # torn tail — stop cleanly
                yield stored_off, payload

    def consume(self, schemas, from_offset: int = 0) -> Iterator[tuple[int, RecordContainer]]:
        """Replay containers from ``from_offset`` (ref: Kafka seek-to-checkpoint).

        Picks up frames appended by *other processes* too: the index is
        re-synced from the file when the caller asks past our known end.
        """
        if from_offset >= len(self._positions):
            self.resync()
        for off, payload in self.frames_from(from_offset):
            yield off, RecordContainer.from_bytes(payload, schemas)

    def resync(self) -> None:
        """Re-scan the log tail for frames appended by another process."""
        with self._publish_lock:
            if not os.path.exists(self.path):
                return
            size = os.path.getsize(self.path)
            pos = 0
            if self._positions:
                # start from the last known frame to find its end
                last = self._positions[-1]
                with open(self.path, "rb") as f:
                    f.seek(last)
                    _, ln = _FRAME.unpack(f.read(_FRAME.size))
                pos = last + _FRAME.size + ln
            with open(self.path, "rb") as f:
                while pos + _FRAME.size <= size:
                    f.seek(pos)
                    _, ln = _FRAME.unpack(f.read(_FRAME.size))
                    if pos + _FRAME.size + ln > size:
                        break
                    self._positions.append(pos)
                    pos += _FRAME.size + ln

    def truncate(self, end_offset: int) -> int:
        """Drop every frame at ``end_offset`` and beyond (the REJOIN
        divergent-tail repair: a restarted deposed leader truncates frames
        the current leader never saw before catching up). Returns the
        number of frames dropped."""
        with self._publish_lock:
            if end_offset >= len(self._positions):
                return 0
            dropped = len(self._positions) - end_offset
            pos = self._positions[end_offset]
            with open(self.path, "r+b") as f:
                f.truncate(pos)
            del self._positions[end_offset:]
        return dropped

    @property
    def end_offset(self) -> int:
        return len(self._positions)

    def close(self) -> None:
        """Bus-interface parity with BrokerBus: FileBus opens its log per
        operation, so there is nothing to release — owners can close any
        bus unconditionally."""
