"""Ingestion sources: the stream abstraction + CSV and synthetic generators.

Reference: coordinator/.../IngestionStream.scala:14,43 (trait + factory),
sources/CsvStream.scala (sample CSV source), gateway/.../TestTimeseriesProducer
(synthetic series for dev/benchmarks).
"""

from __future__ import annotations

import csv
from typing import Iterator

import numpy as np

from ..core.record import RecordBuilder, RecordContainer
from ..core.schemas import GAUGE, Schema


class IngestionStream:
    """Iterable of (offset, RecordContainer); teardown() releases resources."""

    def __iter__(self) -> Iterator[tuple[int, RecordContainer]]:  # pragma: no cover
        raise NotImplementedError

    def teardown(self) -> None:
        pass


class CsvStream(IngestionStream):
    """CSV rows -> containers. Columns: metric,timestamp(ms),value,then labels
    as name=value pairs in remaining columns (header optional)."""

    def __init__(self, path: str, schema: Schema = GAUGE, batch_size: int = 1000,
                 ws: str = "default", ns: str = "default"):
        self.path = path
        self.schema = schema
        self.batch_size = batch_size
        self.ws, self.ns = ws, ns

    def __iter__(self):
        b = RecordBuilder(self.schema)
        offset = 0
        count = 0
        with open(self.path) as f:
            for row in csv.reader(f):
                if not row or row[0] == "metric":
                    continue
                metric, ts, value, *labelcols = row
                labels = {"_metric_": metric, "_ws_": self.ws, "_ns_": self.ns}
                for lc in labelcols:
                    if "=" in lc:
                        k, v = lc.split("=", 1)
                        labels[k] = v
                b.add(labels, int(ts), float(value))
                count += 1
                if count >= self.batch_size:
                    yield offset, b.build()
                    offset += 1
                    count = 0
        if count:
            yield offset, b.build()


class SyntheticStream(IngestionStream):
    """Deterministic synthetic gauge/counter series (ref:
    TestTimeseriesProducer.timeSeriesData: sinusoidal gauges keyed by instance)."""

    def __init__(self, schema: Schema = GAUGE, n_series: int = 100,
                 n_batches: int = 10, samples_per_batch: int = 10,
                 start_ms: int = 1_000_000, interval_ms: int = 10_000,
                 metric: str = "heap_usage0", kind: str = "gauge"):
        self.schema = schema
        self.n_series = n_series
        self.n_batches = n_batches
        self.samples_per_batch = samples_per_batch
        self.start_ms = start_ms
        self.interval_ms = interval_ms
        self.metric = metric
        self.kind = kind

    def labels(self, i: int) -> dict[str, str]:
        return {"_metric_": self.metric, "_ws_": "demo", "_ns_": "App-0",
                "instance": f"Instance-{i}", "host": f"H{i % 10}",
                "dc": f"DC{i % 2}"}

    def __iter__(self):
        counter_base = np.zeros(self.n_series)
        t_idx = 0
        idx = np.arange(self.n_series)[:, None]
        for batch in range(self.n_batches):
            b = RecordBuilder(self.schema)
            k = self.samples_per_batch
            steps = t_idx + np.arange(k)[None, :]
            ts = self.start_ms + steps[0] * self.interval_ms
            if self.kind == "counter":
                incs = np.abs(np.sin(steps / 10 + idx)) * 10     # [S, k]
                vals = counter_base[:, None] + np.cumsum(incs, axis=1)
                counter_base = vals[:, -1].copy()
            else:
                vals = 15.0 * (idx + 1) + 8 * np.sin(steps / 10 + idx)
            # one bulk append per series per batch (samples stay time-ordered
            # per series; cross-series interleaving is irrelevant downstream)
            for i in range(self.n_series):
                b.add_batch(self.labels(i), ts, vals[i])
            t_idx += k
            yield batch, b.build()
