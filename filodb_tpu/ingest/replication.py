"""Partition replication: leader->follower frame streaming + quorum acks.

Reference: Kafka's ISR replication model (the reference outsources this to
Kafka; the capability list ingests "millions of series from Kafka, sharded
across a peer-to-peer cluster" with replicated, durable partitions). Here
the broker tier replicates its own logs:

  * every partition has a replica set of R broker nodes (deterministic from
    the shared ``peers`` list: replicas of partition p are
    ``peers[(p + i) % N] for i in range(R)``, leader = ``peers[p % N]``);
  * the node serving a publish appends locally, then STREAMS the appended
    frames to the other replicas over ``OP_REPLICATE`` (offset-contiguous,
    CRC-checked, pub-ids included so the follower's idempotence window
    matches the leader's) and acks the publisher only once every in-sync
    replica holds the frames — an ack means the data survives one node
    loss while the replica set is healthy;
  * a follower that keeps failing drops out of the in-sync set after
    ``FAIL_THRESHOLD`` consecutive failures (counted, not timed — the
    tests are deterministic) and is retried every ``rejoin_every`` calls;
    ``min_insync`` floors the in-sync count required to ack — below it the
    publish sheds with a typed RETRY (quorum-stall backpressure);
  * catch-up is the same op: the leader re-reads its log tail (with pub-ids
    from the per-partition journal) from the follower's watermark and
    replays it; torn/corrupt frames are detected by per-frame CRC32 at the
    follower and re-sent intact.

The per-partition :class:`PubIdJournal` (offset -> publish id) makes the
idempotence window durable: a restarted broker reloads its recent-id map,
catch-up carries ids to followers, and the ``ingest_soak`` audit
reconciles acked pub-ids against the surviving log with zero loss / zero
duplication.

Split-brain note: deterministic client-side failover (all publishers rank
survivors by watermark with a shared tie-break) keeps one writer per
partition in practice; a dead leader that RESTARTS with unreplicated tail
frames diverges and must rejoin empty (operator wipe) — the same contract
as a Kafka replica that lost its disk.
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import zlib

from ..utils.metrics import (FILODB_INGEST_REPLICATION_LAG, registry)
from ..utils.netio import recv_exact as _recv_exact
from ..utils.tracing import (SPAN_REPLICATE, SPAN_REPLICATE_SERVE, span,
                             tracer)
from .broker import (_REQ, _RESP, ST_ERR, ST_OK, _remember_id,
                     pack_trace_hdr, unpack_trace_hdr)

log = logging.getLogger("filodb_tpu.replication")

# replication stream op (16+ keeps clear air from the client ops in
# broker.py; values must stay distinct ACROSS modules — both are checked by
# filolint's op-parity rule)
OP_REPLICATE = 16

# one replicated frame: offset, publish id, payload crc32, payload length
_RENTRY = struct.Struct("<QQII")

# leadership-epoch block riding every OP_REPLICATE payload (after the trace
# block, before the frames): ``u64 epoch + u16 owner_len + owner bytes``.
# Epoch 0 = unfenced legacy mode. A follower holding a HIGHER epoch refuses
# the batch with the shared fenced-refusal message (cluster/gossip.py
# fence_message), which the leader parses to step down — the epoch fence
# that closes the spurious-failover split-brain window.
_EPOCH_HDR = struct.Struct("<QH")

_MAX_CATCHUP_BYTES = 4 << 20    # per-OP_REPLICATE payload bound


def pack_epoch_hdr(epoch: int, owner: str) -> bytes:
    raw = owner.encode()
    return _EPOCH_HDR.pack(int(epoch), len(raw)) + raw


def unpack_epoch_hdr(payload: bytes) -> tuple[int, str, bytes]:
    """(epoch, owner, rest-of-payload). A malformed block degrades to
    epoch 0 — an unfenced peer's stream still replicates."""
    try:
        epoch, ln = _EPOCH_HDR.unpack_from(payload, 0)
        body = payload[_EPOCH_HDR.size:]
        if ln > len(body):
            return 0, "", payload
        return int(epoch), body[:ln].decode(errors="replace"), body[ln:]
    except (struct.error, ValueError):
        return 0, "", payload


class ReplicationError(RuntimeError):
    """Follower rejected a replication batch (torn frame, bad partition)."""


class PubIdJournal:
    """Durable offset -> publish-id map per partition (sidecar file of
    fixed ``<QQ`` records). Appends ride the partition publish lock; a torn
    tail record is dropped on load exactly like a torn log frame.

    Bounded: only the newest ``max_entries`` records are retained (memory
    AND file, compacted by rewrite at 2x) — far larger than every
    idempotence window (``_RECENT_IDS_MAX``) and any sane replication lag,
    so retries and catch-up always find their ids while a long-lived
    broker's journal stays O(window), not O(lifetime ingest). Frames that
    age past the floor replicate with id 0 (no dedupe needed: they are
    beyond every replay window)."""

    REC = struct.Struct("<QQ")

    def __init__(self, path: str, max_entries: int = 1 << 16):
        self.path = path
        self.max_entries = int(max_entries)
        self._ids: dict[int, int] = {}      # insertion == offset order
        try:
            with open(path, "rb") as f:
                buf = f.read()
        except FileNotFoundError:
            buf = b""
        n = len(buf) // self.REC.size
        for i in range(max(0, n - self.max_entries), n):
            off, pid = self.REC.unpack_from(buf, i * self.REC.size)
            self._ids[off] = pid

    def append(self, off: int, pub_id: int) -> None:
        """Caller holds the partition's publish lock."""
        self.append_many([(off, pub_id)])

    def append_many(self, pairs) -> None:
        """ONE open + ONE write for a whole batch's (offset, pub_id)
        records — the journal must not re-open per frame on the
        PUBLISH_BATCH hot path (caller holds the publish lock)."""
        if not pairs:
            return
        blob = bytearray()
        for off, pid in pairs:
            self._ids[off] = pid
            blob += self.REC.pack(off, pid)
        with open(self.path, "ab") as f:
            f.write(blob)
        if len(self._ids) > 2 * self.max_entries:
            self._compact()

    def _compact(self) -> None:
        """Trim to the newest max_entries and rewrite the file (atomic
        rename; caller holds the publish lock). Amortized: one rewrite per
        max_entries appends."""
        for off in list(self._ids)[:len(self._ids) - self.max_entries]:
            del self._ids[off]
        blob = b"".join(self.REC.pack(off, pid)
                        for off, pid in self._ids.items())
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, self.path)

    def get(self, off: int) -> int:
        return self._ids.get(off, 0)

    def truncate_from(self, off: int) -> int:
        """Drop records at offsets >= ``off`` and rewrite the file (the
        journal twin of FileBus.truncate for REJOIN repair; caller holds
        the partition's publish lock). Returns records dropped."""
        doomed = [o for o in self._ids if o >= off]
        for o in doomed:
            del self._ids[o]
        if doomed:
            blob = b"".join(self.REC.pack(o, pid)
                            for o, pid in self._ids.items())
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, self.path)
        return len(doomed)

    def items(self) -> list[tuple[int, int]]:
        """(offset, pub_id) pairs in offset order — the audit surface."""
        return sorted(self._ids.items())

    def seed_recent(self, recent: dict[int, int], limit: int) -> None:
        """Reload the newest ``limit`` ids into a broker recent-ids map so
        publish-retry idempotence survives a broker restart."""
        for off, pid in self.items()[-limit:]:
            _remember_id(recent, pid, off, limit)


def pack_entries(entries) -> bytes:
    """[(offset, pub_id, frame bytes)] -> OP_REPLICATE payload."""
    return b"".join(
        _RENTRY.pack(off, pid, zlib.crc32(frame), len(frame)) + frame
        for off, pid, frame in entries)


def serve_replication(server, op: int, part: int, payload: bytes) -> bytes:
    """Follower-side dispatch for the replication op space (>= 16;
    BrokerServer._serve delegates here).

    OP_REPLICATE appends offset-contiguous frames, skips frames already
    held, stops at a gap (the leader resends from the returned watermark),
    and rejects CRC mismatches as torn frames. Responds ST_OK with the
    follower's end offset — its replication watermark."""
    if op != OP_REPLICATE:
        raise ValueError(f"unknown replication op {op}")
    # the leader's trace block rides ahead of the frames (stripped before
    # CRC/frame parsing, never appended to the log): the follower's append
    # span joins the original publish trace
    tctx, payload = unpack_trace_hdr(payload)
    with tracer.activate(tctx), \
            span(SPAN_REPLICATE_SERVE, partition=part, broker=server.port):
        return _serve_replication_traced(server, part, payload)


def _serve_replication_traced(server, part: int, payload: bytes) -> bytes:
    epoch, owner, payload = unpack_epoch_hdr(payload)
    epochs = getattr(server, "epochs", None)
    if epochs is not None:
        known, kowner = epochs.get(part)
        # LEXICOGRAPHIC (epoch, owner) ordering, matching
        # PartitionEpochs.adopt: an epoch TIE between two concurrent
        # claimants resolves to the higher owner address — the lower one
        # is refused here exactly like a stale epoch, so it steps down
        if known and (epoch, owner) < (known, kowner):
            # the sender is a deposed leader: refuse the batch with the
            # shared fenced message so it steps down instead of skipping
            from ..cluster.gossip import fence_message
            from ..utils.metrics import (FILODB_CLUSTER_FENCED_REJECTS,
                                         registry)
            registry.counter(FILODB_CLUSTER_FENCED_REJECTS,
                             {"site": "replicate"}).increment()
            msg = fence_message(part, known, kowner)
            return _RESP.pack(ST_ERR, 0, len(msg)) + msg.encode()
        if (epoch, owner) > (known, kowner):
            epochs.adopt(part, epoch, owner)
    bus = server._parts[part]
    with server._publish_locks[part]:
        end = bus.end_offset
        fresh: list[tuple[int, int, bytes]] = []    # (offset, pub_id, frame)
        pos = 0
        while pos < len(payload):
            off, pid, crc, ln = _RENTRY.unpack_from(payload, pos)
            pos += _RENTRY.size
            frame = payload[pos:pos + ln]
            pos += ln
            if len(frame) < ln:
                msg = f"torn replication frame at offset {off} (short read)"
                return _RESP.pack(ST_ERR, 0, len(msg)) + msg.encode()
            if zlib.crc32(frame) != crc:
                msg = f"torn replication frame at offset {off} (crc mismatch)"
                return _RESP.pack(ST_ERR, 0, len(msg)) + msg.encode()
            if off < end + len(fresh):
                continue                    # already replicated
            if off > end + len(fresh):
                break                       # gap: leader resends from `end`
            fresh.append((off, pid, frame))
        if fresh:
            bus.publish_many_bytes([f for _, _, f in fresh])
            recent = server._recent_ids[part]
            server._journals[part].append_many(
                [(off, pid) for off, pid, _f in fresh if pid])
            for off, pid, _f in fresh:
                if pid:
                    _remember_id(recent, pid, off, server._recent_ids_max)
        return _RESP.pack(ST_OK, bus.end_offset, 0)


class FollowerLink:
    """Leader-side client for ONE (partition, follower) replication stream.
    Tracks the follower's watermark (its acked end offset) and consecutive
    failures for ISR bookkeeping."""

    def __init__(self, addr: str, partition: int, fault_plan=None,
                 timeout_s: float = 5.0):
        host, _, port = addr.rpartition(":")
        self.addr = addr
        self._addr = (host or "127.0.0.1", int(port))
        self.partition = partition
        self.fault_plan = fault_plan
        self.timeout_s = timeout_s
        self._sock: socket.socket | None = None
        self.watermark: int | None = None   # None = unknown (probe first)
        self.fails = 0

    def _conn(self) -> socket.socket:
        if self._sock is None:
            # connect stalls run under the partition publish lock — bound
            # them harder than established-stream reads (a SYN-blackholed
            # follower must not freeze the partition's ingest for the full
            # stream timeout while it falls out of the in-sync set)
            self._sock = socket.create_connection(
                self._addr, timeout=min(1.0, self.timeout_s))
            self._sock.settimeout(self.timeout_s)
        return self._sock

    def reset(self) -> None:
        """Drop the connection and watermark after a failure: the next
        attempt reconnects and re-probes."""
        self.watermark = None
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def replicate(self, entries, epoch: int = 0, owner: str = "") -> int:
        """Stream [(offset, pub_id, frame)] to the follower under the
        leader's ``epoch``; returns (and caches) its watermark. Raises
        ConnectionError/ReplicationError on transport faults / rejection
        (a fenced rejection carries the follower's higher epoch)."""
        with span(SPAN_REPLICATE, partition=self.partition, peer=self.addr,
                  frames=len(entries)):
            return self._replicate_traced(entries, epoch, owner)

    def _replicate_traced(self, entries, epoch: int = 0,
                          owner: str = "") -> int:
        payload = pack_trace_hdr(tracer.current_context()) \
            + pack_epoch_hdr(epoch, owner) + pack_entries(entries)
        base = entries[0][0] if entries else 0
        try:
            s = self._conn()
            # fault decisions count only sends that actually reach the
            # wire with frames aboard — probes and refused connects must
            # not consume a rule's deterministic event budget
            torn = None
            if self.fault_plan is not None and entries:
                torn = self.fault_plan.decide("replicate",
                                              partition=self.partition,
                                              offset=base)
            if torn is not None and torn.action == "drop":
                raise ConnectionError("fault: replicate send dropped")
            if torn is not None and torn.action == "corrupt":
                payload = self.fault_plan.corrupt(payload)
            req = _REQ.pack(OP_REPLICATE, self.partition, base, len(payload))
            if torn is not None and torn.action == "torn_write":
                s.sendall((req + payload)[: _REQ.size + len(payload) // 2])
                raise ConnectionError("fault: torn replicate write")
            s.sendall(req + payload)
            st, off, rlen = _RESP.unpack(_recv_exact(s, _RESP.size))
            body = _recv_exact(s, rlen) if rlen else b""
        except (ConnectionError, OSError):
            self.reset()
            raise
        if st != ST_OK:
            # the follower speaks but rejects (torn frame, bad partition):
            # reset the stream so the retry re-reads + re-sends intact bytes
            self.reset()
            raise ReplicationError(body.decode(errors="replace"))
        self.watermark = off
        return off

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


class Replicator:
    """Leader-side replication driver for one BrokerServer node.

    ``ensure(part, target)`` pushes the log up to ``target`` to every other
    replica of the partition and answers whether the publish may ack
    (in-sync count >= min_insync). Called under the partition's publish
    lock, so follower streams stay ordered per partition."""

    FAIL_THRESHOLD = 3      # consecutive failures before a follower leaves
                            # the in-sync set (counted — deterministic)

    def __init__(self, server, peers: list[str], node_index: int,
                 replication: int, min_insync: int = 1,
                 fault_plan=None, rejoin_every: int = 8):
        self.server = server
        self.peers = list(peers)
        self.node_index = int(node_index)
        self.replication = max(1, min(int(replication), len(self.peers)))
        self.min_insync = max(1, int(min_insync))
        self.fault_plan = fault_plan
        self.rejoin_every = max(1, int(rejoin_every))
        self._links: dict[tuple[int, int], FollowerLink] = {}
        self._skips: dict[tuple[int, int], int] = {}

    def replica_indexes(self, part: int) -> list[int]:
        n = len(self.peers)
        return [(part + i) % n for i in range(self.replication)]

    def follower_indexes(self, part: int) -> list[int]:
        return [i for i in self.replica_indexes(part) if i != self.node_index]

    def _link(self, part: int, idx: int) -> FollowerLink:
        key = (part, idx)
        link = self._links.get(key)
        if link is None:
            link = FollowerLink(self.peers[idx], part,
                                fault_plan=self.fault_plan)
            self._links[key] = link
        return link

    def ensure(self, part: int, target: int, fresh=None) -> tuple[bool, int]:
        """Push partition ``part`` up to end offset ``target`` on every
        follower; returns (acked, retry_hint_ms). ``fresh`` optionally
        carries the just-appended (offset, pub_id, frame) entries so the
        steady state skips the log re-read."""
        insync = 1                          # self
        # our leadership epoch rides every batch; followers holding a higher
        # epoch refuse it and we step down (adopt + report not-acked)
        epoch, owner = (self.server.epochs.get(part)
                        if getattr(self.server, "epochs", None) is not None
                        else (0, ""))
        for idx in self.follower_indexes(part):
            link = self._link(part, idx)
            key = (part, idx)
            if link.fails >= self.FAIL_THRESHOLD:
                # out of the in-sync set: retry only every rejoin_every-th
                # publish so a dead peer doesn't tax every ack with a
                # connect attempt — and bound the rejoin probe's connect
                # stall hard (it runs under the partition publish lock; a
                # packet-dropping peer must not freeze ingest for the full
                # steady-state timeout)
                n = self._skips.get(key, 0) + 1
                self._skips[key] = n
                if n % self.rejoin_every:
                    self._lag_gauge(part, link).update(
                        float(target - (link.watermark or 0)))
                    continue
                link.timeout_s = 1.0
            else:
                link.timeout_s = 5.0
            try:
                wm = link.watermark
                if wm is None:
                    wm = link.replicate([], epoch, owner)   # probe
                while wm < target:
                    if fresh and fresh[0][0] == wm and \
                            sum(len(f) for _o, _p, f in fresh) \
                            <= _MAX_CATCHUP_BYTES:
                        batch = fresh       # steady state, byte-bounded —
                        # an oversized publish burst falls through to the
                        # chunked log read below
                    else:
                        batch = self.server._frames_with_ids(
                            part, wm, target, _MAX_CATCHUP_BYTES)
                    if not batch:
                        raise ReplicationError(
                            f"no frames to replicate at watermark {wm}")
                    new_wm = link.replicate(batch, epoch, owner)
                    if new_wm <= wm:
                        raise ReplicationError(
                            f"follower {link.addr} made no progress "
                            f"(watermark {new_wm})")
                    wm = new_wm
                link.fails = 0
                self._skips[key] = 0
                insync += 1
            except (ConnectionError, OSError, ReplicationError) as e:
                link.fails += 1
                link.reset()
                if isinstance(e, ReplicationError):
                    self._maybe_step_down(part, str(e))
                log.warning("replication to %s for partition %d failed "
                            "(%d consecutive): %s", self.peers[idx], part,
                            link.fails, e)
            self._lag_gauge(part, link).update(
                float(target - (link.watermark or 0)))
        return insync >= self.min_insync, 100

    def _maybe_step_down(self, part: int, msg: str) -> None:
        """A follower refused a batch with a fenced message: adopt the
        higher epoch so this node's publish path refuses further acks —
        the deposed leader steps down the moment it learns of its
        deposition."""
        epochs = getattr(self.server, "epochs", None)
        if epochs is None:
            return
        from ..cluster.gossip import parse_fenced
        parsed = parse_fenced(msg)
        if parsed is None:
            return
        fpart, fepoch, fowner = parsed
        if fpart == part and epochs.adopt(part, fepoch, fowner):
            log.warning("partition %d: stepped down — follower fenced us at "
                        "epoch %d (owner %s)", part, fepoch, fowner)

    def _lag_gauge(self, part: int, link: FollowerLink):
        return registry.gauge(FILODB_INGEST_REPLICATION_LAG,
                              {"partition": str(part), "peer": link.addr})

    def close(self) -> None:
        for link in self._links.values():
            link.close()
        self._links.clear()
