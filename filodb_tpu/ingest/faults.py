"""Deterministic fault injection for the ingest plane.

Reference posture: the reference validates its Kafka ingestion with
chaos-style integration jobs; here faults are FIRST-CLASS and deterministic
so tier-1 tests (and the ``ingest_soak`` bench scenario) can kill a leader
at an exact log offset, drop exactly the 3rd response, or corrupt exactly
one replication frame — with NO wall-clock dependence and NO luck.

A :class:`FaultPlan` is a list of :class:`FaultRule`. Hook sites call
``plan.decide(site, partition=..., op=..., offset=...)``; matching is
COUNTER-based (the nth matching event at that site fires the rule), so a
plan replays identically run to run. The plan's RNG exists only for
actions that need bytes to corrupt — seeded, never time-derived.

Hook sites wired in this package:

  ``append``      broker, after a partition append (ctx: partition, offset
                  = new end offset) — ``kill_server`` implements
                  kill-at-offset leader death.
  ``serve``       broker, before sending a response (ctx: partition, op) —
                  ``drop_response`` severs without replying (the
                  lost-response shape), ``delay`` holds the response.
  ``replicate``   leader->follower stream, before sending a frame batch —
                  ``torn_write`` truncates mid-frame and severs,
                  ``corrupt`` flips a payload byte (CRC mismatch at the
                  follower), ``drop`` fails the send outright.
  ``client_recv`` BrokerBus, between send and response read —
                  ``drop_response`` closes the socket (client-side lost
                  response; the windowed publisher must replay).
"""

from __future__ import annotations

import random
import threading


class FaultRule:
    """One deterministic fault: fire on the nth..nth+count-1 matching
    events at ``site``. ``partition``/``op`` filter events; ``at_offset``
    matches only events whose offset reached it (kill-at-offset)."""

    __slots__ = ("site", "action", "nth", "count", "partition", "op",
                 "at_offset", "delay_s")

    def __init__(self, site: str, action: str, nth: int = 1, count: int = 1,
                 partition: int | None = None, op: int | None = None,
                 at_offset: int | None = None, delay_s: float = 0.0):
        self.site = site
        self.action = action
        self.nth = int(nth)
        self.count = count          # None = keep firing forever
        self.partition = partition
        self.op = op
        self.at_offset = at_offset
        self.delay_s = float(delay_s)

    def matches(self, partition, op, offset) -> bool:
        if self.partition is not None and partition != self.partition:
            return False
        if self.op is not None and op != self.op:
            return False
        if self.at_offset is not None and (offset is None
                                           or offset < self.at_offset):
            return False
        return True


class FaultPlan:
    """Deterministic fault schedule. ``decide`` returns the fired rule (or
    None); ``fired`` logs every firing for test assertions."""

    def __init__(self, rules: list[FaultRule] | tuple = (), seed: int = 0):
        self.rules = list(rules)
        self.rng = random.Random(seed)      # NEVER wall-clock seeded
        self.fired: list[tuple[str, str, dict]] = []
        self._counts: dict[int, int] = {}   # rule id -> matching events seen
        self._lock = threading.Lock()

    @classmethod
    def from_spec(cls, spec: list[dict] | None, seed: int = 0) -> "FaultPlan":
        """Build from config (``ingest.faults``): a list of rule dicts with
        the FaultRule field names."""
        rules = [FaultRule(**dict(r)) for r in (spec or [])]
        return cls(rules, seed=seed)

    def decide(self, site: str, partition=None, op=None,
               offset=None) -> FaultRule | None:
        with self._lock:
            for i, r in enumerate(self.rules):
                if r.site != site or not r.matches(partition, op, offset):
                    continue
                n = self._counts.get(i, 0) + 1
                self._counts[i] = n
                if n < r.nth:
                    continue
                if r.count is not None and n >= r.nth + r.count:
                    continue
                self.fired.append((site, r.action,
                                   {"partition": partition, "op": op,
                                    "offset": offset, "event": n}))
                return r
        return None

    def corrupt(self, payload: bytes) -> bytes:
        """Flip one byte (position from the seeded RNG — deterministic for
        a given plan instance and call sequence)."""
        if not payload:
            return payload
        i = self.rng.randrange(len(payload))
        b = bytearray(payload)
        b[i] ^= 0xFF
        return bytes(b)


def plan_from_config(cfg) -> FaultPlan | None:
    """``ingest.faults`` config -> FaultPlan (None when no rules: the hot
    paths skip the hook entirely)."""
    spec = cfg.get("ingest.faults")
    if not spec:
        return None
    return FaultPlan.from_spec(spec)
