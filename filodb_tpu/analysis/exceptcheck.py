"""Except-flow checker: typed-error discipline on broad handlers.

The typed ``QueryError`` hierarchy is the repo's error *protocol*: the
HTTP layer classifies it into status codes, the dispatch layer into
replan/shed decisions. A broad ``except Exception`` between the raise and
the classifier silently downgrades the protocol — and a swallow-all
handler on an ingest/commit path turns data loss into a no-op. Three
rules, all running on the shared interprocedural facts
(analysis/callgraph.py):

  * ``except-swallow`` — a broad handler (``except Exception`` /
    ``except BaseException`` / bare) whose body leaves NO observable
    trace: no raise, no call (logging, counter, cleanup helper), no
    assignment. ``pass``/``continue``/bare-``return`` bodies silently
    drop errors; every such site must either narrow the type, leave a
    trace (the ``filodb_swallowed_errors`` counter exists for exactly
    this), or carry an inline suppression with a reason.
  * ``except-overbroad-typed`` — a broad handler catching a try body
    that MAY RAISE a typed ``QueryError`` descendant (computed
    interprocedurally through helper calls, filtered by intermediate
    handlers), where no PRECEDING handler in the chain names the typed
    class (or an ancestor), and the broad handler neither re-raises nor
    forwards the exception object. Thread entry points are exempt —
    they are sinks; nothing above them can classify.
  * ``except-state-leak`` — the two-phase-commit shape: state CLAIMED
    under a lock (``self.X.pop(...)`` / ``.remove(...)`` inside ``with
    self.<lock>:``) before or inside a try whose broad handler neither
    re-raises nor restores the claimed attribute (directly or via one
    helper call). The claim dies with the handler and the rows are
    gone — memstore's flush requeue and the downsampler's claim-restore
    are the positive patterns.
"""

from __future__ import annotations

import ast

from .callgraph import (PackageIndex, attr_root, catching_names,
                        handler_is_observable, handler_names,
                        is_broad_handler, leaf_name)
from .findings import Finding

ERROR_ROOT = "QueryError"
CLAIM_METHODS = {"pop", "popitem", "remove", "popleft"}
LOCK_ATTRS = {"lock", "_lock", "owner_lock", "_sink_lock"}


_handler_observable = handler_is_observable   # shared definition (callgraph)


def _own_trys(fn: ast.AST) -> list[ast.Try]:
    """Try statements belonging to THIS function only. Nested defs are
    their own FuncUnits (analyzed with their own sink status); re-walking
    them from the enclosing unit would both duplicate findings and drop a
    worker closure's thread-entry exemption."""
    out: list[ast.Try] = []

    def rec(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Try):
                out.append(child)
            rec(child)

    rec(fn)
    return out


def _handler_reraises_or_forwards(handler: ast.ExceptHandler) -> bool:
    """Bare `raise`, `raise X(...) from e`, or the bound exception object
    passed onward (fut.set_exception(e), out.append(e), log(..., e))."""
    bound = handler.name
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return True
            if bound and isinstance(node, ast.Call):
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name) and sub.id == bound:
                            return True
    return False


def _self_attr_root(expr: ast.expr) -> str | None:
    return attr_root(expr, receivers=("self",))


def _claims_in(stmts: list[ast.stmt]) -> dict[str, int]:
    """Attr roots claimed (popped/removed from a self collection) inside a
    `with self.<lock>:` block within these statements -> first line."""
    out: dict[str, int] = {}
    for stmt in stmts:
        for node in ast.walk(stmt):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            locked = any(
                isinstance(item.context_expr, ast.Attribute)
                and item.context_expr.attr in LOCK_ATTRS
                for item in node.items)
            if not locked:
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in CLAIM_METHODS:
                    root = _self_attr_root(sub.func.value)
                    if root:
                        out.setdefault(root, sub.lineno)
    return out


class _TypedEscapes(ast.NodeVisitor):
    """Typed exception names that can ESCAPE a statement list: direct raises
    plus resolved callees' may-raise sets, filtered by nested try handlers
    encountered on the way (and not collected from nested defs, whose raises
    don't execute inline)."""

    def __init__(self, index: PackageIndex, unit, typed: set,
                 may_raise: dict):
        self.index = index
        self.u = unit
        self.typed = typed
        self.may_raise = may_raise
        self.out: set = set()
        self._caught: list[frozenset] = []

    def _escapes(self, exc: str) -> bool:
        return not any(self.index.catches(frame, exc)
                       for frame in self._caught)

    def visit_Try(self, node: ast.Try):  # noqa: N802
        # re-raising handlers don't terminate the exception (shared
        # catching_names semantics with the may-raise fixpoint)
        names = catching_names(node.handlers)
        self._caught.append(names)
        for stmt in node.body:
            self.visit(stmt)
        self._caught.pop()
        # handler bodies and finally re-raise to the OUTER context; orelse
        # runs only when nothing raised, and this try's handlers don't
        # cover it either
        for part in (node.handlers, node.orelse, node.finalbody):
            for sub in part:
                body = sub.body if isinstance(sub, ast.ExceptHandler) else [sub]
                for stmt in body:
                    self.visit(stmt)

    visit_TryStar = visit_Try

    def visit_Raise(self, node: ast.Raise):  # noqa: N802
        exc = node.exc
        if isinstance(exc, ast.Call):
            exc = exc.func
        n = leaf_name(exc) if exc is not None else None
        if n in self.typed and self._escapes(n):
            self.out.add(n)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):  # noqa: N802
        key = self.index.resolve_call(self.u.path, self.u.cls, node)
        if key and key in self.may_raise:
            for exc in self.may_raise[key]:
                if self._escapes(exc):
                    self.out.add(exc)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):  # noqa: N802
        pass        # a nested def's body doesn't raise at definition time

    visit_AsyncFunctionDef = visit_FunctionDef


class ExceptChecker:
    rules = ("except-swallow", "except-overbroad-typed", "except-state-leak")

    def __init__(self, error_root: str = ERROR_ROOT):
        self.error_root = error_root
        self._modules: dict[str, ast.Module] = {}
        self.project: PackageIndex | None = None

    def check_module(self, path: str, tree: ast.Module) -> list[Finding]:
        self._modules[path] = tree
        return []

    def finalize(self) -> list[Finding]:
        index = self.project or PackageIndex(self._modules)
        typed = index.descendants_of(self.error_root)
        may_raise = index.may_raise(typed_only=typed) if typed else {}
        findings: list[Finding] = []
        for key, u in sorted(index.funcs.items()):
            if u.path not in self._modules:
                continue
            findings += self._check_func(u, index, typed, may_raise)
        return findings

    def _check_func(self, u, index: PackageIndex, typed: set,
                    may_raise: dict) -> list[Finding]:
        findings: list[Finding] = []
        is_sink = u.key in index.thread_entries or u.name == "__del__"
        for node in _own_trys(u.node):
            findings += self._check_try(u, node, index, typed, may_raise,
                                        is_sink)
        return findings

    def _check_try(self, u, node: ast.Try, index: PackageIndex, typed: set,
                   may_raise: dict, is_sink: bool) -> list[Finding]:
        findings: list[Finding] = []
        # typed classes the try body can raise (direct + via resolved calls,
        # minus anything an inner handler already caught — the collector
        # tracks nested try frames, so a defensive inner `except QueryError`
        # keeps the outer broad handler clean)
        collector = _TypedEscapes(index, u, typed, may_raise)
        for stmt in node.body:
            collector.visit(stmt)
        body_typed = collector.out
        seen_names: set = set()
        for h in node.handlers:
            names = set(handler_names(h))
            if is_broad_handler(h):
                if not _handler_observable(h):
                    findings.append(Finding(
                        "except-swallow", u.path, h.lineno, u.qualname,
                        f"swallow:{h.lineno - u.node.lineno}",
                        "broad except with no observable action silently "
                        "drops the error — narrow the type, log/count it "
                        "(filodb_swallowed_errors), or suppress inline "
                        "with a reason"))
                uncovered = {t for t in body_typed
                             if t not in seen_names
                             and not (index.ancestry(t) & seen_names)}
                if uncovered and not is_sink \
                        and not _handler_reraises_or_forwards(h):
                    sample = sorted(uncovered)[0]
                    findings.append(Finding(
                        "except-overbroad-typed", u.path, h.lineno,
                        u.qualname, f"overbroad:{sample}",
                        f"broad except catches typed {sorted(uncovered)} "
                        f"(the {self.error_root} protocol) without a "
                        "preceding typed handler and without re-raising or "
                        "forwarding — upstream classification (HTTP status, "
                        "replan/shed) is silently lost"))
                findings += self._check_state_leak(u, node, h)
            seen_names |= names
        return findings

    def _check_state_leak(self, u, node: ast.Try,
                          h: ast.ExceptHandler) -> list[Finding]:
        # claims: inside the try body, or in the with-block immediately
        # preceding the try in the same statement list
        claims = _claims_in(node.body)
        prev = self._prev_sibling(u.node, node)
        if prev is not None:
            claims = {**_claims_in([prev]), **claims}
        if not claims:
            return []
        restored = self._restored_attrs(u, h)
        for stmt in h.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Raise):
                    return []
        leaked = {a: ln for a, ln in claims.items() if a not in restored}
        if len(leaked) < len(claims):
            return []        # some claimed state restored: treated as handled
        attr, line = sorted(leaked.items())[0]
        return [Finding(
            "except-state-leak", u.path, h.lineno, u.qualname,
            f"leak:{attr}",
            f"state claimed from self.{attr} under a lock before/inside "
            "this try is neither restored nor re-raised in the broad "
            "handler — a publish/commit failure silently drops the claimed "
            "rows; restore the claim (see downsample._emit_complete) or "
            "re-raise")]

    @staticmethod
    def _prev_sibling(fn: ast.AST, target: ast.Try) -> ast.stmt | None:
        for node in ast.walk(fn):
            body = getattr(node, "body", None)
            for part in (body, getattr(node, "orelse", None),
                         getattr(node, "finalbody", None)):
                if not isinstance(part, list):
                    continue
                for i, stmt in enumerate(part):
                    if stmt is target:
                        return part[i - 1] if i else None
        return None

    def _restored_attrs(self, u, h: ast.ExceptHandler) -> set:
        out: set = set()
        index = self.project
        for stmt in h.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.target]
                    for t in targets:
                        root = _self_attr_root(t)
                        if root:
                            out.add(root)
                if isinstance(sub, ast.Call):
                    if isinstance(sub.func, ast.Attribute):
                        root = _self_attr_root(sub.func.value)
                        if root and sub.func.attr in (
                                "update", "extend", "append", "add",
                                "setdefault", "insert", "appendleft"):
                            out.add(root)
                    # one helper hop: self._requeue_...() restoring the attr
                    if index is not None:
                        key = index.resolve_call(u.path, u.cls, sub)
                        uu = index.funcs.get(key) if key else None
                        if uu is not None:
                            for n2 in ast.walk(uu.node):
                                root = None
                                if isinstance(n2, (ast.Assign, ast.AugAssign)):
                                    tgts = n2.targets if isinstance(
                                        n2, ast.Assign) else [n2.target]
                                    for t in tgts:
                                        root = _self_attr_root(t) or root
                                elif isinstance(n2, ast.Call) and \
                                        isinstance(n2.func, ast.Attribute):
                                    root = _self_attr_root(n2.func.value)
                                if root:
                                    out.add(root)
        return out
