"""index-pure-python-postings: the vectorized-ops-only contract of the
columnar index modules.

The part-key index's postings plane (``core/index*.py`` — the columnar
engine of ISSUE 15) exists because per-element Python iteration over
posting arrays is exactly what cannot survive 1M series: one innocuous
``for pid in postings:`` in the hot module quietly turns an O(words)
bitmap AND back into an interpreter loop, and no unit test notices until a
production shard does. This rule makes the contract structural: inside any
module whose basename matches ``index*.py`` (fixture twins carry a
``bad_``/``good_`` prefix), a ``for`` statement or comprehension whose
ITERABLE mentions a posting identifier (any name or attribute containing
"posting", or a ``.tolist()`` of one) is a finding. Loops over terms,
staged segment lists, or trigram codes are fine — only the posting arrays
themselves are ops-only."""

from __future__ import annotations

import ast
import re

from .findings import Finding

# the hot-module scope: core/index*.py (the columnar engine and future
# index_* modules) plus the fixture twins — NOT every module that happens
# to be named index*.py (this checker included)
_INDEX_MODULE = re.compile(
    r"(?:^|/)core/index[^/]*\.py$"
    r"|(?:^|/)fixtures/filolint/(?:bad_|good_)index[^/]*\.py$")

_POSTING = re.compile("posting", re.IGNORECASE)


def _mentions_postings(expr: ast.expr) -> str | None:
    """The first posting-ish identifier inside ``expr``, or None."""
    for node in ast.walk(expr):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name is not None and _POSTING.search(name):
            return name
    return None


class IndexChecker:
    rules = ("index-pure-python-postings",)

    def __init__(self):
        self.project = None          # unused; kept for checker symmetry

    def check_module(self, path: str, tree: ast.Module) -> list[Finding]:
        if not _INDEX_MODULE.search(path):
            return []
        findings: list[Finding] = []
        for node in ast.walk(tree):
            iters: list[ast.expr] = []
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                iters.extend(g.iter for g in node.generators)
            for it in iters:
                name = _mentions_postings(it)
                if name is None:
                    continue
                findings.append(Finding(
                    "index-pure-python-postings", path, node.lineno,
                    self._enclosing(tree, node), f"loop:{name}",
                    f"per-element Python loop over posting array {name!r} "
                    "in a columnar index module — postings are "
                    "vectorized-ops-only (bitmap algebra, searchsorted "
                    "merges, fancy-index gathers); an interpreter loop "
                    "here is the 1M-series bottleneck the module exists "
                    "to prevent"))
        return findings

    def finalize(self) -> list[Finding]:
        return []

    @staticmethod
    def _enclosing(tree: ast.Module, target: ast.AST) -> str:
        best = "<module>"
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                for sub in ast.walk(node):
                    if sub is target:
                        best = node.name if best == "<module>" \
                            else f"{best}.{node.name}"
                        break
        return best
