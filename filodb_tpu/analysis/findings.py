"""Finding model, inline suppressions, and the checked-in baseline.

A finding's identity for baseline matching is (rule, file, symbol, detail) —
deliberately NOT the line number, so a baseline entry survives unrelated
edits to the file. ``detail`` is a short stable key chosen by each checker
(the attribute written, the callee name, the missing tag...).
"""

from __future__ import annotations

import json
import re
from dataclasses import asdict, dataclass
from pathlib import Path

SUPPRESS_RE = re.compile(r"#\s*filolint:\s*ignore\[([A-Za-z0-9_\-*,\s]+)\]")
SKIP_FILE_RE = re.compile(r"#\s*filolint:\s*skip-file")

# the meta-rule reported when an inline ignore no longer suppresses
# anything (see runner._stale_ignores)
STALE_IGNORE_RULE = "filolint-stale-ignore"


@dataclass(frozen=True)
class Finding:
    rule: str          # e.g. "lock-unheld-call"
    path: str          # repo-relative posix path
    line: int          # 1-based
    symbol: str        # enclosing qualname ("Class.method", "func", "<module>")
    detail: str        # stable short key for baseline identity
    message: str

    @property
    def fingerprint(self) -> tuple:
        return (self.rule, self.path, self.symbol, self.detail)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.symbol}: {self.message}"


def load_suppressions(source: str) -> dict[int, set[str]]:
    """line (1-based) -> set of suppressed rule names ("*" = all).

    A whole-file opt-out (``# filolint: skip-file`` in the first 5 lines)
    maps to line 0 carrying {"*"}."""
    out: dict[int, set[str]] = {}
    lines = source.splitlines()
    for head in lines[:5]:
        if SKIP_FILE_RE.search(head):
            out[0] = {"*"}
    for i, text in enumerate(lines, start=1):
        m = SUPPRESS_RE.search(text)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def is_suppressed(f: Finding, supp: dict[int, set[str]]) -> bool:
    if 0 in supp:
        return True
    rules = supp.get(f.line)
    if not rules:
        return False
    if f.rule == STALE_IGNORE_RULE:
        # a stale-ignore finding points AT an ignore comment; letting that
        # comment's own ``*`` (or the stale rule name it carries) swallow
        # the finding would make the rule unfireable. Only an ignore that
        # names the meta-rule explicitly counts as an accepted exception.
        return STALE_IGNORE_RULE in rules
    return "*" in rules or f.rule in rules


class Baseline:
    """Checked-in list of intentionally-kept findings, each with a reason.

    Format (filolint_baseline.json):
        {"entries": [{"rule": ..., "file": ..., "symbol": ..., "detail": ...,
                      "reason": "why this one stays"}]}
    """

    def __init__(self, entries: list[dict] | None = None):
        self.entries = entries or []
        self._index = {(e["rule"], e["file"], e["symbol"], e["detail"])
                       for e in self.entries}

    @classmethod
    def load(cls, path: Path | str | None) -> "Baseline":
        if path is None:
            return cls()
        p = Path(path)
        if not p.exists():
            return cls()
        data = json.loads(p.read_text())
        return cls(data.get("entries", []))

    def covers(self, f: Finding) -> bool:
        return f.fingerprint in self._index

    @staticmethod
    def write(path: Path | str, findings: list[Finding],
              reason: str | None = None,
              keep: list[dict] | None = None) -> None:
        """Write ``findings`` (appended to ``keep``) as baseline entries.

        A baseline entry is a PROMISE that the finding is intentional, so a
        reason is mandatory — callers without one are refused (the
        ``--update-baseline`` CLI surfaces this as an error instead of
        writing 'TODO' placeholders that nobody ever fills in)."""
        if findings and not (reason and reason.strip()):
            raise ValueError(
                "baseline entries require a reason — pass --reason "
                "'why these findings are intentional'")
        entries = list(keep or [])
        entries += [{"rule": f.rule, "file": f.path, "symbol": f.symbol,
                     "detail": f.detail, "reason": reason}
                    for f in findings]
        Path(path).write_text(json.dumps({"entries": entries}, indent=2) + "\n")


def as_json(findings: list[Finding]) -> str:
    return json.dumps([asdict(f) for f in findings], indent=2)


def report_json(report) -> str:
    """Machine-readable report: per-bucket findings + counts (the shape CI
    annotators and the bench harness consume)."""
    return json.dumps({
        "files_analyzed": report.files_analyzed,
        "counts": {"new": len(report.new),
                   "suppressed": len(report.suppressed),
                   "baselined": len(report.baselined)},
        "new": [asdict(f) for f in report.new],
        "suppressed": [asdict(f) for f in report.suppressed],
        "baselined": [asdict(f) for f in report.baselined],
    }, indent=2)


SARIF_RULE_HELP = "see ANALYSIS.md for the invariant behind each rule"


def report_sarif(report, rule_ids: tuple) -> str:
    """SARIF 2.1.0 — the interchange format GitHub code scanning, VS Code
    SARIF viewers and most CI annotators ingest. Only NEW findings are
    results (suppressed/baselined are accepted states, not alerts)."""
    return json.dumps({
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                    "master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "filolint",
                "informationUri": "ANALYSIS.md",
                "rules": [{"id": r,
                           "shortDescription": {"text": SARIF_RULE_HELP}}
                          for r in rule_ids],
            }},
            "results": [{
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": f"{f.symbol}: {f.message}"},
                "partialFingerprints": {
                    "filolint/v1": "/".join(map(str, f.fingerprint))},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line)},
                }}],
            } for f in report.new],
        }],
    }, indent=2)
