"""Wire-protocol exhaustiveness checker (query/wire.py and its consumers).

The tagged-binary result codec and the plan envelope are a convention pair:
encoder and decoder live in the same file but nothing forces them to agree.
PR 2's batched dispatch made the failure mode concrete — a tag encoded but
not decoded surfaces as "unknown remote result tag" on the PEER'S caller,
i.e. a cross-node incident, not a unit-test failure.

  * ``wire-tag-parity`` — every single-byte tag literal the encode side
    (serialize_result / pack_multipart) emits must be matched on the decode
    side (deserialize_result / unpack_multipart), and vice versa. The same
    rule also covers REQUEST-OP constants of the framework's TCP services
    (``op_specs`` below): every ``OP_*`` constant a module defines must be
    dispatched by the server function AND sent by the client class — a new
    op wired on only one side is a live protocol desync — and two op names
    must never share a value.
  * ``wire-nesting-bound`` — the plan envelope's nesting bound must be ONE
    shared module constant compared on both _enc_plan and _dec_plan (a
    literal on either side lets the sides drift: the planner would ship
    plans the peer rejects).
  * ``wire-error-classified`` — every typed error wire.py raises
    (QueryError subclasses + QueryError itself) must be classified by the
    HTTP dispatch table (the except-chain in http/api.py) either directly or
    via a handled ancestor, and a subclass handler must come BEFORE its
    ancestor's (Python takes the first matching except — a dead subclass
    handler silently degrades a 503 to a 422).
  * ``wire-trace-parity`` — the trace-context carriers (the /exec
    ``TRACE_HEADER`` and the broker/replication ``pack_trace_hdr`` /
    ``unpack_trace_hdr`` payload blocks) must be referenced on EVERY side
    listed in ``trace_specs``: an inject without its extract (or vice
    versa) silently severs cross-node traces — or worse, leaves the
    receiver parsing a payload whose first bytes it no longer strips.

The function/file names checked are configured in ``WIRE_SPEC`` below —
extend it when a new codec pair appears.
"""

from __future__ import annotations

import ast

from .findings import Finding

WIRE_SPEC = {
    "wire_module": "filodb_tpu/query/wire.py",
    "classifier_module": "filodb_tpu/http/api.py",
    "error_base_modules": ["filodb_tpu/query/rangevector.py"],
    # (encode fn, decode fn) pairs whose 1-byte bytes literals must agree
    "codec_pairs": [("serialize_result", "deserialize_result"),
                    ("pack_multipart", "unpack_multipart")],
    # functions that must share one named depth-bound constant
    "depth_pair": ("_enc_plan", "_dec_plan"),
    # the root of the typed-error hierarchy the HTTP layer classifies
    "error_root": "QueryError",
    # request-op constant parity for the framework's TCP services: every
    # `prefix`-named module constant must be dispatched in `server_fn` and
    # sent from `client_class`
    "op_specs": [
        {"module": "filodb_tpu/ingest/broker.py", "prefix": "OP_",
         "server_fn": "_serve", "client_class": "BrokerBus"},
        # the replication stream: OP_REPLICATE lives in replication.py with
        # both its sender (FollowerLink) and its dispatch (serve_replication,
        # delegated to from BrokerServer._serve)
        {"module": "filodb_tpu/ingest/replication.py", "prefix": "OP_",
         "server_fn": "serve_replication", "client_class": "FollowerLink"},
        # the durable chunk tier (PR 10): every StoreServer op — including
        # the streaming OP_APPEND_CRC and atomic OP_CHECKPOINT — must be
        # dispatched by StoreServer._serve AND sent by the RemoteStore
        # client; a one-sided op is a live flush/recovery protocol desync
        {"module": "filodb_tpu/core/diststore.py", "prefix": "OP_",
         "server_fn": "_serve", "client_class": "RemoteStore"},
        # the elastic-cluster op family (PR 12): gossip digests, epoch
        # read/claim/announce, and the REJOIN log sync all live in
        # cluster/gossip.py with serve_cluster as the one dispatch (brokers
        # and GossipServers both delegate there) and ClusterLink as the one
        # sender — a one-sided op desyncs failover or membership
        {"module": "filodb_tpu/cluster/gossip.py", "prefix": "OP_",
         "server_fn": "serve_cluster", "client_class": "ClusterLink"},
    ],
    # trace-context carrier parity: every (module, scope) side must
    # reference the symbol — scopes are function OR class names, so the
    # sender may be a whole client class (BrokerBus packs inside its group
    # sender) while the receiver is one dispatch function
    "trace_specs": [
        {"symbol": "TRACE_HEADER",
         "sides": [["filodb_tpu/query/wire.py", "_dispatch_post_traced"],
                   ["filodb_tpu/http/api.py", "_trace_ctx"]]},
        {"symbol": "pack_trace_hdr",
         "sides": [["filodb_tpu/ingest/broker.py", "BrokerBus"],
                   ["filodb_tpu/ingest/replication.py", "FollowerLink"]]},
        {"symbol": "unpack_trace_hdr",
         "sides": [["filodb_tpu/ingest/broker.py", "_serve"],
                   ["filodb_tpu/ingest/replication.py",
                    "serve_replication"]]},
    ],
}


def _functions(tree: ast.Module) -> dict[str, ast.FunctionDef]:
    return {n.name: n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _byte_tags(fn: ast.FunctionDef) -> dict[bytes, int]:
    """All single-byte bytes literals in a function -> first line seen."""
    out: dict[bytes, int] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Constant) and isinstance(node.value, bytes) \
                and len(node.value) == 1:
            out.setdefault(node.value, node.lineno)
    return out


class WireChecker:
    rules = ("wire-tag-parity", "wire-nesting-bound", "wire-error-classified",
             "wire-trace-parity")

    def __init__(self, spec: dict | None = None):
        self.spec = spec or WIRE_SPEC
        self._modules: dict[str, ast.Module] = {}

    def check_module(self, path: str, tree: ast.Module) -> list[Finding]:
        # cross-file rule: stash and run in finalize
        self._modules[path] = tree
        return []

    def finalize(self) -> list[Finding]:
        findings: list[Finding] = []
        wire_path = self.spec["wire_module"]
        wire = self._modules.get(wire_path)
        if wire is not None:
            fns = _functions(wire)
            findings += self._tag_parity(wire_path, fns)
            findings += self._nesting_bound(wire_path, wire, fns)
            findings += self._error_classified(wire_path, wire)
        for op_spec in self.spec.get("op_specs", ()):
            tree = self._modules.get(op_spec["module"])
            if tree is not None:
                findings += self._op_parity(op_spec, tree)
        for t_spec in self.spec.get("trace_specs", ()):
            findings += self._trace_parity(t_spec)
        return findings

    # -- tags --------------------------------------------------------------

    def _tag_parity(self, path: str,
                    fns: dict[str, ast.FunctionDef]) -> list[Finding]:
        findings = []
        for enc_name, dec_name in self.spec["codec_pairs"]:
            enc, dec = fns.get(enc_name), fns.get(dec_name)
            if enc is None or dec is None:
                missing = enc_name if enc is None else dec_name
                findings.append(Finding(
                    "wire-tag-parity", path, 1, "<module>",
                    f"missing-fn:{missing}",
                    f"codec function {missing}() not found — update "
                    "analysis/wirecheck.WIRE_SPEC if it moved"))
                continue
            etags, dtags = _byte_tags(enc), _byte_tags(dec)
            for tag, line in sorted(etags.items()):
                if tag not in dtags:
                    findings.append(Finding(
                        "wire-tag-parity", path, line, enc_name,
                        f"undecoded:{tag!r}",
                        f"envelope tag {tag!r} is encoded by {enc_name}() "
                        f"but {dec_name}() has no branch for it — peers "
                        "answer payloads this side cannot decode"))
            for tag, line in sorted(dtags.items()):
                if tag not in etags:
                    findings.append(Finding(
                        "wire-tag-parity", path, line, dec_name,
                        f"unencoded:{tag!r}",
                        f"decode branch for tag {tag!r} in {dec_name}() has "
                        f"no encoder in {enc_name}() — dead protocol arm or "
                        "a missing encode path"))
        return findings

    # -- request-op constants -----------------------------------------------

    def _op_parity(self, spec: dict, tree: ast.Module) -> list[Finding]:
        """Every `prefix`-named module constant must be referenced by the
        server dispatch function AND by the client class, and op values must
        be distinct (two ops sharing a value silently route one to the
        other's branch)."""
        path, prefix = spec["module"], spec["prefix"]
        consts: dict[str, tuple[int, object]] = {}   # name -> (line, value)
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id.startswith(prefix):
                    v = node.value
                    consts[tgt.id] = (node.lineno,
                                      v.value if isinstance(v, ast.Constant)
                                      else None)
                elif isinstance(tgt, ast.Tuple) \
                        and isinstance(node.value, ast.Tuple) \
                        and len(tgt.elts) == len(node.value.elts):
                    for el, val in zip(tgt.elts, node.value.elts):
                        if isinstance(el, ast.Name) \
                                and el.id.startswith(prefix):
                            consts[el.id] = (node.lineno,
                                             val.value if isinstance(
                                                 val, ast.Constant) else None)
        if not consts:
            return []
        findings: list[Finding] = []
        by_value: dict[object, str] = {}
        for name, (line, value) in sorted(consts.items()):
            if value is not None and value in by_value:
                findings.append(Finding(
                    "wire-tag-parity", path, line, "<module>",
                    f"op-collision:{name}",
                    f"op constant {name} shares value {value!r} with "
                    f"{by_value[value]} — the server dispatches one of them "
                    "as the other"))
            elif value is not None:
                by_value[value] = name
        server = client = None
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == spec["server_fn"] and server is None:
                server = node
            if isinstance(node, ast.ClassDef) \
                    and node.name == spec["client_class"]:
                client = node
        for role, scope, missing_fn in ((
                "server", server, spec["server_fn"]),
                ("client", client, spec["client_class"])):
            if scope is None:
                findings.append(Finding(
                    "wire-tag-parity", path, 1, "<module>",
                    f"missing-{role}:{missing_fn}",
                    f"op {role} {missing_fn} not found — update "
                    "analysis/wirecheck.WIRE_SPEC op_specs if it moved"))
                continue
            used = {n.id for n in ast.walk(scope)
                    if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}
            for name, (line, _value) in sorted(consts.items()):
                if name not in used:
                    side = ("no dispatch branch in the server — clients "
                            "sending it get 'unknown op'"
                            if role == "server" else
                            "never sent by the client — dead protocol arm "
                            "or a missing send path")
                    findings.append(Finding(
                        "wire-tag-parity", path, line, missing_fn,
                        f"op-un{'served' if role == 'server' else 'sent'}:"
                        f"{name}",
                        f"op constant {name} has {side}"))
        return findings

    # -- trace-context carriers ---------------------------------------------

    def _trace_parity(self, spec: dict) -> list[Finding]:
        """Every (module, scope) side of a trace-context carrier must
        reference ``symbol`` (by Name or attribute). Sides whose module is
        outside the analyzed set are skipped — narrow --changed-only runs
        must not invent cross-file findings."""
        symbol = spec["symbol"]
        findings: list[Finding] = []
        for module, scope_name in spec.get("sides", ()):
            tree = self._modules.get(module)
            if tree is None:
                continue
            scope = None
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)) \
                        and node.name == scope_name:
                    scope = node
                    break
            if scope is None:
                findings.append(Finding(
                    "wire-trace-parity", module, 1, "<module>",
                    f"missing-scope:{scope_name}",
                    f"trace-carrier scope {scope_name} not found in "
                    f"{module} — update analysis/wirecheck.WIRE_SPEC "
                    "trace_specs if it moved"))
                continue
            referenced = any(
                (isinstance(n, ast.Name) and n.id == symbol)
                or (isinstance(n, ast.Attribute) and n.attr == symbol)
                for n in ast.walk(scope))
            if not referenced:
                findings.append(Finding(
                    "wire-trace-parity", module, scope.lineno, scope_name,
                    f"one-sided:{symbol}",
                    f"{scope_name} no longer references trace carrier "
                    f"{symbol} — the other side still speaks it, so "
                    "cross-node traces sever (or the receiver misparses "
                    "the payload head)"))
        return findings

    # -- nesting bound ------------------------------------------------------

    def _nesting_bound(self, path: str, tree: ast.Module,
                       fns: dict[str, ast.FunctionDef]) -> list[Finding]:
        enc_name, dec_name = self.spec["depth_pair"]
        findings: list[Finding] = []
        bounds: dict[str, tuple[set, list]] = {}
        for name in (enc_name, dec_name):
            fn = fns.get(name)
            if fn is None:
                findings.append(Finding(
                    "wire-nesting-bound", path, 1, "<module>",
                    f"missing-fn:{name}",
                    f"{name}() not found — update WIRE_SPEC if it moved"))
                continue
            names: set[str] = set()
            literals: list[int] = []
            for node in ast.walk(fn):
                if not isinstance(node, ast.Compare):
                    continue
                for side in [node.left, *node.comparators]:
                    d = _depth_const(side)
                    if d is None:
                        continue
                    if isinstance(d, str):
                        names.add(d)
                    else:
                        literals.append(node.lineno)
            bounds[name] = (names, literals)
            for line in literals:
                findings.append(Finding(
                    "wire-nesting-bound", path, line, name,
                    "literal-bound",
                    f"{name}() compares depth against a numeric literal — "
                    "use the shared module constant so encoder and decoder "
                    "cannot drift"))
            if not names and not literals:
                findings.append(Finding(
                    "wire-nesting-bound", path, fn.lineno, name,
                    "no-bound",
                    f"{name}() has no depth-bound comparison — unbounded "
                    "recursion on hostile nested envelopes"))
        if len(bounds) == 2:
            (n1, _), (n2, _) = bounds.values()
            if n1 and n2 and n1.isdisjoint(n2):
                findings.append(Finding(
                    "wire-nesting-bound", path, 1, "<module>",
                    f"split-bound:{sorted(n1)[0]}!={sorted(n2)[0]}",
                    f"{enc_name}() bounds depth by {sorted(n1)} but "
                    f"{dec_name}() by {sorted(n2)} — the nesting bound must "
                    "be one shared constant"))
        return findings

    # -- error classification ------------------------------------------------

    def _error_classified(self, wire_path: str,
                          wire: ast.Module) -> list[Finding]:
        root = self.spec["error_root"]
        # class -> direct base names, across wire.py + the base modules
        bases: dict[str, list[str]] = {}
        def_line: dict[str, int] = {}
        mods = [(wire_path, wire)]
        for p in self.spec["error_base_modules"]:
            if p in self._modules:
                mods.append((p, self._modules[p]))
        wire_classes: list[str] = []
        for p, tree in mods:
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    bnames = [b.id for b in node.bases
                              if isinstance(b, ast.Name)]
                    bases[node.name] = bnames
                    def_line.setdefault(node.name, node.lineno)
                    if p == wire_path:
                        wire_classes.append(node.name)

        def ancestry(name: str) -> list[str]:
            out, todo = [], [name]
            while todo:
                n = todo.pop(0)
                for b in bases.get(n, ()):
                    if b not in out:
                        out.append(b)
                        todo.append(b)
            return out

        typed = [c for c in wire_classes if root in ancestry(c)]
        if root in bases:
            typed.append(root)
        if not typed:
            return []

        cl_path = self.spec["classifier_module"]
        cl = self._modules.get(cl_path)
        if cl is None:
            return [Finding(
                "wire-error-classified", wire_path, 1, "<module>",
                f"missing-classifier:{cl_path}",
                f"classifier module {cl_path} not analyzed — cannot verify "
                "the typed-error dispatch table")]
        handler_chains = self._handler_chains(cl)

        findings: list[Finding] = []
        for err in typed:
            anc = set(ancestry(err))
            covered = None
            for chain in handler_chains:
                names = [n for grp in chain for n in grp]
                if err in names or anc & set(names):
                    covered = chain
                    break
            if covered is None:
                findings.append(Finding(
                    "wire-error-classified", wire_path,
                    def_line.get(err, 1), err, f"unclassified:{err}",
                    f"typed error {err} (a {root} descendant) is never "
                    f"classified by the dispatch table in {cl_path} — peers "
                    "see a bare 500 instead of a typed, retryable status"))
                continue
            # subclass handler must precede ancestor handler
            names_in_order = [n for grp in covered for n in grp]
            if err in names_in_order:
                ei = names_in_order.index(err)
                for a in anc:
                    if a in names_in_order and names_in_order.index(a) < ei:
                        findings.append(Finding(
                            "wire-error-classified", wire_path,
                            def_line.get(err, 1), err, f"shadowed:{err}",
                            f"{err} handler in {cl_path} comes AFTER its "
                            f"ancestor {a}'s — Python takes the first match, "
                            "so the specific classification is dead code"))
        return findings

    @staticmethod
    def _handler_chains(tree: ast.Module) -> list[list[list[str]]]:
        """Each Try's except chain as a list of per-handler name groups."""
        chains = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Try):
                continue
            chain = []
            for h in node.handlers:
                t = h.type
                if t is None:
                    chain.append(["BaseException"])
                elif isinstance(t, ast.Tuple):
                    chain.append([_leaf_name(e) for e in t.elts])
                else:
                    chain.append([_leaf_name(t)])
            chains.append(chain)
        return chains


def _leaf_name(node: ast.expr) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return "<?>"


def _depth_const(node: ast.expr):
    """A depth-bound operand: an UPPERCASE constant Name mentioning
    DEPTH/NEST/MAX (returned as str — lowercase names are the counters, not
    the bound) or an int literal >= 2 (returned as int); else None."""
    if isinstance(node, ast.Name) and node.id == node.id.upper() and any(
            k in node.id for k in ("DEPTH", "NEST", "MAX")):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool) and node.value >= 2:
        return node.value
    return None
