"""Interprocedural facts: package call graph + fixpoint fact propagation.

PR 3's checkers were lexical — each function analyzed in isolation, so a
fact that lives in the CALLER (holds the shard lock, runs on a worker
thread) or in the CALLEE (may raise a typed QueryError) was invisible.
This module builds the package-wide index the v2 rule families share:

  * **function units** — every def/method in the analyzed set, keyed
    ``path::Class.method`` / ``path::func``, with its call sites resolved
    where pure-AST resolution is sound: ``self.m()`` -> same class (one
    level of in-package base classes), ``f()`` -> same module,
    ``mod.f()`` -> the from-import/relative-import target module.
  * **exception hierarchy** — every class def in the set with its base
    names; ``descendants_of("QueryError")`` gives the typed hierarchy the
    except-flow rules protect.
  * **may-raise** — per function, the set of typed exception CLASS NAMES
    that can escape it: direct ``raise X(...)`` plus callees' sets,
    filtered at each call site by the enclosing ``try`` handlers in the
    caller (a call under ``except QueryError`` does not propagate
    QueryError).  Computed as a monotone fixpoint over the call graph, so
    recursion and arbitrary depth converge.
  * **thread entry points** — functions used as ``threading.Thread``
    targets (``target=self._loop`` / ``target=fn``) and ``run`` methods of
    in-package Thread subclasses.  Thread entries are exception SINKS:
    nothing above them can classify a typed error, so the except-flow
    rules treat them as boundaries, and the resource rules require their
    loops to fail loud instead of dying silently.

Unresolvable calls (third-party, attribute chains on unknown objects)
contribute no facts — the engine under-approximates rather than guess.
Pure stdlib ``ast``; no jax import.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

BROAD_EXCEPTION_NAMES = {"Exception", "BaseException"}


def dotted_name(node: ast.expr) -> str | None:
    """'a.b.c' for Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def leaf_name(node: ast.expr) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def attr_root(expr: ast.expr, receivers: tuple = ("self",)) -> str | None:
    """First attribute name hanging off a receiver: ``self.a.b[...]`` ->
    "a". One definition for every checker that tracks state at
    object-attribute granularity (receivers varies: resource tracking also
    accepts the socketserver ``outer`` closure idiom)."""
    node = expr
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        parent = node.value
        if isinstance(node, ast.Attribute) and \
                isinstance(parent, ast.Name) and parent.id in receivers:
            return node.attr
        node = parent
    return None


def handler_names(handler: ast.ExceptHandler) -> list[str]:
    """Leaf class names a handler catches ('<bare>' for ``except:``)."""
    t = handler.type
    if t is None:
        return ["BaseException"]
    if isinstance(t, ast.Tuple):
        return [leaf_name(e) or "<?>" for e in t.elts]
    return [leaf_name(t) or "<?>"]


def is_broad_handler(handler: ast.ExceptHandler) -> bool:
    return bool(set(handler_names(handler)) & BROAD_EXCEPTION_NAMES)


def handler_reraises(handler: ast.ExceptHandler) -> bool:
    """Does the handler re-raise what it caught (bare ``raise``)? Such a
    handler observes the exception but does NOT terminate it — it must not
    strip the class from may-raise propagation."""
    return any(isinstance(n, ast.Raise) and n.exc is None
               for n in ast.walk(handler))


def catching_names(handlers: list) -> frozenset:
    """Exception names a try's handler chain TERMINATES: names of handlers
    that don't re-raise (the log-and-reraise idiom keeps propagating)."""
    out: set = set()
    for h in handlers:
        if not handler_reraises(h):
            out.update(handler_names(h))
    return frozenset(out)


def handler_is_observable(handler: ast.ExceptHandler) -> bool:
    """Does the handler leave ANY trace — a raise, a call (logging, counter,
    cleanup helper), an assignment? Pass/continue/bare-return bodies are the
    silent-swallow shape. Shared by except-swallow and
    resource-worker-silent-death so the two families cannot drift on what
    'observable' means."""
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Raise, ast.Call, ast.Assign,
                                 ast.AugAssign, ast.AnnAssign)):
                return True
    return False


@dataclass
class CallSite:
    callee_key: str          # resolved FuncUnit key
    line: int
    caught: frozenset        # exception names caught around this site


@dataclass
class FuncUnit:
    key: str                 # "path::Class.method" / "path::func"
    path: str
    qualname: str            # "Class.method" / "func"
    name: str
    cls: str | None
    node: ast.AST
    calls: list[CallSite] = field(default_factory=list)
    direct_raises: set = field(default_factory=set)   # class NAMES raised
    # names `raise`d bare inside an except handler count as re-raise, not a
    # typed raise (the type is whatever was caught)


@dataclass
class ClassInfo:
    name: str
    path: str
    node: ast.ClassDef
    bases: list[str] = field(default_factory=list)    # leaf base names
    methods: dict = field(default_factory=dict)       # name -> FuncUnit key


class _ImportMap:
    """Module-local name -> (module rel path, symbol) for in-package imports.

    ``from .config import parse_duration_ms`` in filodb_tpu/standalone.py
    maps "parse_duration_ms" -> ("filodb_tpu/config.py", same name);
    ``from . import broker`` / ``import x.y as z`` map the module alias.
    """

    def __init__(self, path: str, known_paths: set):
        self.path = path
        self.known = known_paths
        self.symbols: dict[str, tuple[str, str]] = {}
        self.modules: dict[str, str] = {}             # alias -> module path

    def _resolve_relative(self, level: int, module: str | None) -> str | None:
        base = self.path.rsplit("/", 1)[0]            # containing package dir
        for _ in range(level - 1):
            if "/" not in base:
                return None
            base = base.rsplit("/", 1)[0]
        tail = (module or "").replace(".", "/")
        return f"{base}/{tail}" if tail else base

    def add_import_from(self, node: ast.ImportFrom) -> None:
        if node.level:
            prefix = self._resolve_relative(node.level, node.module)
        else:
            prefix = (node.module or "").replace(".", "/")
        if prefix is None:
            return
        for a in node.names:
            alias = a.asname or a.name
            as_module = f"{prefix}/{a.name}.py"
            as_symbol = f"{prefix}.py"
            if as_module in self.known:
                self.modules[alias] = as_module
            elif as_symbol in self.known:
                self.symbols[alias] = (as_symbol, a.name)

    def add_import(self, node: ast.Import) -> None:
        for a in node.names:
            p = a.name.replace(".", "/") + ".py"
            if p in self.known:
                self.modules[a.asname or a.name.split(".")[-1]] = p


class PackageIndex:
    """Shared interprocedural index over one analysis run's modules."""

    def __init__(self, modules: dict[str, ast.Module]):
        self.modules = modules
        self.funcs: dict[str, FuncUnit] = {}
        self.classes: dict[str, ClassInfo] = {}       # "path::Class"
        self.class_by_name: dict[str, list[ClassInfo]] = {}
        self._imports: dict[str, _ImportMap] = {}
        self.thread_entries: set = set()              # FuncUnit keys
        self._index()
        self._resolve_calls()
        self._find_thread_entries()
        self._may_raise: dict[str, frozenset] | None = None

    # -- construction -------------------------------------------------------

    def _index(self) -> None:
        known = set(self.modules)
        for path, tree in self.modules.items():
            imap = _ImportMap(path, known)
            self._imports[path] = imap
            # function-local imports count too (standalone.py defers most of
            # its wiring imports into start()); name shadowing across
            # functions is rare enough to accept one flat namespace
            for node in ast.walk(tree):
                if isinstance(node, ast.ImportFrom):
                    imap.add_import_from(node)
                elif isinstance(node, ast.Import):
                    imap.add_import(node)
            for node in tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._add_func(path, None, node)
                    self._index_nested(path, node)
                elif isinstance(node, ast.ClassDef):
                    ci = ClassInfo(node.name, path, node,
                                   [leaf_name(b) or "<?>" for b in node.bases])
                    self.classes[f"{path}::{node.name}"] = ci
                    self.class_by_name.setdefault(node.name, []).append(ci)
                    for m in node.body:
                        if isinstance(m, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                            u = self._add_func(path, node.name, m)
                            ci.methods[m.name] = u.key
                            self._index_nested(path, m, cls=node.name)

    def _index_nested(self, path: str, fn: ast.AST,
                      cls: str | None = None) -> None:
        """Nested defs (closure workers like standalone's loop targets) get
        their own units, qualified under the enclosing function."""
        for node in ast.walk(fn):
            if node is fn:
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{fn.name}.{node.name}" if cls is None \
                    else f"{cls}.{fn.name}.{node.name}"
                key = f"{path}::{qual}"
                if key not in self.funcs:
                    self.funcs[key] = FuncUnit(key, path, qual, node.name,
                                               cls, node)

    def _add_func(self, path: str, cls: str | None,
                  node: ast.AST) -> FuncUnit:
        qual = f"{cls}.{node.name}" if cls else node.name
        u = FuncUnit(f"{path}::{qual}", path, qual, node.name, cls, node)
        self.funcs[u.key] = u
        return u

    # -- call resolution ----------------------------------------------------

    def _method_key(self, path: str, cls: str | None,
                    name: str) -> str | None:
        """Resolve a self.NAME() call: the class, then one level of
        in-package bases (same module or imported)."""
        seen = set()
        todo = [f"{path}::{cls}"] if cls else []
        while todo:
            ck = todo.pop(0)
            if ck in seen:
                continue
            seen.add(ck)
            ci = self.classes.get(ck)
            if ci is None:
                continue
            if name in ci.methods:
                return ci.methods[name]
            imap = self._imports.get(ci.path)
            for b in ci.bases:
                if f"{ci.path}::{b}" in self.classes:
                    todo.append(f"{ci.path}::{b}")
                elif imap and b in imap.symbols:
                    bpath, bname = imap.symbols[b]
                    todo.append(f"{bpath}::{bname}")
        return None

    def resolve_call(self, path: str, cls: str | None,
                     call: ast.Call) -> str | None:
        fn = call.func
        if isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name):
                base = fn.value.id
                if base in ("self", "cls", "outer"):
                    return self._method_key(path, cls, fn.attr)
                imap = self._imports.get(path)
                if imap and base in imap.modules:
                    key = f"{imap.modules[base]}::{fn.attr}"
                    return key if key in self.funcs else None
            return None
        if isinstance(fn, ast.Name):
            imap = self._imports.get(path)
            if imap and fn.id in imap.symbols:
                spath, sname = imap.symbols[fn.id]
                key = f"{spath}::{sname}"
                return key if key in self.funcs else None
            key = f"{path}::{fn.id}"
            return key if key in self.funcs else None
        return None

    def _resolve_calls(self) -> None:
        for u in self.funcs.values():
            collector = _CallCollector(self, u)
            body = getattr(u.node, "body", [])
            for stmt in body:
                collector.visit(stmt)

    # -- thread entries ------------------------------------------------------

    def _thread_subclasses(self) -> set:
        """'path::Class' keys of classes transitively deriving Thread."""
        cached = getattr(self, "_thread_subclass_cache", None)
        if cached is not None:
            return cached
        out: set = set()
        changed = True
        while changed:
            changed = False
            for ck, ci in self.classes.items():
                if ck in out:
                    continue
                for b in ci.bases:
                    is_thread = b == "Thread"
                    if not is_thread:
                        imap = self._imports.get(ci.path)
                        tgt = imap.symbols.get(b) if imap else None
                        bk = f"{ci.path}::{b}" if f"{ci.path}::{b}" in \
                            self.classes else (f"{tgt[0]}::{tgt[1]}"
                                               if tgt else None)
                        is_thread = bk in out if bk else False
                    if is_thread:
                        out.add(ck)
                        changed = True
                        break
        self._thread_subclass_cache = out
        return out

    def _find_thread_entries(self) -> None:
        for ck in self._thread_subclasses():
            ci = self.classes[ck]
            if "run" in ci.methods:
                self.thread_entries.add(ci.methods["run"])
        for path, tree in self.modules.items():
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                target = next((kw.value for kw in node.keywords
                               if kw.arg == "target"), None)
                if target is None:
                    continue
                name = dotted_name(node.func) or ""
                if not (name.endswith("Thread") or name == "Thread"):
                    continue
                key = self._resolve_target(path, node, target)
                if key:
                    self.thread_entries.add(key)

    def _enclosing_class(self, path: str, call: ast.Call) -> str | None:
        tree = self.modules.get(path)
        if tree is None:
            return None
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if sub is call:
                        return node.name
        return None

    def _resolve_target(self, path: str, call: ast.Call,
                        target: ast.expr) -> str | None:
        """Thread target= expression -> FuncUnit key (best effort)."""
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id in ("self", "outer"):
            cls = self._enclosing_class(path, call)
            return self._method_key(path, cls, target.attr)
        if isinstance(target, ast.Name):
            # nested closure worker first (standalone's loop targets), then a
            # module-level function
            for key, u in self.funcs.items():
                if u.path == path and u.name == target.id and "." in u.qualname:
                    return key
            key = f"{path}::{target.id}"
            return key if key in self.funcs else None
        return None

    # -- exception hierarchy -------------------------------------------------

    def ancestry(self, class_name: str) -> set:
        """All ancestor class NAMES reachable from class_name (by-name
        resolution across the analyzed set; diamond-safe)."""
        out: set = set()
        todo = [class_name]
        while todo:
            n = todo.pop()
            for ci in self.class_by_name.get(n, ()):
                for b in ci.bases:
                    if b not in out:
                        out.add(b)
                        todo.append(b)
        return out

    def descendants_of(self, root: str) -> set:
        out = set()
        for name in self.class_by_name:
            if name == root or root in self.ancestry(name):
                out.add(name)
        return out

    def catches(self, caught: frozenset, exc_name: str) -> bool:
        """Would a handler naming `caught` intercept an exception of class
        exc_name? (exact, ancestor, or broad match)."""
        if caught & BROAD_EXCEPTION_NAMES:
            return True
        if exc_name in caught:
            return True
        return bool(self.ancestry(exc_name) & caught)

    # -- reverse call graph ---------------------------------------------------

    def callers_of(self) -> dict:
        """Callee FuncUnit key -> set of caller keys (sound edges only —
        same under-approximation as the forward graph). Cached."""
        cached = getattr(self, "_callers_cache", None)
        if cached is not None:
            return cached
        out: dict = {}
        for key, u in self.funcs.items():
            for site in u.calls:
                out.setdefault(site.callee_key, set()).add(key)
        self._callers_cache = out
        return out

    def reachable_only_from(self, key: str, sanctioned: set) -> bool:
        """True iff every reverse-call chain from ``key`` hits a function in
        ``sanctioned`` before it hits an unsanctioned root (a function with
        no in-package callers — a thread entry, an HTTP handler, a public
        API). A sanctioned ancestor terminates its chain: whatever it does
        around the call is its declared responsibility. A chain that ends
        in an unsanctioned root means ``key`` can run with no declared
        site above it. Pure cycles with no outside entry are vacuously
        sanctioned (nothing can invoke them). Under-approximated edges
        (getattr dispatch, third-party callbacks) make this lenient, never
        falsely loud — consistent with the forward graph's contract."""
        if key in sanctioned:
            return True
        callers = self.callers_of()
        seen: set = set()
        todo = [key]
        while todo:
            k = todo.pop()
            if k in seen:
                continue
            seen.add(k)
            ups = callers.get(k, ())
            if not ups and k != key:
                return False          # unsanctioned root reached
            if not ups and k == key:
                return False          # key itself is a root
            for up in ups:
                if up in sanctioned:
                    continue          # this chain is accounted for
                todo.append(up)
        return True

    # -- may-raise fixpoint ---------------------------------------------------

    def may_raise(self, typed_only: set | None = None) -> dict[str, frozenset]:
        """Function key -> exception class names that can escape it.

        ``typed_only`` restricts the domain (the except-flow rules pass the
        QueryError hierarchy) — smaller sets, faster fixpoint. Cached for
        the index's lifetime when typed_only is None-or-first-call."""
        if self._may_raise is not None and typed_only is None:
            return self._may_raise
        domain = typed_only
        cur: dict[str, set] = {}
        for key, u in self.funcs.items():
            direct = set(u.direct_raises)
            if domain is not None:
                direct &= domain
            cur[key] = direct
        changed = True
        while changed:
            changed = False
            for key, u in self.funcs.items():
                mine = cur[key]
                for site in u.calls:
                    callee = cur.get(site.callee_key)
                    if not callee:
                        continue
                    for exc in callee:
                        if exc not in mine and \
                                not self.catches(site.caught, exc):
                            mine.add(exc)
                            changed = True
        out = {k: frozenset(v) for k, v in cur.items()}
        if typed_only is None:
            self._may_raise = out
        return out


class _CallCollector(ast.NodeVisitor):
    """One pass over a function: resolved call sites with their enclosing
    try-handler context, plus direct typed raises."""

    def __init__(self, index: PackageIndex, unit: FuncUnit):
        self.index = index
        self.u = unit
        self._caught: list[frozenset] = []

    def visit_Try(self, node: ast.Try):  # noqa: N802
        # only handlers that TERMINATE the exception filter propagation —
        # `except QueryError: log(); raise` keeps the typed class flowing
        names = catching_names(node.handlers)
        self._caught.append(names)
        for stmt in node.body:
            self.visit(stmt)
        self._caught.pop()
        for part in (node.handlers, node.orelse, node.finalbody):
            for stmt in part:
                self.visit(stmt)

    visit_TryStar = visit_Try

    def visit_Raise(self, node: ast.Raise):  # noqa: N802
        exc = node.exc
        if isinstance(exc, ast.Call):
            exc = exc.func
        name = leaf_name(exc) if exc is not None else None
        if name:
            self.u.direct_raises.add(name)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):  # noqa: N802
        key = self.index.resolve_call(self.u.path, self.u.cls, node)
        if key is not None:
            caught = frozenset().union(*self._caught) if self._caught \
                else frozenset()
            self.u.calls.append(CallSite(key, node.lineno, caught))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):  # noqa: N802
        pass        # nested defs are their own units

    visit_AsyncFunctionDef = visit_FunctionDef
