"""Orchestration: file discovery, checker dispatch, suppression + baseline.

Pure stdlib + ast — importable with no jax/numpy on the path, so the tier-1
test and CI hooks pay only parse time (~100ms for the whole package).
"""

from __future__ import annotations

import ast
from collections import Counter
from dataclasses import dataclass, field
from pathlib import Path

from .callgraph import PackageIndex
from .decodecheck import DecodeChecker
from .exceptcheck import ExceptChecker
from .findings import Baseline, Finding, is_suppressed, load_suppressions
from .indexcheck import IndexChecker
from .jitcheck import JitChecker
from .lockcheck import LockChecker
from .meshcheck import MeshChecker
from .resourcecheck import ResourceChecker
from .surfacecheck import SurfaceChecker
from .wirecheck import WireChecker

# generated / vendored files never analyzed
DEFAULT_EXCLUDES = ("remote_storage_pb2.py",)

ALL_RULES = tuple(sorted(
    set(LockChecker.rules) | set(JitChecker.rules) | set(WireChecker.rules)
    | set(ResourceChecker.rules) | set(ExceptChecker.rules)
    | set(SurfaceChecker.rules) | set(IndexChecker.rules)
    | set(MeshChecker.rules) | set(DecodeChecker.rules)))

DEFAULT_BASELINE = "filolint_baseline.json"


@dataclass
class AnalysisReport:
    new: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    files_analyzed: int = 0
    # repo-relative paths actually analyzed — narrow-scope tooling
    # (--changed-only --update-baseline) must not touch baseline entries
    # for files outside this set
    analyzed_paths: list[str] = field(default_factory=list)

    @property
    def all_findings(self) -> list[Finding]:
        return self.new + self.suppressed + self.baselined

    def counts_by_rule(self, which: str = "new") -> dict[str, int]:
        items = getattr(self, which)
        return dict(Counter(f.rule for f in items))

    def summary(self) -> str:
        lines = [f"filolint: {self.files_analyzed} files analyzed, "
                 f"{len(self.new)} new finding(s), "
                 f"{len(self.suppressed)} suppressed inline, "
                 f"{len(self.baselined)} baselined"]
        per_rule = Counter(f.rule for f in self.all_findings)
        for rule in ALL_RULES:
            n_all = per_rule.get(rule, 0)
            n_new = sum(1 for f in self.new if f.rule == rule)
            if n_all or n_new:
                lines.append(f"  {rule:<24} {n_all:>3} total, {n_new} new")
        return "\n".join(lines)


def _discover(root: Path, paths: list[str] | None) -> list[Path]:
    if paths:
        out: list[Path] = []
        for p in paths:
            pp = (root / p) if not Path(p).is_absolute() else Path(p)
            if pp.is_dir():
                out.extend(sorted(pp.rglob("*.py")))
            else:
                out.append(pp)
    else:
        out = sorted((root / "filodb_tpu").rglob("*.py"))
    return [p for p in out if p.name not in DEFAULT_EXCLUDES]


def analyze_file(path: Path, root: Path | None = None,
                 checkers=None) -> list[Finding]:
    """Analyze one file standalone (fixture self-tests use this). Cross-file
    rules (lock-order graph, wire classification) still run via finalize over
    just this file."""
    root = root or path.parent
    checkers = checkers if checkers is not None else _default_checkers()
    rel = _relpath(path, root)
    source = path.read_text()
    tree = ast.parse(source, filename=str(path))
    findings: list[Finding] = []
    for c in checkers:
        findings += c.check_module(rel, tree)
    findings += _finalize(checkers, {rel: tree})
    supp = load_suppressions(source)
    return [f for f in findings if not is_suppressed(f, supp)]


def _default_checkers(wire_spec: dict | None = None, full_scope: bool = True):
    surface = SurfaceChecker()
    surface.full_scope = full_scope
    return [LockChecker(), JitChecker(), WireChecker(spec=wire_spec),
            ResourceChecker(), ExceptChecker(), IndexChecker(),
            MeshChecker(), DecodeChecker(), surface]


def _finalize(checkers, modules: dict) -> list[Finding]:
    """Run every checker's finalize with ONE shared interprocedural index —
    the call graph / may-raise / thread-entry facts are built once and the
    resource/except/lock checkers all consume them."""
    project = PackageIndex(modules)
    findings: list[Finding] = []
    for c in checkers:
        if hasattr(c, "project"):
            c.project = project
        fin = getattr(c, "finalize", None)
        if fin is not None:
            findings += fin()
    return findings


def _relpath(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def run_analysis(root: Path | str, paths: list[str] | None = None,
                 baseline_path: Path | str | None = "auto",
                 wire_spec: dict | None = None) -> AnalysisReport:
    """Analyze ``paths`` (default: the filodb_tpu package under ``root``).

    ``baseline_path="auto"`` uses <root>/filolint_baseline.json when present.
    Returns an AnalysisReport with findings split into new / inline-suppressed
    / baselined."""
    root = Path(root)
    if baseline_path == "auto":
        baseline_path = root / DEFAULT_BASELINE
    baseline = Baseline.load(baseline_path)
    checkers = _default_checkers(wire_spec, full_scope=paths is None)
    report = AnalysisReport()
    per_file_supp: dict[str, dict[int, set[str]]] = {}
    modules: dict[str, ast.Module] = {}
    findings: list[Finding] = []
    for path in _discover(root, paths):
        rel = _relpath(path, root)
        try:
            source = path.read_text()
            tree = ast.parse(source, filename=str(path))
        except (OSError, SyntaxError) as e:
            findings.append(Finding("parse-error", rel, 1, "<module>",
                                    "parse", f"cannot analyze: {e}"))
            continue
        per_file_supp[rel] = load_suppressions(source)
        modules[rel] = tree
        report.files_analyzed += 1
        report.analyzed_paths.append(rel)
        for c in checkers:
            findings += c.check_module(rel, tree)
    findings += _finalize(checkers, modules)
    for f in findings:
        if is_suppressed(f, per_file_supp.get(f.path, {})):
            report.suppressed.append(f)
        elif baseline.covers(f):
            report.baselined.append(f)
        else:
            report.new.append(f)
    return report
