"""Orchestration: file discovery, checker dispatch, suppression + baseline.

Pure stdlib + ast — importable with no jax/numpy on the path, so the tier-1
test and CI hooks pay only parse time (~100ms for the whole package).

All rule families run over ONE shared :class:`~.corpus.Corpus`: module ASTs
parsed once, the PackageIndex built lazily exactly once, per-function CFGs
memoized by node identity. ``run_analysis(shared_corpus=False)`` preserves
the naive cost model (each family re-parses the package and builds its own
index) purely so the tier-1 timing test can assert the sharing is a real
win — findings are fingerprint-identical in both modes.
"""

from __future__ import annotations

import ast
import time
from collections import Counter
from dataclasses import dataclass, field
from pathlib import Path

from .corpus import Corpus, parse_corpus
from .decodecheck import DecodeChecker
from .epochcheck import EpochChecker
from .exceptcheck import ExceptChecker
from .findings import (Baseline, Finding, STALE_IGNORE_RULE, is_suppressed,
                       load_suppressions)
from .indexcheck import IndexChecker
from .jitcheck import JitChecker
from .livecheck import LiveChecker
from .lockcheck import LockChecker
from .meshcheck import MeshChecker
from .resourcecheck import ResourceChecker
from .surfacecheck import SurfaceChecker
from .wirecheck import WireChecker

# generated / vendored files never analyzed
DEFAULT_EXCLUDES = ("remote_storage_pb2.py",)

ALL_RULES = tuple(sorted(
    set(LockChecker.rules) | set(JitChecker.rules) | set(WireChecker.rules)
    | set(ResourceChecker.rules) | set(ExceptChecker.rules)
    | set(SurfaceChecker.rules) | set(IndexChecker.rules)
    | set(MeshChecker.rules) | set(DecodeChecker.rules)
    | set(EpochChecker.rules) | set(LiveChecker.rules)
    | {STALE_IGNORE_RULE}))

DEFAULT_BASELINE = "filolint_baseline.json"


@dataclass
class AnalysisReport:
    new: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    files_analyzed: int = 0
    # repo-relative paths actually analyzed — narrow-scope tooling
    # (--changed-only --update-baseline) must not touch baseline entries
    # for files outside this set
    analyzed_paths: list[str] = field(default_factory=list)
    # --stats observability: seconds per rule family (+ "parse",
    # "stale-ignore"), total wall time, and Corpus build/hit counters
    timings: dict = field(default_factory=dict)
    corpus_stats: dict = field(default_factory=dict)
    wall_s: float = 0.0

    @property
    def all_findings(self) -> list[Finding]:
        return self.new + self.suppressed + self.baselined

    def counts_by_rule(self, which: str = "new") -> dict[str, int]:
        items = getattr(self, which)
        return dict(Counter(f.rule for f in items))

    def summary(self) -> str:
        lines = [f"filolint: {self.files_analyzed} files analyzed, "
                 f"{len(self.new)} new finding(s), "
                 f"{len(self.suppressed)} suppressed inline, "
                 f"{len(self.baselined)} baselined"]
        per_rule = Counter(f.rule for f in self.all_findings)
        for rule in ALL_RULES:
            n_all = per_rule.get(rule, 0)
            n_new = sum(1 for f in self.new if f.rule == rule)
            if n_all or n_new:
                lines.append(f"  {rule:<24} {n_all:>3} total, {n_new} new")
        return "\n".join(lines)

    def stats_lines(self) -> list[str]:
        lines = [f"filolint --stats: wall {self.wall_s:.3f}s"]
        for name, secs in sorted(self.timings.items(),
                                 key=lambda kv: -kv[1]):
            lines.append(f"  {name:<20} {secs:.4f}s")
        if self.corpus_stats:
            cs = self.corpus_stats
            lines.append(
                f"  corpus: {cs.get('modules', 0)} modules, "
                f"{cs.get('index_builds', 0)} index build(s) "
                f"({cs.get('index_build_s', 0.0)}s), "
                f"{cs.get('cfg_builds', 0)} CFG build(s) / "
                f"{cs.get('cfg_hits', 0)} hit(s)")
        return lines


def _discover(root: Path, paths: list[str] | None) -> list[Path]:
    if paths:
        out: list[Path] = []
        for p in paths:
            pp = (root / p) if not Path(p).is_absolute() else Path(p)
            if pp.is_dir():
                out.extend(sorted(pp.rglob("*.py")))
            else:
                out.append(pp)
    else:
        out = sorted((root / "filodb_tpu").rglob("*.py"))
    return [p for p in out if p.name not in DEFAULT_EXCLUDES]


def analyze_file(path: Path, root: Path | None = None,
                 checkers=None) -> list[Finding]:
    """Analyze one file standalone (fixture self-tests use this). Cross-file
    rules (lock-order graph, wire classification) still run via finalize over
    just this file."""
    root = root or path.parent
    checkers = checkers if checkers is not None else _default_checkers()
    rel = _relpath(path, root)
    source = path.read_text()
    tree = ast.parse(source, filename=str(path))
    findings: list[Finding] = []
    for c in checkers:
        findings += c.check_module(rel, tree)
    findings += _finalize(checkers, {rel: tree})
    supp = load_suppressions(source)
    findings += _stale_ignores(findings, {rel: supp})
    return [f for f in findings if not is_suppressed(f, supp)]


def _default_checkers(wire_spec: dict | None = None, full_scope: bool = True):
    surface = SurfaceChecker()
    surface.full_scope = full_scope
    live = LiveChecker()
    # unresolved-sanction errors need the whole package in view — a scoped
    # run would call a live sanction stale just because its target module
    # wasn't analyzed
    live.full_scope = full_scope
    return [LockChecker(), JitChecker(), WireChecker(spec=wire_spec),
            ResourceChecker(), ExceptChecker(), IndexChecker(),
            MeshChecker(), DecodeChecker(), EpochChecker(), live,
            surface]


def _finalize(checkers, modules: dict, corpus: Corpus | None = None,
              timings: dict | None = None) -> list[Finding]:
    """Run every checker's finalize with ONE shared interprocedural corpus —
    the call graph / may-raise / thread-entry facts and per-function CFGs are
    built once and the resource/except/lock/epoch checkers all consume them."""
    if corpus is None:
        corpus = Corpus(modules)
    findings: list[Finding] = []
    for c in checkers:
        t0 = time.perf_counter()
        if hasattr(c, "project"):
            c.project = corpus.index
        if hasattr(c, "corpus"):
            c.corpus = corpus
        fin = getattr(c, "finalize", None)
        if fin is not None:
            findings += fin()
        if timings is not None:
            name = type(c).__name__
            timings[name] = timings.get(name, 0.0) + \
                (time.perf_counter() - t0)
            # per-rule sub-timings (livecheck reports its four passes)
            for sub, secs in getattr(c, "sub_timings", {}).items():
                timings[f"{name}.{sub}"] = \
                    timings.get(f"{name}.{sub}", 0.0) + secs
    return findings


def _stale_ignores(findings: list[Finding],
                   per_file_supp: dict[str, dict]) -> list[Finding]:
    """An inline ``# filolint: ignore[...]`` that no longer suppresses any
    finding is itself a finding: the comment documents an exception that no
    longer exists, and silently keeps suppressing whatever fires there NEXT.
    Judged against pre-suppression findings; skip-file markers (line 0) and
    ignores naming only the meta-rule are exempt."""
    out: list[Finding] = []
    fired: dict[tuple, set] = {}
    for f in findings:
        fired.setdefault((f.path, f.line), set()).add(f.rule)
    for path, supp in per_file_supp.items():
        for line, rules in sorted(supp.items()):
            if line == 0:
                continue
            here = fired.get((path, line), set())
            for r in sorted(rules):
                if r == STALE_IGNORE_RULE:
                    continue            # naming the meta-rule is always meta
                stale = not here if r == "*" else r not in here
                if stale:
                    out.append(Finding(
                        STALE_IGNORE_RULE, path, line, "<module>",
                        f"ignore[{r}]",
                        f"inline ignore[{r}] suppresses nothing — the "
                        "finding it excused is gone (or the rule name is "
                        "wrong); delete the comment, or it will silently "
                        "swallow the next finding on this line"))
    return out


def _relpath(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def run_analysis(root: Path | str, paths: list[str] | None = None,
                 baseline_path: Path | str | None = "auto",
                 wire_spec: dict | None = None,
                 shared_corpus: bool = True) -> AnalysisReport:
    """Analyze ``paths`` (default: the filodb_tpu package under ``root``).

    ``baseline_path="auto"`` uses <root>/filolint_baseline.json when present.
    ``shared_corpus=False`` runs each rule family against its own freshly
    parsed corpus + index (the pre-sharing cost model, kept for the tier-1
    timing assertion; findings are identical). Returns an AnalysisReport
    with findings split into new / inline-suppressed / baselined."""
    t_start = time.perf_counter()
    root = Path(root)
    if baseline_path == "auto":
        baseline_path = root / DEFAULT_BASELINE
    baseline = Baseline.load(baseline_path)
    full_scope = paths is None
    files = [(_relpath(p, root), p) for p in _discover(root, paths)]
    report = AnalysisReport()
    per_file_supp: dict[str, dict[int, set[str]]] = {}
    findings: list[Finding] = []

    def _ingest(corpus: Corpus, errors: list) -> None:
        for rel, e in errors:
            findings.append(Finding("parse-error", rel, 1, "<module>",
                                    "parse", f"cannot analyze: {e}"))
        for rel in corpus.modules:
            per_file_supp[rel] = load_suppressions(corpus.sources[rel])
            report.files_analyzed += 1
            report.analyzed_paths.append(rel)

    if shared_corpus:
        t0 = time.perf_counter()
        corpus, errors = parse_corpus(files)
        report.timings["parse"] = time.perf_counter() - t0
        _ingest(corpus, errors)
        checkers = _default_checkers(wire_spec, full_scope)
        for c in checkers:
            t0 = time.perf_counter()
            for rel, tree in corpus.modules.items():
                findings += c.check_module(rel, tree)
            report.timings[type(c).__name__] = time.perf_counter() - t0
        findings += _finalize(checkers, corpus.modules, corpus=corpus,
                              timings=report.timings)
        report.corpus_stats = corpus.stats()
    else:
        # legacy per-family cost model: every family pays its own parse of
        # the whole file set AND its own PackageIndex/CFG builds
        n_families = len(_default_checkers(wire_spec, full_scope))
        for i in range(n_families):
            c = _default_checkers(wire_spec, full_scope)[i]
            t0 = time.perf_counter()
            corpus, errors = parse_corpus(files)
            if i == 0:
                _ingest(corpus, errors)
            for rel, tree in corpus.modules.items():
                findings += c.check_module(rel, tree)
            findings += _finalize([c], corpus.modules, corpus=corpus)
            report.timings[type(c).__name__] = \
                report.timings.get(type(c).__name__, 0.0) + \
                (time.perf_counter() - t0)

    if full_scope:
        # *-unused-style judgements need the whole package in view; a scoped
        # run would call live suppressions stale just because the rule that
        # fires there didn't run
        t0 = time.perf_counter()
        findings += _stale_ignores(findings, per_file_supp)
        report.timings["stale-ignore"] = time.perf_counter() - t0

    for f in findings:
        if is_suppressed(f, per_file_supp.get(f.path, {})):
            report.suppressed.append(f)
        elif baseline.covers(f):
            report.baselined.append(f)
        else:
            report.new.append(f)
    report.wall_s = time.perf_counter() - t_start
    return report
