"""Shared analysis corpus: one parse, one index, memoized CFGs per run.

Before this module each rule family paid its own interprocedural costs:
the runner parsed every file once, but the resource/except rules each
rebuilt per-function CFGs on demand, fixture self-tests rebuilt a fresh
PackageIndex per file, and a naive per-family runner (the comparison mode
``run_analysis(shared_corpus=False)`` preserves it for the tier-1 timing
assertion) re-parses the whole package once per family. The Corpus is the
single shared substrate: module ASTs + raw sources, the PackageIndex
built lazily exactly once, and a per-function CFG cache keyed by node
identity (module ASTs live as long as the corpus, so ``id`` is stable).

Pure stdlib ``ast`` — importable with no jax/numpy on the path.
"""

from __future__ import annotations

import ast
import time
from pathlib import Path

from .callgraph import PackageIndex
from .cfg import CFG, build_cfg


class Corpus:
    """One run's shared AST/index/CFG substrate."""

    def __init__(self, modules: dict[str, ast.Module],
                 sources: dict[str, str] | None = None):
        self.modules = modules
        self.sources = sources or {}
        self._index: PackageIndex | None = None
        self._cfgs: dict[int, CFG] = {}
        # observability for --stats and the tier-1 sharing assertion
        self.index_builds = 0
        self.index_build_s = 0.0
        self.cfg_builds = 0
        self.cfg_hits = 0

    @property
    def index(self) -> PackageIndex:
        if self._index is None:
            t0 = time.perf_counter()
            self._index = PackageIndex(self.modules)
            self.index_build_s += time.perf_counter() - t0
            self.index_builds += 1
        return self._index

    def cfg(self, fn: ast.AST) -> CFG:
        """The per-function CFG, built at most once per corpus — every rule
        family that asks about the same function shares one graph."""
        key = id(fn)
        got = self._cfgs.get(key)
        if got is not None:
            self.cfg_hits += 1
            return got
        self.cfg_builds += 1
        g = build_cfg(fn)
        self._cfgs[key] = g
        return g

    def stats(self) -> dict:
        return {"modules": len(self.modules),
                "index_builds": self.index_builds,
                "index_build_s": round(self.index_build_s, 4),
                "cfg_builds": self.cfg_builds,
                "cfg_hits": self.cfg_hits}


def parse_corpus(files: list[tuple[str, Path]]) -> tuple[Corpus, list]:
    """Parse ``(relpath, path)`` pairs into a Corpus. Returns the corpus
    plus ``(relpath, error)`` pairs for unreadable/unparseable files (the
    runner renders those as parse-error findings)."""
    modules: dict[str, ast.Module] = {}
    sources: dict[str, str] = {}
    errors: list = []
    for rel, path in files:
        try:
            source = path.read_text()
            modules[rel] = ast.parse(source, filename=str(path))
            sources[rel] = source
        except (OSError, SyntaxError) as e:
            errors.append((rel, e))
    return Corpus(modules, sources), errors
