"""JIT-hygiene checker.

Inside a ``jax.jit``-compiled function, the cheap-looking host idioms are the
expensive ones (accelerator guide: host/device boundary):

  * ``jit-host-sync`` — ``float(x)`` / ``x.item()`` / ``np.asarray(x)`` /
    ``np.array(x)`` / ``jax.device_get(x)`` on a traced value forces a
    device→host sync per call (or a ConcretizationError); on a tunneled
    link one stray sync is ~100ms per query.
  * ``jit-traced-branch`` — Python ``if``/``while`` on a traced parameter is
    a trace error; "fixing" it by making the value static retraces per
    distinct value. Shape/len/isinstance/`is None` tests are static and fine.
  * ``jit-mutable-closure`` — a jitted function reading module-level mutable
    state (list/dict/set) bakes the values seen at TRACE time into the
    compiled program; later mutations are silently ignored. Writing
    (``global``) from traced code never lands.
  * ``jit-static-args`` — a float-typed static argument retraces per distinct
    value (the silent 100x cliff); an unhashable static argument (list/dict/
    set/ndarray) raises at call time. Checked both at the decoration (float
    defaults on static params) and at same-module call sites.
  * ``jit-donation-unused`` — donation discipline on the flush path, both
    directions: (a) a ``donate_argnums``/``donate_argnames`` argument that
    never flows to the function's return is a donation with zero aliasing
    win — the input buffer is deleted (the caller may still hold it) and
    nothing is updated in place; (b) a jitted function that scatter-updates
    a parameter (``p.at[...].set/add``) and returns the result WITHOUT
    donating it allocates a full copy of the buffer per call — on the
    memstore flush path that is a store-sized allocation per staged-row
    commit (core/chunkstore.py's scatter jits donate for exactly this
    reason). Deliberate copies suppress with an inline
    ``filolint: ignore[jit-donation-unused]`` comment + reason.

Jitted functions are recognized by decorator (``@jax.jit``,
``@functools.partial(jax.jit, ...)``), by wrapping assignment
(``g = jax.jit(f, ...)``), and by factory return (``return jax.jit(f)``).
Cross-function flows (a jitted fn calling a helper that syncs) are out of
scope — keep helpers either pure or inline. Suppress deliberate host code
with an inline ``filolint: ignore[jit-host-sync]`` comment.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .findings import Finding

HOST_SYNC_ATTRS = {"item"}            # x.item()
JAX_SYNC_FUNCS = {"device_get"}       # jax.device_get(x)
NUMPY_SYNC_FUNCS = {"asarray", "array"}
UNHASHABLE_CTORS = {"list", "dict", "set", "bytearray"}
MUTABLE_CTORS = {"list", "dict", "set", "defaultdict", "OrderedDict",
                 "Counter", "deque", "bytearray"}
STATIC_TEST_CALLS = {"len", "isinstance", "getattr", "hasattr", "callable"}
STATIC_TEST_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes"}


def _dotted(node: ast.expr) -> str | None:
    """'jax.jit' for Attribute chains / Names, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class _JitInfo:
    node: ast.FunctionDef
    qualname: str
    static_names: set = field(default_factory=set)
    static_nums: set = field(default_factory=set)   # positional indices
    donate_names: set = field(default_factory=set)
    donate_nums: set = field(default_factory=set)   # positional indices
    aliases: set = field(default_factory=set)       # names callable at sites

    def params(self) -> list[str]:
        a = self.node.args
        return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
                + [p.arg for p in a.kwonlyargs])

    def _resolve(self, names: set, nums: set) -> set:
        out = set(names)
        plist = self.params()
        for i in nums:
            if 0 <= i < len(plist):
                out.add(plist[i])
        return out

    def static_params(self) -> set:
        return self._resolve(self.static_names, self.static_nums)

    def donated_params(self) -> set:
        return self._resolve(self.donate_names, self.donate_nums)


class _ModuleIndex(ast.NodeVisitor):
    """First pass: numpy/jax import aliases, module-level mutable globals,
    and the set of jitted functions (with their static-arg info)."""

    def __init__(self):
        self.numpy_aliases: set[str] = set()
        self.jax_aliases: set[str] = {"jax"}
        self.jit_names: set[str] = set()       # bare names that mean jax.jit
        self.partial_names: set[str] = {"partial"}
        self.mutable_globals: dict[str, int] = {}
        self.module_names: set[str] = set()    # imports/defs/module assigns
        self._scope: list[str] = []
        self.by_name: dict[str, list[tuple[str, ast.FunctionDef]]] = {}

    def visit_Import(self, node: ast.Import):  # noqa: N802
        for a in node.names:
            as_ = a.asname or a.name.split(".")[0]
            self.module_names.add(as_)
            if a.name == "numpy":
                self.numpy_aliases.add(as_)
            elif a.name == "jax":
                self.jax_aliases.add(as_)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):  # noqa: N802
        for a in node.names:
            self.module_names.add(a.asname or a.name)
        if node.module == "jax":
            for a in node.names:
                if a.name == "jit":
                    self.jit_names.add(a.asname or "jit")
        if node.module == "functools":
            for a in node.names:
                if a.name == "partial":
                    self.partial_names.add(a.asname or "partial")
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef):  # noqa: N802
        qual = ".".join(self._scope + [node.name]) or node.name
        self.by_name.setdefault(node.name, []).append((qual, node))
        if not self._scope:
            self.module_names.add(node.name)
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef):  # noqa: N802
        if not self._scope:
            self.module_names.add(node.name)
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    def visit_Assign(self, node: ast.Assign):  # noqa: N802
        if not self._scope:    # module level only
            val = node.value
            mutable = isinstance(val, (ast.List, ast.Dict, ast.Set,
                                       ast.ListComp, ast.DictComp,
                                       ast.SetComp))
            if isinstance(val, ast.Call):
                callee = _dotted(val.func)
                if callee and callee.split(".")[-1] in MUTABLE_CTORS:
                    mutable = True
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.module_names.add(t.id)
                    if mutable:
                        self.mutable_globals[t.id] = node.lineno
        self.generic_visit(node)


SCATTER_UPDATE_ATTRS = {"set", "add", "subtract", "multiply", "divide",
                        "min", "max", "power", "apply"}


def _names_flowing_to_return(fn: ast.FunctionDef) -> set:
    """Over-approximate the set of names whose value can reach a ``return``
    expression: seed with the names read in return expressions, close
    backwards through (Ann/Aug)Assign statements, ``for``/``with`` target
    bindings, and mutating method calls on a name (``out.append(x)`` makes
    ``out`` depend on ``x``). Reassignment versions are not distinguished —
    over-approximation only ever SUPPRESSES findings."""
    deps: dict[str, set] = {}

    def _loads(expr: ast.expr | None) -> set:
        if expr is None:
            return set()
        return {n.id for n in ast.walk(expr)
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}

    def _bind(targets, names: set) -> None:
        for t in targets:
            for tn in ast.walk(t):
                if isinstance(tn, ast.Name) and isinstance(tn.ctx,
                                                           (ast.Store,
                                                            ast.Load)):
                    deps.setdefault(tn.id, set()).update(names)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            _bind(node.targets, _loads(node.value))
        elif isinstance(node, ast.AugAssign):
            names = _loads(node.value)
            if isinstance(node.target, ast.Name):
                names.add(node.target.id)
            _bind([node.target], names)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            _bind([node.target], _loads(node.value))
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            _bind([node.target], _loads(node.iter))
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    _bind([item.optional_vars], _loads(item.context_expr))
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and isinstance(node.func.value, ast.Name)):
            # a method call may mutate its receiver with the args' values
            names = set()
            for a in node.args:
                names |= _loads(a)
            for kw in node.keywords:
                names |= _loads(kw.value)
            if names:
                deps.setdefault(node.func.value.id, set()).update(names)
    flowing: set = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            flowing |= {n.id for n in ast.walk(node.value)
                        if isinstance(n, ast.Name)
                        and isinstance(n.ctx, ast.Load)}
    changed = True
    while changed:
        changed = False
        for name in list(flowing):
            for s in deps.get(name, ()):
                if s not in flowing:
                    flowing.add(s)
                    changed = True
    return flowing


def _scatter_updated_params(fn: ast.FunctionDef, params: set) -> dict:
    """{param name: first lineno} of parameters used as the BASE of an
    in-place-eligible ``p.at[...].set/add/...`` update chain."""
    out: dict[str, int] = {}
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in SCATTER_UPDATE_ATTRS
                and isinstance(node.func.value, ast.Subscript)
                and isinstance(node.func.value.value, ast.Attribute)
                and node.func.value.value.attr == "at"
                and isinstance(node.func.value.value.value, ast.Name)):
            continue
        base = node.func.value.value.value.id
        if base in params:
            out.setdefault(base, node.lineno)
    return out


class JitChecker:
    rules = ("jit-host-sync", "jit-traced-branch", "jit-mutable-closure",
             "jit-static-args", "jit-donation-unused")

    def check_module(self, path: str, tree: ast.Module) -> list[Finding]:
        idx = _ModuleIndex()
        idx.visit(tree)
        jitted = self._find_jitted(tree, idx)
        findings: list[Finding] = []
        for info in jitted.values():
            findings += self._check_body(path, info, idx)
            findings += self._check_decoration(path, info)
            findings += self._check_donation(path, info)
        findings += self._check_call_sites(path, tree, jitted)
        return findings

    # -- donation discipline ----------------------------------------------

    def _check_donation(self, path: str, info: _JitInfo) -> list[Finding]:
        """jit-donation-unused, both directions: a donated argument that
        never flows to an output (the donation deletes an input for zero
        aliasing win), and a scatter-updated-and-returned parameter that is
        NOT donated (a full buffer copy per call on the flush path)."""
        findings: list[Finding] = []
        donated = info.donated_params()
        params = set(info.params()) - {"self"}
        flowing = _names_flowing_to_return(info.node)
        for name in sorted(donated):
            if name not in flowing:
                findings.append(Finding(
                    "jit-donation-unused", path, info.node.lineno,
                    info.qualname, f"donated-unread:{name}",
                    f"donated argument {name!r} never flows to the jitted "
                    "function's return — the donation deletes the caller's "
                    "buffer without any in-place update to alias into; "
                    "drop it from donate_argnums or update-and-return it"))
        scattered = _scatter_updated_params(info.node, params)
        for name, lineno in sorted(scattered.items()):
            if name in flowing and name not in donated:
                findings.append(Finding(
                    "jit-donation-unused", path, lineno, info.qualname,
                    f"undonated-scatter:{name}",
                    f"parameter {name!r} is scatter-updated and returned "
                    "but not donated — the update allocates a full copy of "
                    "the buffer per call; donate it (donate_argnums) so "
                    "the commit updates the array in place, or suppress "
                    "with a reason if the copy is deliberate"))
        return findings

    # -- recognizing jitted functions ------------------------------------

    def _is_jit_expr(self, node: ast.expr, idx: _ModuleIndex) -> bool:
        d = _dotted(node)
        if d is None:
            return False
        if d in idx.jit_names:
            return True
        parts = d.split(".")
        return len(parts) == 2 and parts[0] in idx.jax_aliases \
            and parts[1] == "jit"

    def _jit_call_static(self, call: ast.Call, info: _JitInfo) -> None:
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for v in ast.walk(kw.value):
                    if isinstance(v, ast.Constant) and isinstance(v.value, str):
                        info.static_names.add(v.value)
            elif kw.arg == "static_argnums":
                for v in ast.walk(kw.value):
                    if isinstance(v, ast.Constant) and isinstance(v.value, int):
                        info.static_nums.add(v.value)
            elif kw.arg == "donate_argnames":
                for v in ast.walk(kw.value):
                    if isinstance(v, ast.Constant) and isinstance(v.value, str):
                        info.donate_names.add(v.value)
            elif kw.arg == "donate_argnums":
                for v in ast.walk(kw.value):
                    if isinstance(v, ast.Constant) and isinstance(v.value, int):
                        info.donate_nums.add(v.value)

    def _find_jitted(self, tree: ast.Module,
                     idx: _ModuleIndex) -> dict[int, _JitInfo]:
        jitted: dict[int, _JitInfo] = {}

        def mark(fn: ast.FunctionDef, qual: str) -> _JitInfo:
            info = jitted.get(id(fn))
            if info is None:
                info = jitted[id(fn)] = _JitInfo(fn, qual)
                info.aliases.add(fn.name)
            return info

        # decorators
        for qual_list in idx.by_name.values():
            for qual, fn in qual_list:
                for dec in fn.decorator_list:
                    if self._is_jit_expr(dec, idx):
                        mark(fn, qual)
                    elif isinstance(dec, ast.Call):
                        callee = _dotted(dec.func)
                        if callee and (callee.split(".")[-1]
                                       in idx.partial_names) and dec.args \
                                and self._is_jit_expr(dec.args[0], idx):
                            info = mark(fn, qual)
                            self._jit_call_static(dec, info)
                        elif self._is_jit_expr(dec.func, idx):
                            info = mark(fn, qual)
                            self._jit_call_static(dec, info)

        # wrapping assignments / factory returns: jax.jit(f, ...)
        for node in ast.walk(tree):
            call = None
            alias = None
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                call = node.value
                if len(node.targets) == 1 and isinstance(node.targets[0],
                                                         ast.Name):
                    alias = node.targets[0].id
            elif isinstance(node, ast.Return) and isinstance(node.value,
                                                             ast.Call):
                call = node.value
            if call is None or not self._is_jit_expr(call.func, idx):
                continue
            if not call.args or not isinstance(call.args[0], ast.Name):
                continue   # jax.jit(partial(...)) — target not resolvable
            target = call.args[0].id
            for qual, fn in idx.by_name.get(target, ()):
                info = mark(fn, qual)
                self._jit_call_static(call, info)
                if alias:
                    info.aliases.add(alias)
        return jitted

    # -- body checks ------------------------------------------------------

    def _check_body(self, path: str, info: _JitInfo,
                    idx: _ModuleIndex) -> list[Finding]:
        findings: list[Finding] = []
        static = info.static_params()
        traced = set(info.params()) - static - {"self"}
        qual = info.qualname

        for node in ast.walk(info.node):
            if isinstance(node, ast.Call):
                findings += self._sync_call(path, qual, node, static, idx)
            elif isinstance(node, (ast.If, ast.While)):
                name = self._traced_test_name(node.test, traced)
                if name is not None:
                    findings.append(Finding(
                        "jit-traced-branch", path, node.lineno, qual,
                        f"branch:{name}",
                        f"Python branch on traced value {name!r} inside a "
                        "jitted function — traces fail (or retrace per value "
                        "if made static); use jnp.where/lax.cond"))
            elif isinstance(node, ast.Global):
                findings.append(Finding(
                    "jit-mutable-closure", path, node.lineno, qual,
                    f"global:{','.join(node.names)}",
                    "mutating module state from a jitted function never "
                    "lands in the compiled program"))
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id in idx.mutable_globals and node.id not in traced \
                        and node.id not in static \
                        and not self._is_local(info.node, node.id):
                    findings.append(Finding(
                        "jit-mutable-closure", path, node.lineno, qual,
                        f"closure:{node.id}",
                        f"jitted function closes over mutable module global "
                        f"{node.id!r} (defined line "
                        f"{idx.mutable_globals[node.id]}); its value is "
                        "frozen at trace time — pass it as an argument"))
        return findings

    @staticmethod
    def _is_local(fn: ast.FunctionDef, name: str) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store) \
                    and node.id == name:
                return True
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn and name in [a.arg for a in
                                                    node.args.args]:
                return True
        return False

    @staticmethod
    def _maybe_traced(expr: ast.expr, static: set,
                      idx: _ModuleIndex) -> bool:
        """Could this expression carry a traced value? False when every Name
        it references is a module-level constant/import or a static param —
        then the call is a trace-time constant, the idiomatic way to bake
        host math into the program (e.g. float(np.log(GAMMA)))."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                if n.id not in idx.module_names and n.id not in static:
                    return True
        return False

    def _sync_call(self, path: str, qual: str, node: ast.Call,
                   static: set, idx: _ModuleIndex) -> list[Finding]:
        func = node.func
        # float(x) on a potentially-traced value (params/locals); float() of
        # module constants is trace-time host math and fine
        if isinstance(func, ast.Name) and func.id == "float" and node.args:
            if self._maybe_traced(node.args[0], static, idx):
                return [Finding(
                    "jit-host-sync", path, node.lineno, qual, "float()",
                    "float() on a traced value inside jit is a device→host "
                    "sync (ConcretizationError on abstract values) — keep it "
                    "as a 0-d array or make the argument static")]
        if isinstance(func, ast.Attribute):
            if func.attr in HOST_SYNC_ATTRS \
                    and self._maybe_traced(func.value, static, idx):
                return [Finding(
                    "jit-host-sync", path, node.lineno, qual, ".item()",
                    ".item() inside jit forces a device→host sync — return "
                    "the array and fetch outside the jitted function")]
            d = _dotted(func)
            if d:
                root, _, leaf = d.rpartition(".")
                if root in idx.numpy_aliases and leaf in NUMPY_SYNC_FUNCS \
                        and any(self._maybe_traced(a, static, idx)
                                for a in node.args):
                    return [Finding(
                        "jit-host-sync", path, node.lineno, qual, f"np.{leaf}",
                        f"{d}() inside jit materializes the traced value on "
                        "host — use jnp instead, or hoist out of the jitted "
                        "function")]
                if root in idx.jax_aliases and leaf in JAX_SYNC_FUNCS:
                    return [Finding(
                        "jit-host-sync", path, node.lineno, qual,
                        f"jax.{leaf}",
                        f"{d}() inside jit is a device→host transfer — fetch "
                        "outside the compiled function")]
        return []

    def _traced_test_name(self, test: ast.expr, traced: set) -> str | None:
        """The name of a traced parameter the branch condition depends on,
        or None when the test is statically evaluable (shape/len/isinstance/
        `is (not) None` forms)."""
        hits: list[str] = []

        def scan(node: ast.expr):
            if isinstance(node, ast.Attribute):
                if node.attr in STATIC_TEST_ATTRS:
                    return
                scan(node.value)
            elif isinstance(node, ast.Call):
                fname = _dotted(node.func)
                if fname and fname.split(".")[-1] in STATIC_TEST_CALLS:
                    return
                for a in node.args:
                    scan(a)
            elif isinstance(node, ast.Compare):
                if all(isinstance(op, (ast.Is, ast.IsNot))
                       for op in node.ops):
                    return
                scan(node.left)
                for c in node.comparators:
                    scan(c)
            elif isinstance(node, ast.BoolOp):
                for v in node.values:
                    scan(v)
            elif isinstance(node, ast.UnaryOp):
                scan(node.operand)
            elif isinstance(node, ast.BinOp):
                scan(node.left)
                scan(node.right)
            elif isinstance(node, ast.Subscript):
                scan(node.value)
            elif isinstance(node, ast.Name) and node.id in traced:
                hits.append(node.id)

        scan(test)
        return hits[0] if hits else None

    # -- decoration + call-site checks ------------------------------------

    def _check_decoration(self, path: str, info: _JitInfo) -> list[Finding]:
        findings = []
        static = info.static_params()
        args = info.node.args
        defaults = dict(zip([a.arg for a in args.args][-len(args.defaults):]
                            if args.defaults else [], args.defaults))
        for name in sorted(static):
            d = defaults.get(name)
            if isinstance(d, ast.Constant) and isinstance(d.value, float):
                findings.append(Finding(
                    "jit-static-args", path, info.node.lineno, info.qualname,
                    f"static-float:{name}",
                    f"static arg {name!r} defaults to a float — each "
                    "distinct value retraces the whole program; pass floats "
                    "as traced 0-d arrays"))
        return findings

    def _check_call_sites(self, path: str, tree: ast.Module,
                          jitted: dict[int, _JitInfo]) -> list[Finding]:
        by_alias: dict[str, _JitInfo] = {}
        for info in jitted.values():
            for alias in info.aliases:
                by_alias[alias] = info
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            info = by_alias.get(node.func.id)
            if info is None or node.func.id == info.node.name and \
                    node.lineno == info.node.lineno:
                continue
            plist = info.params()
            static = info.static_params()
            for i, arg in enumerate(node.args):
                if i < len(plist) and plist[i] in static:
                    findings += self._static_arg_value(
                        path, node.func.id, plist[i], arg)
            for kw in node.keywords:
                if kw.arg in static:
                    findings += self._static_arg_value(
                        path, node.func.id, kw.arg, kw.value)
        return findings

    def _static_arg_value(self, path: str, callee: str, pname: str,
                          arg: ast.expr) -> list[Finding]:
        sym = f"<call:{callee}>"
        if isinstance(arg, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                            ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            return [Finding(
                "jit-static-args", path, arg.lineno, sym,
                f"unhashable:{pname}",
                f"unhashable value for static arg {pname!r} of jitted "
                f"{callee}() — static args are dict keys of the trace "
                "cache; pass a tuple")]
        if isinstance(arg, ast.Call):
            fname = _dotted(arg.func)
            leaf = fname.split(".")[-1] if fname else ""
            if leaf in UNHASHABLE_CTORS or (fname and leaf in ("asarray",
                                                               "array")):
                return [Finding(
                    "jit-static-args", path, arg.lineno, sym,
                    f"unhashable:{pname}",
                    f"unhashable {fname}(...) for static arg {pname!r} of "
                    f"jitted {callee}() — static args must be hashable")]
            if leaf == "float":
                return [Finding(
                    "jit-static-args", path, arg.lineno, sym,
                    f"float:{pname}",
                    f"float-typed static arg {pname!r} of jitted {callee}() "
                    "— retraces per distinct value")]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, float):
            return [Finding(
                "jit-static-args", path, arg.lineno, sym, f"float:{pname}",
                f"float literal for static arg {pname!r} of jitted "
                f"{callee}() — retraces per distinct value; hoist to a "
                "module constant or pass as a traced 0-d array")]
        return []
