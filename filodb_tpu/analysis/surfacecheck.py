"""Declared-surface checker: config keys and metric names.

A production system's operational surface — the config knobs it reads and
the metrics it exports — must be DECLARED, not discovered by grepping.
The reference keeps 367 lines of documented defaults in
filodb-defaults.conf; here one dict is the single source of truth per
surface, and these rules make drift impossible:

  * ``surface-config-undeclared`` — every dotted config key read through
    a Config receiver (``cfg["ingest.decode_ahead"]``, ``cfg.get(...)``,
    ``self.config[...]``) must be a key of ``CONFIG_SPEC`` (declared in
    filodb_tpu/config.py with type/default/doc; DEFAULTS is derived from
    it, so an undeclared key is also an unreadable one).
  * ``surface-config-unused`` — a declared key that no code reads (by
    full dotted name or by leaf segment — ``store_config()`` reads leaves
    off the sub-dict) is dead surface: a typo'd rename or a removed
    feature still showing up in docs.
  * ``surface-config-type`` — a declared default literal that cannot
    satisfy its declared type string (an ``int`` defaulting to a string,
    a ``duration`` defaulting to ``"5x"``, a non-null default missing its
    ``|null``): DEFAULTS derives from the spec, so such a key ships a
    value the declared readers (``int(...)``, ``parse_duration_ms``)
    crash on the first time an operator relies on the default. Only
    LITERAL defaults are judged — computed expressions (``1 << 20``) are
    skipped, never guessed.
  * ``surface-metric-undeclared`` — every ``filodb_*`` metric registered
    via ``registry.counter/gauge/histogram`` must be one of the declared
    name CONSTANTS in utils/metrics.py's ``METRICS_SPEC`` (call sites use
    the constant; a raw string literal is flagged even when the name
    matches). F-string names must match a declared wildcard family
    (``filodb_shard_*``).
  * ``surface-metric-kind`` — registering a declared name under a
    different instrument kind than the spec (a counter re-registered as a
    gauge is a Prometheus type conflict at scrape time).
  * ``surface-metric-duplicate`` — two declared constants sharing one
    metric-name string: both sites export under the same series name and
    their values interleave meaninglessly.
  * ``surface-metric-unused`` — a declared metric no code registers.
  * ``surface-trace-undeclared`` — every span name at a ``span(...)`` /
    ``tracer.span(...)`` call site must be one of the declared ``SPAN_*``
    constants in utils/tracing.py's ``TRACE_SPEC`` (a raw string literal
    is flagged even when the name matches — the taxonomy has exactly one
    spelling per span).
  * ``surface-trace-unused`` — a declared span no code opens.
  * ``surface-cache-unbounded`` / ``surface-cache-no-eviction-metric`` —
    every class named ``*Cache`` must expose a capacity bound (a
    ``capacity``/``maxsize``/``max_entries`` parameter or attribute, or a
    ``maxlen=``-bounded container) and account its evictions (an
    identifier or metric name containing "eviction"). An unbounded cache
    is a slow memory leak with no operational signal; the PR 8 plan and
    result caches set the contract and this rule keeps every future cache
    honest.
  * ``surface-cache-unbounded-bytes`` — a ``*Cache`` class that ACCOUNTS
    bytes (stores an attribute whose name contains "bytes") holds
    variable-size entries, so an entry-count bound alone does not bound
    memory: it must also declare a byte capacity (``max_bytes`` /
    ``capacity_bytes`` parameter or attribute). The PR 13 fragment cache
    (per-step value columns of wildly varying width) set this contract.

All three surfaces are verified against the docs tables by
tests/test_static_analysis.py (README tables are generated from the same
dicts), so docs cannot drift either. When an analysis run's module set
contains no spec (narrow ``--changed-only`` scopes, fixture self-tests
that define their own), the corresponding rules are skipped rather than
guessed.
"""

from __future__ import annotations

import ast

from .callgraph import dotted_name
from .findings import Finding

CONFIG_RECEIVERS = {"cfg", "config"}
METRIC_KINDS = {"counter", "gauge", "histogram"}
METRIC_PREFIX = "filodb_"


def _const_str(node: ast.expr) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _is_config_receiver(expr: ast.expr) -> bool:
    if isinstance(expr, ast.Name):
        return expr.id in CONFIG_RECEIVERS
    if isinstance(expr, ast.Attribute):
        return expr.attr in CONFIG_RECEIVERS
    return False


def _fstring_prefix(node: ast.JoinedStr) -> str | None:
    """Leading literal text of an f-string ('' if it starts dynamic)."""
    if node.values and isinstance(node.values[0], ast.Constant) and \
            isinstance(node.values[0].value, str):
        return node.values[0].value
    return ""


CACHE_CAP_NAMES = {"capacity", "maxsize", "max_entries", "maxlen"}
# byte-capacity spellings: required for caches that ACCOUNT bytes (their
# entries vary in size — an entry-count bound alone does not bound memory)
CACHE_BYTE_CAP_NAMES = {"max_bytes", "capacity_bytes", "bytes_capacity",
                        "byte_capacity"}


class SurfaceChecker:
    rules = ("surface-config-undeclared", "surface-config-unused",
             "surface-config-type",
             "surface-metric-undeclared", "surface-metric-kind",
             "surface-metric-duplicate", "surface-metric-unused",
             "surface-trace-undeclared", "surface-trace-unused",
             "surface-cache-unbounded", "surface-cache-no-eviction-metric",
             "surface-cache-unbounded-bytes")

    def __init__(self):
        self._modules: dict[str, ast.Module] = {}
        self.project = None             # unused; kept for checker symmetry
        # ``full_scope=False`` (narrow --changed-only runs) skips the
        # *-unused rules: a registration outside the analyzed set is not
        # evidence of dead surface
        self.full_scope = True

    def check_module(self, path: str, tree: ast.Module) -> list[Finding]:
        self._modules[path] = tree
        return self._check_cache_classes(path, tree)

    # -- bounded caches -------------------------------------------------------

    def _check_cache_classes(self, path: str,
                             tree: ast.Module) -> list[Finding]:
        """Every ``*Cache`` class needs a capacity bound and eviction
        accounting — purely lexical (names and keywords), which is exactly
        the contract: the bound and the signal must be VISIBLE in the
        class, not implied by usage elsewhere."""
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef) \
                    or not node.name.lower().endswith("cache"):
                continue
            has_cap = has_evict = False
            has_byte_cap = has_byte_acct = False
            # docstrings don't count as eviction ACCOUNTING — "eviction is
            # handled elsewhere" in prose must not satisfy the rule
            doc_ids = {
                id(sub.body[0].value) for sub in ast.walk(node)
                if isinstance(sub, (ast.ClassDef, ast.FunctionDef,
                                    ast.AsyncFunctionDef))
                and sub.body and isinstance(sub.body[0], ast.Expr)
                and isinstance(sub.body[0].value, ast.Constant)
                and isinstance(sub.body[0].value.value, str)
            }
            for sub in ast.walk(node):
                if isinstance(sub, ast.arg) and sub.arg in CACHE_CAP_NAMES:
                    has_cap = True
                elif isinstance(sub, ast.Attribute) \
                        and isinstance(sub.ctx, ast.Store) \
                        and sub.attr in CACHE_CAP_NAMES:
                    has_cap = True
                elif isinstance(sub, ast.keyword) \
                        and sub.arg in ("maxlen", "maxsize"):
                    has_cap = True
                if isinstance(sub, ast.arg) \
                        and sub.arg in CACHE_BYTE_CAP_NAMES:
                    has_byte_cap = True
                elif isinstance(sub, ast.Attribute) \
                        and isinstance(sub.ctx, ast.Store):
                    if sub.attr in CACHE_BYTE_CAP_NAMES:
                        has_byte_cap = True
                    elif "bytes" in sub.attr.lower():
                        # byte ACCOUNTING (e.g. self._bytes running total):
                        # variable-size entries — demands a byte capacity
                        has_byte_acct = True
                ident = None
                if isinstance(sub, ast.Attribute):
                    ident = sub.attr
                elif isinstance(sub, ast.Name):
                    ident = sub.id
                elif isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str) \
                        and id(sub) not in doc_ids:
                    ident = sub.value
                if ident is not None and "eviction" in ident.lower():
                    has_evict = True
            if not has_cap:
                findings.append(Finding(
                    "surface-cache-unbounded", path, node.lineno, node.name,
                    f"class:{node.name}",
                    f"cache class {node.name} has no visible capacity bound "
                    "(capacity/maxsize/max_entries attribute or param, or a "
                    "maxlen-bounded container) — an unbounded cache is a "
                    "slow memory leak"))
            if not has_evict:
                findings.append(Finding(
                    "surface-cache-no-eviction-metric", path, node.lineno,
                    node.name, f"evictions:{node.name}",
                    f"cache class {node.name} never accounts evictions (no "
                    "identifier or metric containing 'eviction') — capacity "
                    "pressure must be operationally visible, not silent"))
            if has_byte_acct and not has_byte_cap:
                findings.append(Finding(
                    "surface-cache-unbounded-bytes", path, node.lineno,
                    node.name, f"bytes:{node.name}",
                    f"cache class {node.name} accounts bytes (its entries "
                    "vary in size) but declares no byte capacity "
                    "(max_bytes/capacity_bytes) — an entry-count bound "
                    "alone does not bound memory for variable-size "
                    "entries"))
        return findings

    def finalize(self) -> list[Finding]:
        findings: list[Finding] = []
        findings += self._check_config()
        findings += self._check_metrics()
        findings += self._check_traces()
        return findings

    # -- config ---------------------------------------------------------------

    def _find_spec_dict(self, name: str) -> tuple[str, ast.Dict] | None:
        for path, tree in self._modules.items():
            for node in tree.body:
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Dict):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == name:
                            return path, node.value
                if isinstance(node, ast.AnnAssign) and \
                        isinstance(node.value, ast.Dict) and \
                        isinstance(node.target, ast.Name) and \
                        node.target.id == name:
                    return path, node.value
        return None

    _DURATION_RE = None     # compiled lazily (module import stays light)

    @classmethod
    def _default_matches(cls, typ: str, node: ast.expr) -> bool:
        """True unless the default LITERAL provably violates ``typ``.
        Computed expressions return True (skipped, never guessed)."""
        import re as _re
        if typ.endswith("|null"):
            if isinstance(node, ast.Constant) and node.value is None:
                return True
            typ = typ[:-len("|null")]
        if isinstance(node, ast.UnaryOp) and \
                isinstance(node.op, ast.USub) and \
                isinstance(node.operand, ast.Constant):
            node = node.operand
        if typ.startswith("list[") and typ.endswith("]"):
            if not isinstance(node, ast.List):
                return not isinstance(node, (ast.Constant, ast.Dict))
            inner = typ[5:-1]
            return all(cls._default_matches(inner, el) for el in node.elts)
        if typ == "dict":
            return isinstance(node, ast.Dict) or \
                not isinstance(node, (ast.Constant, ast.List))
        if not isinstance(node, ast.Constant):
            return True            # computed expression: not judged
        v = node.value
        if typ == "bool":
            return isinstance(v, bool)
        if typ == "int":
            return isinstance(v, int) and not isinstance(v, bool)
        if typ == "float":
            return isinstance(v, (int, float)) and not isinstance(v, bool)
        if typ == "str":
            return isinstance(v, str)
        if typ == "duration":
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                return True        # raw milliseconds are accepted anywhere
            if cls._DURATION_RE is None:
                cls._DURATION_RE = _re.compile(r"\d+(?:\.\d+)?(?:ms|[smhd])")
            return isinstance(v, str) and \
                cls._DURATION_RE.fullmatch(v) is not None
        return True                # unknown type string: out of scope

    def _check_config(self) -> list[Finding]:
        spec = self._find_spec_dict("CONFIG_SPEC")
        if spec is None:
            return []              # narrow scope: nothing to check against
        spec_path, spec_dict = spec
        declared: dict[str, int] = {}
        spec_key_ids: set = set()
        for k in spec_dict.keys:
            s = _const_str(k) if k is not None else None
            if s is not None:
                declared[s] = k.lineno
                spec_key_ids.add(id(k))
        findings: list[Finding] = []
        # default-vs-type parity: the spec IS the deployment contract, so
        # a default its own declared type cannot represent is a shipped bug
        for k, v in zip(spec_dict.keys, spec_dict.values):
            key = _const_str(k) if k is not None else None
            if key is None or not isinstance(v, ast.Tuple) \
                    or len(v.elts) < 2:
                continue
            typ = _const_str(v.elts[0])
            if typ and not self._default_matches(typ, v.elts[1]):
                findings.append(Finding(
                    "surface-config-type", spec_path, k.lineno,
                    "CONFIG_SPEC", f"type:{key}",
                    f"config key {key!r} declares type {typ!r} but its "
                    "default literal cannot satisfy it — the derived "
                    "DEFAULTS tree would hand readers a value their "
                    "declared parser crashes on"))
        used_full: set = set()
        all_strings: set = set()
        for path, tree in self._modules.items():
            for node in ast.walk(tree):
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str) and \
                        id(node) not in spec_key_ids:
                    # the spec's own key literals don't count as usage —
                    # otherwise a dead TOP-LEVEL key (leaf == key) could
                    # never be flagged unused
                    all_strings.add(node.value)
                key = recv = None
                if isinstance(node, ast.Subscript) and \
                        _is_config_receiver(node.value):
                    key = _const_str(node.slice)
                    recv = node.value
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "get" and \
                        _is_config_receiver(node.func.value) and node.args:
                    key = _const_str(node.args[0])
                    recv = node.func.value
                if key is None or recv is None:
                    continue
                used_full.add(key)
                if key not in declared:
                    qual = self._enclosing(tree, node)
                    findings.append(Finding(
                        "surface-config-undeclared", path, node.lineno,
                        qual, f"key:{key}",
                        f"config key {key!r} is not declared in CONFIG_SPEC "
                        f"({spec_path}) — declare it with type/default/doc "
                        "(DEFAULTS derives from the spec, so an undeclared "
                        "key KeyErrors at runtime anyway)"))
        for key, line in sorted(declared.items()):
            if not self.full_scope:
                break
            leaf = key.rsplit(".", 1)[-1]
            if key not in used_full and leaf not in all_strings:
                findings.append(Finding(
                    "surface-config-unused", spec_path, line, "CONFIG_SPEC",
                    f"key:{key}",
                    f"declared config key {key!r} is never read anywhere in "
                    "the analyzed set — dead surface; remove it or wire it "
                    "up"))
        return findings

    # -- metrics --------------------------------------------------------------

    def _metric_constants(self) -> tuple[str, dict, dict] | None:
        """(spec path, constant name -> value, metric value -> (kind, const
        name)) from the module that declares METRICS_SPEC."""
        spec = self._find_spec_dict("METRICS_SPEC")
        if spec is None:
            return None
        path, spec_dict = spec
        tree = self._modules[path]
        consts: dict[str, str] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                v = _const_str(node.value)
                if v is not None and v.startswith(METRIC_PREFIX):
                    consts[node.targets[0].id] = v
        entries: dict[str, tuple[str, str, int]] = {}   # value -> (kind, const, line)
        for k, v in zip(spec_dict.keys, spec_dict.values):
            name = None
            const = None
            if isinstance(k, ast.Name):
                const = k.id
                name = consts.get(k.id)
            else:
                name = _const_str(k)
            kind = ""
            if isinstance(v, ast.Tuple) and v.elts:
                kind = _const_str(v.elts[0]) or ""
            if name is not None:
                entries[name] = (kind, const or name, k.lineno)
        return path, consts, entries

    def _check_metrics(self) -> list[Finding]:
        meta = self._metric_constants()
        if meta is None:
            return []
        spec_path, consts, entries = meta
        findings: list[Finding] = []
        # duplicate name values in the spec/constants
        by_value: dict[str, str] = {}
        for cname, value in sorted(consts.items()):
            if value in by_value:
                findings.append(Finding(
                    "surface-metric-duplicate", spec_path, 1, "METRICS_SPEC",
                    f"dup:{value}",
                    f"metric constants {by_value[value]} and {cname} share "
                    f"the name {value!r} — two semantic sites exporting one "
                    "series interleave meaninglessly; rename one"))
            else:
                by_value[value] = cname
        registered: set = set()
        wildcards = {n[:-1] for n in entries if n.endswith("*")}
        for path, tree in self._modules.items():
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in METRIC_KINDS and node.args):
                    continue
                recv = dotted_name(node.func.value) or ""
                if not (recv == "registry" or recv.endswith(".reg")
                        or recv in ("reg", "self.reg")):
                    continue
                kind = node.func.attr
                arg = node.args[0]
                qual = self._enclosing(tree, node)
                lit = _const_str(arg)
                if lit is not None and lit.startswith(METRIC_PREFIX):
                    findings.append(Finding(
                        "surface-metric-undeclared", path, node.lineno, qual,
                        f"literal:{lit}",
                        f"metric {lit!r} registered from a string literal — "
                        "use the declared constant from utils/metrics.py "
                        "METRICS_SPEC so the name has exactly one spelling"))
                    continue
                if isinstance(arg, ast.JoinedStr):
                    prefix = _fstring_prefix(arg)
                    if prefix.startswith(METRIC_PREFIX):
                        fam = next((w for w in wildcards
                                    if prefix.startswith(w)), None)
                        if fam is None:
                            findings.append(Finding(
                                "surface-metric-undeclared", path,
                                node.lineno, qual, f"family:{prefix}",
                                f"dynamic metric family {prefix!r}* has no "
                                "wildcard entry in METRICS_SPEC — declare "
                                "the family with kind and doc"))
                        else:
                            registered.add(fam + "*")
                            spec_kind = entries.get(fam + "*", ("",))[0]
                            if spec_kind and spec_kind != kind:
                                findings.append(Finding(
                                    "surface-metric-kind", path, node.lineno,
                                    qual, f"kind:{prefix}*",
                                    f"family {prefix!r}* registered as "
                                    f"{kind} but declared as {spec_kind}"))
                    continue
                cname = None
                if isinstance(arg, ast.Name):
                    cname = arg.id
                elif isinstance(arg, ast.Attribute):
                    cname = arg.attr
                if cname is None:
                    continue
                value = consts.get(cname)
                if value is None:
                    if cname.startswith("FILODB_"):
                        findings.append(Finding(
                            "surface-metric-undeclared", path, node.lineno,
                            qual, f"const:{cname}",
                            f"metric constant {cname} is not declared in "
                            "utils/metrics.py METRICS_SPEC"))
                    continue
                registered.add(value)
                spec_kind = entries.get(value, ("",))[0]
                if spec_kind and spec_kind != kind:
                    findings.append(Finding(
                        "surface-metric-kind", path, node.lineno, qual,
                        f"kind:{value}",
                        f"metric {value!r} registered as {kind} but "
                        f"declared as {spec_kind} — a kind mismatch is a "
                        "Prometheus type conflict at scrape time"))
        for name, (kind, const, line) in sorted(entries.items()):
            if not self.full_scope:
                break
            if name not in registered:
                findings.append(Finding(
                    "surface-metric-unused", spec_path, line, "METRICS_SPEC",
                    f"unused:{name}",
                    f"declared metric {name!r} is never registered in the "
                    "analyzed set — dead surface; remove the entry or wire "
                    "it up"))
        return findings

    # -- traces ---------------------------------------------------------------

    SPAN_CONST_PREFIX = "SPAN_"

    def _trace_constants(self) -> tuple[str, dict, dict] | None:
        """(spec path, constant name -> span name, span name -> (const,
        line)) from the module declaring TRACE_SPEC (utils/tracing.py in
        production; fixtures declare their own)."""
        spec = self._find_spec_dict("TRACE_SPEC")
        if spec is None:
            return None
        path, spec_dict = spec
        tree = self._modules[path]
        consts: dict[str, str] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id.startswith(self.SPAN_CONST_PREFIX):
                v = _const_str(node.value)
                if v is not None:
                    consts[node.targets[0].id] = v
        entries: dict[str, tuple[str, int]] = {}   # span name -> (const, line)
        for k in spec_dict.keys:
            if isinstance(k, ast.Name):
                name = consts.get(k.id)
                if name is not None:
                    entries[name] = (k.id, k.lineno)
            else:
                s = _const_str(k) if k is not None else None
                if s is not None:
                    entries[s] = (s, k.lineno)
        return path, consts, entries

    @staticmethod
    def _is_span_call(node: ast.Call) -> bool:
        """A ``span(...)`` / ``<tracer>.span(...)`` call site with a
        positional name argument (re.Match.span() and friends take none)."""
        if not node.args:
            return False
        f = node.func
        if isinstance(f, ast.Name):
            return f.id == "span"
        return isinstance(f, ast.Attribute) and f.attr == "span"

    def _check_traces(self) -> list[Finding]:
        meta = self._trace_constants()
        if meta is None:
            return []              # narrow scope: nothing to check against
        spec_path, consts, entries = meta
        findings: list[Finding] = []
        used: set[str] = set()
        for path, tree in self._modules.items():
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and self._is_span_call(node)):
                    continue
                arg = node.args[0]
                qual = self._enclosing(tree, node)
                lit = _const_str(arg)
                if lit is not None:
                    findings.append(Finding(
                        "surface-trace-undeclared", path, node.lineno, qual,
                        f"literal:{lit}",
                        f"span {lit!r} opened from a string literal — use "
                        "the declared SPAN_* constant from utils/tracing.py "
                        "TRACE_SPEC so the taxonomy has exactly one "
                        "spelling"))
                    continue
                cname = None
                if isinstance(arg, ast.Name):
                    cname = arg.id
                elif isinstance(arg, ast.Attribute):
                    cname = arg.attr
                if cname is None or \
                        not cname.startswith(self.SPAN_CONST_PREFIX):
                    continue       # a non-SPAN_ expression: not our surface
                value = consts.get(cname)
                if value is None or value not in entries:
                    findings.append(Finding(
                        "surface-trace-undeclared", path, node.lineno, qual,
                        f"const:{cname}",
                        f"span constant {cname} is not declared in "
                        f"TRACE_SPEC ({spec_path}) — declare it with a "
                        "one-line doc"))
                    continue
                used.add(value)
        for name, (const, line) in sorted(entries.items()):
            if not self.full_scope:
                break
            if name not in used:
                findings.append(Finding(
                    "surface-trace-unused", spec_path, line, "TRACE_SPEC",
                    f"unused:{name}",
                    f"declared span {name!r} ({const}) is never opened in "
                    "the analyzed set — dead surface; remove the entry or "
                    "wire it up"))
        return findings

    # -- shared ---------------------------------------------------------------

    @staticmethod
    def _enclosing(tree: ast.Module, target: ast.AST) -> str:
        best = "<module>"
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                for sub in ast.walk(node):
                    if sub is target:
                        best = node.name if best == "<module>" \
                            else f"{best}.{node.name}"
                        break
        return best
