"""mesh-sharding-undeclared: explicit boundary shardings on mesh programs.

The one-program mesh query path (ISSUE 16, parallel/distributed.py) jits
``shard_map`` bodies over GLOBAL sharded store operands. jax will happily
compile such a call with no ``in_shardings``/``out_shardings`` — or with only
one side declared — and silently insert resharding/gather transfers at the
undeclared boundary: the program still answers correctly, but every dispatch
re-gathers the sharded store blocks through one device, which is exactly the
host-loop cost the mesh path exists to delete. No unit test notices (results
match); only the dispatch-floor bench regresses. This rule makes the
contract structural, inside ``parallel/`` (fixture twins carry a
``bad_``/``good_`` prefix):

  * a ``jit``/``pjit`` call declaring ONE of ``in_shardings``/
    ``out_shardings`` is always a finding (the jax_graft pattern —
    SNIPPETS.md [2] — requires both or neither);
  * a ``jit``/``pjit`` call declaring NEITHER is a finding when sharded
    store operands observably cross it: the jitted callable is invoked
    (directly or via the assigned name) with an argument mentioning a
    sharded identifier (``slot_*``, ``global_*``, ``*sharded*``,
    ``dstore``). Bare jit over replicated scalars/step grids stays legal.
"""

from __future__ import annotations

import ast
import re

from .findings import Finding

# the mesh-program scope: every module under parallel/ plus the fixture twins
_MESH_MODULE = re.compile(
    r"(?:^|/)parallel/[^/]+\.py$"
    r"|(?:^|/)fixtures/filolint/(?:bad_|good_)mesh_sharding\.py$")

# identifiers that mark a global sharded store operand in this codebase
_SHARDED = re.compile(r"(?:^|_)(slot|global|sharded|dstore)", re.IGNORECASE)

_JIT_NAMES = ("jit", "pjit")


def _is_jit_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else None)
    return name in _JIT_NAMES


def _mentions_sharded(expr: ast.expr) -> str | None:
    """The first sharded-store identifier inside ``expr``, or None."""
    for node in ast.walk(expr):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name is not None and _SHARDED.search(name):
            return name
    return None


class MeshChecker:
    rules = ("mesh-sharding-undeclared",)

    def __init__(self):
        self.project = None          # unused; kept for checker symmetry

    def check_module(self, path: str, tree: ast.Module) -> list[Finding]:
        if not _MESH_MODULE.search(path):
            return []
        findings: list[Finding] = []
        bare_names: set[str] = set()
        bare_calls: list[ast.Call] = []
        for node in ast.walk(tree):
            if not _is_jit_call(node):
                continue
            kws = {k.arg for k in node.keywords}
            has_in = "in_shardings" in kws
            has_out = "out_shardings" in kws
            if has_in and has_out:
                continue
            if has_in or has_out:
                missing = "out_shardings" if has_in else "in_shardings"
                findings.append(Finding(
                    "mesh-sharding-undeclared", path, node.lineno,
                    self._enclosing(tree, node), f"half:{missing}",
                    f"mesh program declares only one boundary sharding — "
                    f"without {missing} jax infers the other side and "
                    "silently inserts a re-gather through one device; "
                    "declare BOTH in_shardings and out_shardings "
                    "(parallel/distributed.py _sharded_jit)"))
                continue
            bare_calls.append(node)
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.value in bare_calls:
                bare_names.add(node.targets[0].id)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            direct = node.func in bare_calls       # jit(f)(slot_...)
            via_name = (isinstance(node.func, ast.Name)
                        and node.func.id in bare_names)
            if not (direct or via_name):
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                name = _mentions_sharded(arg)
                if name is None:
                    continue
                findings.append(Finding(
                    "mesh-sharding-undeclared", path, node.lineno,
                    self._enclosing(tree, node), f"bare:{name}",
                    f"sharded store operand {name!r} crosses a jit "
                    "boundary with NO declared shardings — implicit "
                    "propagation re-gathers the global array through one "
                    "device on every dispatch; declare in_shardings and "
                    "out_shardings (parallel/distributed.py _sharded_jit)"))
                break
        return findings

    def finalize(self) -> list[Finding]:
        return []

    @staticmethod
    def _enclosing(tree: ast.Module, target: ast.AST) -> str:
        best = "<module>"
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                for sub in ast.walk(node):
                    if sub is target:
                        best = node.name if best == "<module>" \
                            else f"{best}.{node.name}"
                        break
        return best
