"""surface-decode-variant-twin: every decode variant declares BOTH backends.

The fused compressed-resident tier (ISSUE 17, ops/decodereg.py) streams
narrow blocks through TWO kernel backends built from one tiling plan — the
Pallas body and its XLA scan twin — and ``query.fused_kernels`` picks the
serving one at runtime. A decode variant registered with only one backend
twin compiles and passes every single-backend test, then silently breaks
variant parity the first time the OTHER mode serves it (the runtime guard in
``register_variant`` raises, but only on the import that registers — a
``pallas=None`` placeholder or a missing keyword reaches production as a
server that cannot flip modes). This rule makes the two-twin contract
structural, inside ``ops/decodereg.py`` (fixture twins carry a
``bad_``/``good_`` prefix):

  * every ``register_variant(...)`` call must pass BOTH ``pallas=`` and
    ``xla=`` keywords;
  * neither may be the literal ``None`` (the "wire it later" placeholder
    that defeats the runtime ValueError until the deferred import runs).
"""

from __future__ import annotations

import ast
import re

from .findings import Finding

# the decode-registry scope: the registry module plus the fixture twins
_DECODE_MODULE = re.compile(
    r"(?:^|/)ops/decodereg\.py$"
    r"|(?:^|/)fixtures/filolint/(?:bad_|good_)decode_variant\.py$")

_REQUIRED = ("pallas", "xla")


def _is_register_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else None)
    return name == "register_variant"


def _variant_name(node: ast.Call) -> str:
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    for k in node.keywords:
        if k.arg == "name" and isinstance(k.value, ast.Constant) \
                and isinstance(k.value.value, str):
            return k.value.value
    return "<dynamic>"


class DecodeChecker:
    rules = ("surface-decode-variant-twin",)

    def __init__(self):
        self.project = None          # unused; kept for checker symmetry

    def check_module(self, path: str, tree: ast.Module) -> list[Finding]:
        if not _DECODE_MODULE.search(path):
            return []
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if not _is_register_call(node):
                continue
            vname = _variant_name(node)
            kws = {k.arg: k.value for k in node.keywords}
            for side in _REQUIRED:
                other = _REQUIRED[1 - _REQUIRED.index(side)]
                val = kws.get(side)
                if val is None and side not in kws:
                    findings.append(Finding(
                        "surface-decode-variant-twin", path, node.lineno,
                        self._enclosing(tree, node),
                        f"missing:{vname}:{side}",
                        f"decode variant {vname!r} is registered without a "
                        f"{side}= twin — a variant only the {other} backend "
                        "can serve silently breaks fused variant parity "
                        "when query.fused_kernels selects the other mode; "
                        "declare BOTH backend twins (ops/decodereg.py "
                        "register_variant)"))
                elif isinstance(val, ast.Constant) and val.value is None:
                    findings.append(Finding(
                        "surface-decode-variant-twin", path, node.lineno,
                        self._enclosing(tree, node),
                        f"none:{vname}:{side}",
                        f"decode variant {vname!r} passes {side}=None — the "
                        "placeholder defeats the register-time guard until "
                        "the deferred import runs in production; wire a "
                        "real decode twin for both backends"))
        return findings

    def finalize(self) -> list[Finding]:
        return []

    @staticmethod
    def _enclosing(tree: ast.Module, target: ast.AST) -> str:
        best = "<module>"
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                for sub in ast.walk(node):
                    if sub is target:
                        best = node.name if best == "<module>" \
                            else f"{best}.{node.name}"
                        break
        return best
