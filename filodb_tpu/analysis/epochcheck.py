"""Epoch & visibility contracts: static cache-coherence for the write path.

Every serving-path cache (the PR 8 result cache's watermark-vector
equality, the PR 13 fragment cache's per-step validity, the mesh topk
release-epoch validation) is correct only because every mutation of
query-visible store state bumps ``data_epoch`` under the shard lock with
an honest min-affected timestamp. This family makes that discipline
structural. ``core/memstore.py`` declares the surface as ``EPOCH_SPEC``
(a pure-literal dict the checker reads from the AST): the field-sensitive
mutator shapes (``self.store.append`` / ``self.index.remove_part_keys`` /
a local alias of ``self.sink``), the sanctioned visibility sites with
their affected-timestamp class, and the admission-only shapes that need
declaration but no bump (a zero-sample series changes no query result).

Write-side rules (interprocedural — PackageIndex call graph + shared
per-function CFGs):

  * ``epoch-undeclared-visibility`` — a function that mutates a visible
    (or admission) shape and is neither a declared EPOCH_SPEC site nor
    reachable ONLY from declared sites (reverse-call closure): a
    visibility point the spec does not know about.
  * ``epoch-bump-uncovered`` — a visible-data mutation not fenced by the
    bump on every CFG path: every ENTRY→mutation path passes a bump, or
    every mutation→EXIT path does (either order is atomic under one lock
    hold). A conditional fence guarded by the mutation's own result
    (``dropped = sink.age_out(...)`` … ``if dropped: bump``) counts —
    a zero-row rewrite mutated nothing. An uncovered mutation in an
    UNdeclared helper propagates the obligation to its callers' call
    sites (the caller must fence the call).
  * ``epoch-bump-unlocked`` — a bump call neither inside ``with
    <recv>.lock:``, nor in a ``*_locked`` method (caller-holds contract),
    nor after ``assert_owned(self.lock …)``: the epoch/log pair would
    tear against ``epoch_state()`` readers.
  * ``epoch-bump-overclaim`` — a bump passing ``EPOCH_AFFECTS_ALL`` while
    a batch minimum is provably in scope (a ``*min*`` local or a
    ``.min()`` reduction assigned earlier in the function), or a declared
    ``batch_min_ts`` site whose every bump names only the destructive
    sentinel: over-claiming turns per-step fragment validity into
    full invalidation on every flush.

Read-side rules (the dual contract — per-function, CFG-ordered):

  * ``epoch-capture-after-execute`` — an epoch capture
    (``_epoch_state``/``_epoch_vector``/``epoch_state`` call, or a
    comprehension over ``data_epoch``/``_release_epoch``) on a CFG path
    AFTER an execution dispatch, or a cache probe (``.get/.probe/.hit``
    with an epoch argument) reachable from a dispatch: a capture taken
    after execution cannot fence the data the execution read — a
    concurrent mutation lands between the read and the capture and the
    validation passes vacuously. (Stores — ``.put``/``.store`` — after
    execution are the NORMAL pattern and stay legal: they must use the
    pre-execution capture, which the next rule enforces.)
  * ``epoch-validate-refetched`` — a cache get/probe/put/store/hit whose
    epoch argument refetches inline (a capture call or epoch attribute
    read inside the argument) instead of passing the pre-execution
    capture by name: validating against a refreshed vector accepts
    entries the mutation between capture and validation invalidated.

Fixture twins: bad/good_epoch_visibility.py (undeclared + uncovered),
bad/good_epoch_bump.py (unlocked + overclaim), bad/good_epoch_probe.py
(capture-after-execute + validate-refetched). Pure stdlib ``ast``.
"""

from __future__ import annotations

import ast
import re

from .callgraph import leaf_name
from .cfg import CFG, EXIT, covered_on_all_paths
from .findings import Finding

_SPEC_NAME = "EPOCH_SPEC"
_DEFAULT_BUMP = "_bump_epoch_locked"
_ALL_SENTINEL = "EPOCH_AFFECTS_ALL"

# read-side shapes are universal (no spec needed): how this codebase
# captures epoch state, dispatches execution, and talks to caches
_CAPTURE_CALLS = ("_epoch_state", "_epoch_vector", "epoch_state")
_CAPTURE_ATTRS = ("data_epoch", "_release_epoch")
_EXEC_RE = re.compile(
    r"^(_?exec\w*|evaluate\w*|resolve|topk|bottomk|aggregate|quantile"
    r"|to_plan|query_range|query_instant)$")
_PROBE_OPS = ("get", "probe", "hit")
_PUT_OPS = ("put", "store")
_CACHE_RECV = re.compile(r"cache", re.IGNORECASE)


def _own_nodes(fn: ast.AST):
    """Walk a function's body without descending into nested defs (nested
    functions are their own FuncUnits)."""
    todo = list(getattr(fn, "body", []))
    while todo:
        node = todo.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            todo.append(child)


def _extract_spec(tree: ast.Module) -> dict | None:
    """The module's ``EPOCH_SPEC`` literal, or None. literal_eval keeps the
    contract honest: a computed spec cannot be statically checked."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == _SPEC_NAME:
            try:
                spec = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                return None
            return spec if isinstance(spec, dict) else None
    return None


def _receiver_attr(recv: ast.expr, aliases: dict) -> str | None:
    """The state-attribute name a mutator receiver resolves to:
    ``self.store`` -> "store", a local alias (``sink = self.sink``) ->
    "sink", a bare matching Name -> itself."""
    if isinstance(recv, ast.Attribute):
        return recv.attr
    if isinstance(recv, ast.Name):
        return aliases.get(recv.id, recv.id)
    return None


def _call_leaf(node: ast.Call) -> str | None:
    return leaf_name(node.func)


def _contains_bump(node: ast.AST, bump: str) -> bool:
    return any(isinstance(n, ast.Call) and _call_leaf(n) == bump
               for n in ast.walk(node))


def _names_in(node: ast.AST):
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            yield n.id
        elif isinstance(n, ast.Attribute):
            yield n.attr


def _stmt_index_of(cfg: CFG, target: ast.AST) -> int | None:
    """The innermost CFG statement containing ``target`` (compound
    statements are CFG nodes too, so pick the smallest subtree)."""
    best, best_size = None, None
    for i, s in enumerate(cfg.stmts):
        for sub in ast.walk(s):
            if sub is target:
                size = sum(1 for _ in ast.walk(s))
                if best_size is None or size < best_size:
                    best, best_size = i, size
                break
    return best


def _reaches(cfg: CFG, frm: int, to: int) -> bool:
    seen = set()
    todo = list(cfg.succ.get(frm, ())) + list(cfg.exc_succ.get(frm, ()))
    while todo:
        n = todo.pop()
        if n in seen or n == EXIT:
            continue
        seen.add(n)
        if n == to:
            return True
        todo.extend(cfg.succ.get(n, ()))
        todo.extend(cfg.exc_succ.get(n, ()))
    return False


class EpochChecker:
    rules = ("epoch-undeclared-visibility", "epoch-bump-uncovered",
             "epoch-bump-unlocked", "epoch-bump-overclaim",
             "epoch-capture-after-execute", "epoch-validate-refetched")

    # the one module whose spec governs cross-file analysis in a full run;
    # a fixture twin's own spec governs only itself
    GLOBAL_SPEC_PATH = re.compile(r"(?:^|/)core/memstore\.py$")

    def __init__(self):
        self.project = None
        self.corpus = None
        self._modules: dict[str, ast.Module] = {}
        self._specs: dict[str, dict] = {}

    def check_module(self, path: str, tree: ast.Module) -> list[Finding]:
        self._modules[path] = tree
        spec = _extract_spec(tree)
        if spec is not None:
            self._specs[path] = spec
        return []

    # -- spec resolution ------------------------------------------------------

    def _global_spec(self) -> tuple[str, dict] | None:
        for path, spec in self._specs.items():
            if self.GLOBAL_SPEC_PATH.search(path):
                return path, spec
        if len(self._specs) == 1:
            return next(iter(self._specs.items()))
        return None

    def _spec_for(self, path: str) -> tuple[str, dict] | None:
        if path in self._specs:
            return path, self._specs[path]
        return self._global_spec()

    def _cfg(self, fn: ast.AST) -> CFG:
        if self.corpus is not None:
            return self.corpus.cfg(fn)
        from .cfg import build_cfg
        return build_cfg(fn)

    # -- finalize -------------------------------------------------------------

    def finalize(self) -> list[Finding]:
        findings: list[Finding] = []
        if self.project is None:
            return findings
        findings += self._write_side()
        findings += self._read_side()
        return findings

    # -- write side -----------------------------------------------------------

    def _write_side(self) -> list[Finding]:
        findings: list[Finding] = []
        # pass 1: per-function facts under that module's governing spec
        facts: dict[str, dict] = {}       # FuncUnit key -> fact record
        sanctioned_by_spec: dict[str, set] = {}   # spec path -> site keys
        for key, u in self.project.funcs.items():
            got = self._spec_for(u.path)
            if got is None:
                continue
            spec_path, spec = got
            sites = sanctioned_by_spec.get(spec_path)
            if sites is None:
                sites = {f"{spec_path}::{s['fn']}"
                         for s in (spec.get("sites") or {}).values()}
                sanctioned_by_spec[spec_path] = sites
            rec = self._collect_fn(u, spec)
            if rec is not None:
                rec["spec_path"], rec["spec"] = spec_path, spec
                facts[key] = rec

        # pass 2: coverage of direct visible mutations; obligation
        # propagation from uncovered UNdeclared helpers to their callers
        uncovered_helpers: set = set()
        for key, rec in facts.items():
            u = self.project.funcs[key]
            spec, spec_path = rec["spec"], rec["spec_path"]
            declared = key in sanctioned_by_spec[spec_path]
            affects = self._affects_of(key, spec, spec_path)
            uncovered = [m for m in rec["visible"]
                         if not self._covered(u, m, rec["bump_name"])]
            if uncovered and affects == "admit":
                uncovered = []       # admission sites carry no data bump
            if uncovered and declared:
                for m in uncovered:
                    findings.append(Finding(
                        "epoch-bump-uncovered", u.path, m["line"],
                        u.qualname, m["detail"],
                        f"visible-state mutation {m['detail']} is not "
                        "fenced by a data-epoch bump on every CFG path — "
                        "a query caching between the mutation and the "
                        "bump validates against a stale vector forever; "
                        "bump before or after the mutation under the same "
                        "lock hold (core/memstore.py EPOCH_SPEC)"))
            elif uncovered:
                uncovered_helpers.add(key)
            if rec["visible"] or rec["admit"]:
                sanctioned = declared or self.project.reachable_only_from(
                    key, sanctioned_by_spec[spec_path])
                if not sanctioned:
                    m = (rec["visible"] or rec["admit"])[0]
                    findings.append(Finding(
                        "epoch-undeclared-visibility", u.path, m["line"],
                        u.qualname, m["detail"],
                        f"{u.qualname} mutates query-visible store state "
                        f"({m['detail']}) but is not a declared EPOCH_SPEC "
                        "site and is reachable outside every declared "
                        "site — an epoch-invisible visibility point; "
                        "declare it in core/memstore.py EPOCH_SPEC with "
                        "its affected-ts class, or route it through a "
                        "declared site"))
            findings += self._bump_rules(u, rec, declared, affects)

        # pass 3: callers of uncovered undeclared helpers must fence the
        # call like a mutation of their own (bounded propagation)
        findings += self._propagate(facts, uncovered_helpers,
                                    sanctioned_by_spec)
        return findings

    def _affects_of(self, key: str, spec: dict, spec_path: str) -> str | None:
        qual = key.split("::", 1)[1]
        for s in (spec.get("sites") or {}).values():
            if s["fn"] == qual:
                return s.get("affects")
        return None

    def _collect_fn(self, u, spec: dict) -> dict | None:
        """One lexical pass over a function: local aliases of spec state
        attrs, visible/admission mutation events, bump calls."""
        visible_calls = spec.get("visible_calls") or {}
        admit_calls = spec.get("admit_calls") or {}
        admit_maps = tuple(spec.get("admit_maps") or ())
        bump_name = spec.get("bump") or _DEFAULT_BUMP
        state_attrs = set(visible_calls) | set(admit_calls)
        aliases: dict[str, str] = {}
        for node in _own_nodes(u.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Attribute) \
                    and node.value.attr in state_attrs:
                aliases[node.targets[0].id] = node.value.attr
        visible, admit, bumps = [], [], []
        for node in _own_nodes(u.node):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                attr = _receiver_attr(node.func.value, aliases)
                meth = node.func.attr
                ev = {"node": node, "line": node.lineno,
                      "detail": f"{attr}.{meth}"}
                if meth == bump_name:
                    bumps.append(ev)
                elif attr in visible_calls and meth in visible_calls[attr]:
                    visible.append(ev)
                elif attr in admit_calls and meth in admit_calls[attr]:
                    admit.append(ev)
                elif meth in ("pop", "update", "clear", "setdefault",
                              "popitem") \
                        and isinstance(node.func.value, ast.Attribute) \
                        and node.func.value.attr in admit_maps:
                    ev["detail"] = f"{node.func.value.attr}.{meth}"
                    admit.append(ev)
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else ([node.target] if hasattr(node, "target")
                          else node.targets)
                for t in targets:
                    base = t.value if isinstance(t, ast.Subscript) else t
                    if isinstance(base, ast.Attribute) \
                            and base.attr in admit_maps:
                        admit.append({"node": node, "line": node.lineno,
                                      "detail": f"{base.attr}[]"})
        if not (visible or admit or bumps):
            return None
        return {"visible": visible, "admit": admit, "bumps": bumps,
                "bump_name": bump_name, "aliases": aliases}

    def _covered(self, u, m: dict, bump_name: str) -> bool:
        """Is mutation ``m`` bump-fenced in ``u`` on every path? A
        result-guarded fence (``x = mutate(); if x: bump``) counts: the
        skipped branch is the nothing-mutated case."""
        cfg = self._cfg(u.node)
        idx = _stmt_index_of(cfg, m["node"])
        if idx is None:
            return False
        stmt = cfg.stmts[idx]
        if _contains_bump(stmt, bump_name):
            return True               # mutation and bump share a statement
        result_name = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            result_name = stmt.targets[0].id

        def fence(s: ast.stmt) -> bool:
            if _contains_bump(s, bump_name) and not isinstance(
                    s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not isinstance(s, (ast.If, ast.For, ast.While,
                                      ast.With, ast.Try)):
                    return True
                # a compound node only fences when EVERY continuation out
                # of it bumped — accept the one guarded idiom we can prove:
                # ``if <result>: ...bump...`` with no else
                if isinstance(s, ast.If) and result_name is not None \
                        and not s.orelse \
                        and result_name in set(_names_in(s.test)) \
                        and _contains_bump(s, bump_name):
                    return True
            return False

        return covered_on_all_paths(cfg, idx, fence)

    def _bump_rules(self, u, rec: dict, declared: bool,
                    affects: str | None) -> list[Finding]:
        """Lock discipline + over-claim at each bump call site."""
        findings: list[Finding] = []
        if not rec["bumps"]:
            if declared and affects == "batch_min_ts" and rec["visible"]:
                # a batch_min site with no bump of its own is only legal
                # when its mutations route through covered callees — the
                # coverage rule already judged that; nothing extra here
                pass
            return findings
        lock_name = rec["spec"].get("lock") or "lock"
        fn_locked = u.name.endswith("_locked")
        with_lock_spans: list[tuple[int, int]] = []
        assert_lines: list[int] = []
        for node in _own_nodes(u.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if any(isinstance(n, ast.Attribute)
                           and n.attr == lock_name
                           for n in ast.walk(item.context_expr)):
                        end = max((s.lineno for s in ast.walk(node)
                                   if hasattr(s, "lineno")),
                                  default=node.lineno)
                        with_lock_spans.append((node.lineno, end))
            elif isinstance(node, ast.Call) \
                    and _call_leaf(node) == "assert_owned" \
                    and any(a == lock_name for a in _names_in(node)):
                assert_lines.append(node.lineno)
        saw_min_source = any(
            isinstance(n, ast.Assign) and (
                any("min" in name for t in n.targets
                    for name in _names_in(t))
                or any(isinstance(c, ast.Call)
                       and _call_leaf(c) in ("min",)
                       or (isinstance(c, ast.Call)
                           and isinstance(c.func, ast.Attribute)
                           and c.func.attr == "min")
                       for c in ast.walk(n.value)))
            for n in _own_nodes(u.node))
        all_only = True
        for b in rec["bumps"]:
            line = b["line"]
            held = fn_locked \
                or any(lo <= line <= hi for lo, hi in with_lock_spans) \
                or any(al <= line for al in assert_lines)
            if not held:
                findings.append(Finding(
                    "epoch-bump-unlocked", u.path, line, u.qualname,
                    "bump", f"{rec['bump_name']} called without the shard "
                    f"lock (no enclosing `with …{lock_name}:`, no "
                    "`*_locked` caller-holds contract, no assert_owned) — "
                    "the epoch/log pair tears against epoch_state() "
                    "readers"))
            args = b["node"].args
            names = set()
            for a in args:
                names.update(_names_in(a))
            mentions_all = _ALL_SENTINEL in names
            mentions_min = any("min" in n for n in names)
            if not (mentions_all and not mentions_min):
                all_only = False
            if mentions_all and not mentions_min and saw_min_source:
                findings.append(Finding(
                    "epoch-bump-overclaim", u.path, line, u.qualname,
                    "overclaim", "bump records EPOCH_AFFECTS_ALL while a "
                    "batch minimum is in scope in this function — the "
                    "destructive sentinel turns per-step fragment "
                    "validity into full invalidation; pass the batch "
                    "min-ts instead"))
        if declared and affects == "batch_min_ts" and all_only \
                and rec["bumps"]:
            b = rec["bumps"][0]
            findings.append(Finding(
                "epoch-bump-overclaim", u.path, b["line"], u.qualname,
                "site-class", "declared batch_min_ts site bumps only "
                "EPOCH_AFFECTS_ALL — the site's class promises a batch "
                "minimum (core/memstore.py EPOCH_SPEC); record it or "
                "re-class the site"))
        return findings

    def _propagate(self, facts: dict, uncovered: set,
                   sanctioned_by_spec: dict) -> list[Finding]:
        """An uncovered mutation in an undeclared helper becomes a fencing
        obligation at every caller's call site, transitively."""
        findings: list[Finding] = []
        callers = self.project.callers_of()
        seen: set = set(uncovered)
        todo = list(uncovered)
        while todo:
            helper = todo.pop()
            hu = self.project.funcs[helper]
            for caller in callers.get(helper, ()):  # may be empty: rule 1
                cu = self.project.funcs.get(caller)
                if cu is None:
                    continue
                # the caller may carry no mutation facts of its own —
                # resolve its governing spec directly, not via `facts`
                got = self._spec_for(cu.path)
                bump = (got[1].get("bump") if got else None) \
                    or _DEFAULT_BUMP
                call_nodes = [
                    n for n in _own_nodes(cu.node)
                    if isinstance(n, ast.Call)
                    and leaf_name(n.func) == hu.name]
                declared = False
                if got is not None:
                    sites = sanctioned_by_spec.get(got[0])
                    if sites is None:
                        sites = {f"{got[0]}::{s['fn']}"
                                 for s in (got[1].get("sites")
                                           or {}).values()}
                        sanctioned_by_spec[got[0]] = sites
                    declared = caller in sites
                for cn in call_nodes:
                    m = {"node": cn, "line": cn.lineno,
                         "detail": f"call:{hu.qualname}"}
                    if self._covered(cu, m, bump):
                        continue
                    if declared:
                        findings.append(Finding(
                            "epoch-bump-uncovered", cu.path, cn.lineno,
                            cu.qualname, m["detail"],
                            f"call to {hu.qualname} (which mutates "
                            "visible state without its own bump) is not "
                            "bump-fenced here on every CFG path"))
                    elif caller not in seen:
                        seen.add(caller)
                        todo.append(caller)
        return findings

    # -- read side ------------------------------------------------------------

    def _read_side(self) -> list[Finding]:
        findings: list[Finding] = []
        for key, u in self.project.funcs.items():
            captures, execs, cache_ops = [], [], []
            capture_names: set[str] = set()
            for node in _own_nodes(u.node):
                if isinstance(node, ast.Assign) \
                        and self._is_capture_expr(node.value):
                    captures.append(node)
                    for t in node.targets:
                        els = t.elts if isinstance(t, ast.Tuple) else [t]
                        capture_names.update(
                            e.id for e in els if isinstance(e, ast.Name))
                elif isinstance(node, ast.Call):
                    leaf = _call_leaf(node)
                    if leaf and _EXEC_RE.match(leaf):
                        execs.append(node)
                    elif isinstance(node.func, ast.Attribute) \
                            and node.func.attr in _PROBE_OPS + _PUT_OPS:
                        recv = leaf_name(node.func.value)
                        if recv and _CACHE_RECV.search(recv):
                            cache_ops.append(node)
            for op in cache_ops:
                for a in list(op.args) + [k.value for k in op.keywords]:
                    if self._is_capture_expr(a) \
                            and not isinstance(a, ast.Name):
                        findings.append(Finding(
                            "epoch-validate-refetched", u.path, op.lineno,
                            u.qualname, f"{op.func.attr}",
                            "cache validation refetches the epoch vector "
                            "inline instead of passing the pre-execution "
                            "capture — a mutation between capture and "
                            "validation is accepted as current; capture "
                            "once BEFORE execution and pass that name"))
                        break
            if not execs or not (captures or cache_ops):
                continue
            cfg = self._cfg(u.node)
            exec_idx = {i for e in execs
                        if (i := _stmt_index_of(cfg, e)) is not None}
            for cap in captures:
                ci = _stmt_index_of(cfg, cap)
                if ci is None:
                    continue
                if any(ei != ci and _reaches(cfg, ei, ci)
                       for ei in exec_idx):
                    findings.append(Finding(
                        "epoch-capture-after-execute", u.path, cap.lineno,
                        u.qualname, "capture",
                        "epoch state captured on a path AFTER an "
                        "execution dispatch — a mutation landing between "
                        "the data read and this capture makes every later "
                        "validation pass vacuously; capture before "
                        "dispatch"))
            for op in cache_ops:
                if op.func.attr not in _PROBE_OPS:
                    continue
                has_epoch_arg = any(
                    isinstance(a, ast.Name) and a.id in capture_names
                    for a in list(op.args)
                    + [k.value for k in op.keywords])
                if not has_epoch_arg:
                    continue
                oi = _stmt_index_of(cfg, op)
                if oi is None:
                    continue
                if any(ei != oi and _reaches(cfg, ei, oi)
                       for ei in exec_idx):
                    findings.append(Finding(
                        "epoch-capture-after-execute", u.path, op.lineno,
                        u.qualname, f"probe:{op.func.attr}",
                        "cache probed with a captured epoch vector on a "
                        "path AFTER an execution dispatch — probe before "
                        "executing (the probe exists to skip the work)"))
        return findings

    @staticmethod
    def _is_capture_expr(expr: ast.AST) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call) and _call_leaf(n) in _CAPTURE_CALLS:
                return True
            if isinstance(n, ast.Attribute) and n.attr in _CAPTURE_ATTRS \
                    and isinstance(n.ctx, ast.Load):
                return True
        return False
