"""Lock-discipline checker.

Invariants (see core/memstore.py:140-144 — the shard lock guards the donating
device append against concurrent query capture/dispatch):

  * ``lock-unheld-call`` — a call to a ``*_locked`` method must come from a
    holder: a function that is itself ``*_locked``, or a call site lexically
    inside ``with <owner lock>:`` (the owning object's ``lock`` / ``_lock`` /
    ``owner_lock``). Group-flush and sink locks do NOT qualify — they guard
    different resources.
  * ``lock-unheld-write`` — state written by ``*_locked`` methods (including
    container mutators: append/pop/update/...) is shard state; writing it
    from a non-holder races the lock-holding mutators. ``__init__`` is exempt
    (no concurrency before construction completes).
  * ``lock-guard-inconsistent`` — a class that guards writes to an attribute
    under ``with self.<some lock>:`` in one method but READ-MODIFY-WRITES the
    same attribute unguarded in another (classic lost-update shape for
    metrics counters updated from dispatch threads). Plain rebinding
    assignments are GIL-atomic and exempt — only += / subscript stores /
    container mutators count.
  * ``lock-order-cycle`` / ``lock-order`` — nested ``with`` acquisitions
    (lexical, plus same-class ``self.method()`` propagation) build a directed
    graph over the lock CLASSES (group_flush, sink, shard). A cycle is a
    potential deadlock; an edge contradicting the declared global order
    (utils/diagnostics.LOCK_ORDER — also asserted at runtime under
    FILODB_LOCK_DEBUG=1) is an ordering violation.

Holder forms recognized: the ``_locked`` suffix, a lexical ``with <owner
lock>:``, ``stack.enter_context(<owner lock>)`` (multi-shard ExitStack
acquisition — treated as held for the rest of the function), a
``diagnostics.assert_owned(self.lock, ...)`` call in the body (the contract
is then runtime-checked instead), and — new in v2 — the INHERITED holder: a
private helper (leading underscore) every one of whose in-class call sites
holds the owner lock inherits the fact, transitively through other inherited
helpers (computed as a shrinking fixpoint).  That closes PR 3's documented
lexical blind spot: ``def _bump(self)`` called only from inside ``with
self.lock:`` no longer needs a rename or a suppression.  A helper with even
ONE non-holder call site — or with no in-class call site at all (it may be
called externally) — still must carry the suffix or the runtime assert.
Remaining pure-AST limits (ANALYSIS.md): bare .acquire()/.release() pairs,
and private helpers invoked from OUTSIDE their class, are not recognized.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .findings import Finding

# attribute names that, used as a `with` context manager, count as holding the
# OWNING OBJECT's lock (qualifies as holder for *_locked calls / writes)
OWNER_LOCK_ATTRS = {"lock", "_lock", "owner_lock"}

# lock CLASS names for the order graph; must match utils/diagnostics.LOCK_ORDER
LOCK_CLASS_OF_ATTR = {
    "lock": "shard", "owner_lock": "shard",
    "_sink_lock": "sink",
}
GROUP_FLUSH_ATTR = "_group_flush_locks"
# declared global acquisition order (rank increases left to right); kept in
# sync with filodb_tpu/utils/diagnostics.py LOCK_ORDER (the runtime assert) —
# tests/test_static_analysis.py cross-checks the two.
LOCK_ORDER = ("group_flush", "sink", "shard")

MUTATOR_METHODS = {"append", "extend", "insert", "pop", "popitem", "remove",
                   "discard", "clear", "update", "add", "setdefault",
                   "appendleft", "popleft"}


def lock_class_of(expr: ast.expr) -> str | None:
    """Classify a `with` context expression as one of the ordered lock
    classes, "object" (an unranked per-object `_lock`), or None (not a
    recognized lock)."""
    if isinstance(expr, ast.Subscript):
        base = expr.value
        if isinstance(base, ast.Attribute) and base.attr == GROUP_FLUSH_ATTR:
            return "group_flush"
        return None
    if isinstance(expr, ast.Attribute):
        cls = LOCK_CLASS_OF_ATTR.get(expr.attr)
        if cls:
            return cls
        if expr.attr == "_lock":
            return "object"
        return None
    if isinstance(expr, ast.Name):
        # bare `with lock:` in module-level helpers / fixtures
        if expr.id in ("lock", "owner_lock"):
            return "shard"
        if expr.id == "_lock":
            return "object"
    return None


def _is_owner_lock(expr: ast.expr) -> bool:
    """Does this `with` context hold the owning object's lock (holder-
    qualifying for *_locked calls and locked-state writes)?"""
    if isinstance(expr, ast.Attribute):
        return expr.attr in OWNER_LOCK_ATTRS
    if isinstance(expr, ast.Name):
        return expr.id in OWNER_LOCK_ATTRS
    return False


def _self_attr_root(target: ast.expr) -> str | None:
    """The first attribute name of a `self.X...` store target ("X"), walking
    through nested attributes/subscripts (self.a.b, self.a[i]) — writes are
    tracked at the granularity of the object hanging off self."""
    node = target
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        parent = node.value
        if isinstance(node, ast.Attribute) and isinstance(parent, ast.Name) \
                and parent.id == "self":
            return node.attr
        node = parent
    return None


@dataclass
class _FuncInfo:
    name: str
    qualname: str
    node: ast.AST
    is_locked: bool                      # name ends with _locked
    direct_locks: set = field(default_factory=set)   # lock classes acquired
    calls: set = field(default_factory=set)          # self.X() callee names


class _FunctionScanner(ast.NodeVisitor):
    """Single pass over one function: tracks the lexical stack of held locks,
    records *_locked calls, self-attr writes, acquisitions, and (for the
    order graph) which self-methods are called under which held lock."""

    def __init__(self, info: _FuncInfo):
        self.info = info
        self.held: list[tuple[str | None, bool]] = []  # (lock_class, owner?)
        self.locked_calls: list[tuple[ast.Call, str, bool]] = []
        # (node, attr, holder?, guard_class, rmw?)
        self.writes: list[tuple[ast.AST, str, bool, str | None, bool]] = []
        self.nested_edges: list[tuple[str, str, int]] = []
        self.calls_under: list[tuple[str, str, int]] = []  # (lockcls, callee, line)
        # every self.X() site with the lexical holder state at the site —
        # feeds the v2 inherited-holder fixpoint
        self.self_call_sites: list[tuple[str, bool]] = []
        # set by enter_context(<owner lock>) / assert_owned(...): the rest of
        # the function counts as holding the owner lock
        self.asserted_owner = False

    def _holding_owner(self) -> bool:
        return (self.info.is_locked or self.asserted_owner
                or any(o for _, o in self.held))

    def _held_classes(self) -> list[str]:
        return [c for c, _ in self.held if c and c != "object"]

    def visit_With(self, node: ast.With):  # noqa: N802
        entered = 0
        for item in node.items:
            cls = lock_class_of(item.context_expr)
            owner = _is_owner_lock(item.context_expr)
            if cls is None and not owner:
                continue
            if cls is not None:
                for h in self._held_classes():
                    if h != cls:
                        self.nested_edges.append((h, cls, node.lineno))
                if cls != "object":
                    self.info.direct_locks.add(cls)
            self.held.append((cls, owner))
            entered += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(entered):
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call):  # noqa: N802
        func = node.func
        callee = None
        if isinstance(func, ast.Attribute):
            callee = func.attr
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                self.info.calls.add(callee)
                self.self_call_sites.append((callee, self._holding_owner()))
                for h in self._held_classes():
                    self.calls_under.append((h, callee, node.lineno))
        elif isinstance(func, ast.Name):
            callee = func.id
        # ExitStack multi-lock acquisition / runtime ownership assert: both
        # make the rest of this function a holder
        if callee == "enter_context" and node.args \
                and _is_owner_lock(node.args[0]):
            self.asserted_owner = True
        if callee == "assert_owned" and node.args \
                and _is_owner_lock(node.args[0]):
            self.asserted_owner = True
        if callee and callee.endswith("_locked"):
            self.locked_calls.append((node, callee, self._holding_owner()))
        self.generic_visit(node)

    def _record_write(self, target: ast.expr, line_node: ast.AST,
                      rmw: bool = False):
        attr = _self_attr_root(target)
        if attr is not None:
            # a subscript / nested-attribute store mutates shared structure
            # in place (read-modify-write); rebinding self.X is GIL-atomic
            rmw = rmw or not (isinstance(target, ast.Attribute)
                              and isinstance(target.value, ast.Name))
            self.writes.append((line_node, attr, self._holding_owner(),
                                self._guard_class(), rmw))

    def _guard_class(self) -> str | None:
        """The innermost recognized lock class currently held (any class —
        used by the guard-consistency rule, which is per-attribute, not
        owner-specific)."""
        for cls, owner in reversed(self.held):
            if cls is not None or owner:
                return cls or "shard"
        return None

    def visit_Assign(self, node: ast.Assign):  # noqa: N802
        for t in node.targets:
            self._record_write(t, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):  # noqa: N802
        self._record_write(node.target, node, rmw=True)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):  # noqa: N802
        if node.value is not None:
            self._record_write(node.target, node)
        self.generic_visit(node)

    # container mutators count as writes to the container attribute
    def _maybe_mutator(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in MUTATOR_METHODS:
            attr = _self_attr_root(func.value)
            if attr is not None:
                self.writes.append((node, attr, self._holding_owner(),
                                    self._guard_class(), True))

    # nested defs: conservatively descend (closures run on the same thread
    # unless handed to an executor; lexical lock state is the best signal)
    def visit_FunctionDef(self, node):  # noqa: N802
        self.generic_visit(node)

    def generic_visit(self, node):
        if isinstance(node, ast.Call):
            self._maybe_mutator(node)
        super().generic_visit(node)


class LockChecker:
    """Per-module pass + cross-module finalize (order graph over the repo)."""

    rules = ("lock-unheld-call", "lock-unheld-write", "lock-guard-inconsistent",
             "lock-order", "lock-order-cycle")

    def __init__(self):
        self._edges: list[tuple[str, str, str, int]] = []  # a, b, path, line

    def check_module(self, path: str, tree: ast.Module) -> list[Finding]:
        findings: list[Finding] = []
        for cls in [n for n in tree.body if isinstance(n, ast.ClassDef)]:
            findings += self._check_class(path, cls)
        # module-level functions: *_locked calls / order edges only
        for fn in [n for n in tree.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
            info = _FuncInfo(fn.name, fn.name, fn,
                             fn.name.endswith("_locked"))
            sc = _FunctionScanner(info)
            for stmt in fn.body:
                sc.visit(stmt)
            findings += self._call_findings(path, fn.name, sc)
            for a, b, line in sc.nested_edges:
                self._edges.append((a, b, path, line))
        return findings

    def _check_class(self, path: str, cls: ast.ClassDef) -> list[Finding]:
        findings: list[Finding] = []
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        infos: dict[str, _FuncInfo] = {}
        scanners: dict[str, _FunctionScanner] = {}
        for name, fn in methods.items():
            info = _FuncInfo(name, f"{cls.name}.{name}", fn,
                             name.endswith("_locked"))
            sc = _FunctionScanner(info)
            for stmt in fn.body:
                sc.visit(stmt)
            infos[name] = info
            scanners[name] = sc

        # interprocedural holder inheritance (v2): a PRIVATE helper whose
        # every in-class call site holds the owner lock inherits the holder
        # fact, transitively. Shrinking fixpoint: start optimistic, demote a
        # candidate when any site's caller neither holds lexically, is
        # *_locked, nor (still) inherits.
        sites: dict[str, list[tuple[str, bool]]] = {}
        for caller, sc in scanners.items():
            for callee, held in sc.self_call_sites:
                if callee in methods:
                    sites.setdefault(callee, []).append((caller, held))
        # a method whose REFERENCE escapes (Thread(target=self._m), a stored
        # callback) can run from anywhere — the call-site census is
        # incomplete for it, so it must never inherit the holder fact
        escaped_refs: set[str] = set()
        for fn in methods.values():
            call_funcs = {id(n.func) for n in ast.walk(fn)
                          if isinstance(n, ast.Call)}
            for n in ast.walk(fn):
                if isinstance(n, ast.Attribute) \
                        and isinstance(n.value, ast.Name) \
                        and n.value.id == "self" \
                        and isinstance(n.ctx, ast.Load) \
                        and id(n) not in call_funcs and n.attr in methods:
                    escaped_refs.add(n.attr)
        inherited = {m: True for m in methods
                     if m.startswith("_") and not m.startswith("__")
                     and m not in escaped_refs and sites.get(m)}
        changed = True
        while changed:
            changed = False
            for m in inherited:
                if not inherited[m]:
                    continue
                for caller, held in sites[m]:
                    if not (held or infos[caller].is_locked
                            or inherited.get(caller, False)):
                        inherited[m] = False
                        changed = True
                        break
        holder_inherited = {m for m, ok in inherited.items() if ok}

        # protected state: attrs written by *_locked methods
        protected: set[str] = set()
        for name, sc in scanners.items():
            if infos[name].is_locked:
                protected.update(attr for _, attr, _, _, _ in sc.writes)

        # per-attribute guard census for lock-guard-inconsistent
        guarded_attrs: dict[str, set[str]] = {}
        for name, sc in scanners.items():
            if name == "__init__":
                continue
            for _, attr, _, guard, _ in sc.writes:
                if guard is not None:
                    guarded_attrs.setdefault(attr, set()).add(
                        infos[name].qualname)

        for name, sc in scanners.items():
            qual = infos[name].qualname
            is_inherited = name in holder_inherited
            findings += self._call_findings(path, qual, sc,
                                            exempt=is_inherited)
            if name == "__init__":
                continue
            for node, attr, holder, guard, rmw in sc.writes:
                if attr in protected and not holder \
                        and not infos[name].is_locked and not is_inherited:
                    findings.append(Finding(
                        "lock-unheld-write", path, node.lineno, qual,
                        f"write:{attr}",
                        f"writes self.{attr} (state mutated by *_locked "
                        f"methods of {cls.name}) without holding the owner "
                        "lock — wrap in `with self.lock:` or rename the "
                        "method *_locked"))
                elif attr not in protected and guard is None and rmw \
                        and attr in guarded_attrs \
                        and qual not in guarded_attrs[attr]:
                    findings.append(Finding(
                        "lock-guard-inconsistent", path, node.lineno, qual,
                        f"guard:{attr}",
                        f"read-modify-writes self.{attr} unguarded, but "
                        f"{sorted(guarded_attrs[attr])[0]} guards the same "
                        "attribute under a lock — concurrent updates lose "
                        "increments; take the lock on both paths"))

        # order edges: lexical + one-hop self-call propagation with
        # transitive closure of each method's acquisitions
        trans: dict[str, set[str]] = {n: set(i.direct_locks)
                                      for n, i in infos.items()}
        changed = True
        while changed:
            changed = False
            for name, info in infos.items():
                for callee in info.calls:
                    if callee in trans and not trans[callee] <= trans[name]:
                        trans[name] |= trans[callee]
                        changed = True
        for name, sc in scanners.items():
            for a, b, line in sc.nested_edges:
                self._edges.append((a, b, path, line))
            for lockcls, callee, line in sc.calls_under:
                for acquired in trans.get(callee, ()):
                    if acquired != lockcls:
                        self._edges.append((lockcls, acquired, path, line))
        return findings

    def _call_findings(self, path: str, qual: str, sc: _FunctionScanner,
                       exempt: bool = False) -> list[Finding]:
        if exempt:      # inherited holder: every in-class call site holds
            return []
        out = []
        for node, callee, holder in sc.locked_calls:
            if not holder:
                out.append(Finding(
                    "lock-unheld-call", path, node.lineno, qual,
                    f"call:{callee}",
                    f"calls {callee}() without holding the owner lock — "
                    "*_locked methods must run under `with <owner>.lock:` "
                    "(or from another *_locked method, or — v2 — be a "
                    "private helper whose every in-class call site holds)"))
        return out

    def finalize(self) -> list[Finding]:
        findings: list[Finding] = []
        rank = {c: i for i, c in enumerate(LOCK_ORDER)}
        graph: dict[str, set[str]] = {}
        where: dict[tuple[str, str], tuple[str, int]] = {}
        for a, b, path, line in self._edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
            where.setdefault((a, b), (path, line))
        # declared-order violations
        for (a, b), (path, line) in sorted(where.items()):
            if a in rank and b in rank and rank[a] >= rank[b]:
                findings.append(Finding(
                    "lock-order", path, line, "<lock-graph>", f"{a}->{b}",
                    f"acquires {b!r} lock while holding {a!r} — violates the "
                    f"declared order {LOCK_ORDER} (diagnostics.LOCK_ORDER); "
                    "a concurrent thread taking them in order can deadlock"))
        # cycles (covers classes outside the declared order too)
        for cyc in _cycles(graph):
            a, b = cyc[0], cyc[1 % len(cyc)]
            path, line = where.get((a, b), ("<unknown>", 0))
            findings.append(Finding(
                "lock-order-cycle", path, line, "<lock-graph>",
                "->".join(cyc),
                f"lock acquisition cycle {' -> '.join(cyc + (cyc[0],))}: "
                "two threads entering at different points deadlock"))
        return findings


def _cycles(graph: dict[str, set[str]]) -> list[tuple[str, ...]]:
    """Elementary cycles via DFS (the graph has a handful of nodes)."""
    out: list[tuple[str, ...]] = []
    seen_cycles: set[frozenset] = set()

    def dfs(node: str, path: list[str], on_path: set[str]):
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_path:
                cyc = tuple(path[path.index(nxt):])
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    out.append(cyc)
                continue
            path.append(nxt)
            on_path.add(nxt)
            dfs(nxt, path, on_path)
            on_path.discard(nxt)
            path.pop()

    for start in sorted(graph):
        dfs(start, [start], {start})
    return out
