"""Resource-lifecycle checker (threads, servers, sockets, file handles).

PR 4 made the ingest plane thread- and socket-heavy; these rules machine-
check the lifecycle conventions a production database survives on — a
silently-dead decode thread or a leaked gateway socket is an ingest
outage, not a test failure:

  * ``resource-thread-no-stop`` — every started ``threading.Thread`` needs
    a shutdown story: ``daemon=True`` at construction (incl. a Thread
    subclass whose ``__init__`` passes it), or a ``join()`` reachable from
    the owning class (directly, via an iterated collection the thread was
    appended to, or through a helper method — the interprocedural class
    closure).  An anonymous non-daemon ``Thread(...).start()`` can never
    be joined and is always flagged.
  * ``resource-server-no-stop`` — a ``serve_forever`` thread target
    additionally needs a paired ``<server>.shutdown()`` in the owning
    class, and the thread must be STORED and joined (a deterministic
    ``stop()``); an anonymous serve_forever thread is flagged even when
    daemon (daemon teardown never releases the listening socket
    deterministically).
  * ``resource-worker-silent-death`` — a thread-entry function (Thread
    target / Thread-subclass ``run``, from the shared call-graph facts)
    whose loop can die on an exception with no observable trace: the loop
    must be inside — or contain — a ``try`` with a broad handler that does
    something observable (logs, counts, stores the error for the
    consumer).  A worker that exits silently turns into a stalled shard
    hours later with nothing in the logs.
  * ``resource-no-release`` — a locally-acquired file handle or socket
    (``open(...)``, ``socket.socket(...)``, ``socket.create_connection``)
    must be released on ALL CFG paths (``with`` / ``try: ... finally:
    close()``), unless it is returned or stored on ``self`` (then the
    class-level rules own it).  Path analysis comes from analysis/cfg.py,
    including the exceptional edges.  The same rule covers TRANSITIVE
    socket ownership (the replicated-ingest tier's shape): an in-package
    class that stores a raw socket on ``self`` is a *socket owner*
    (BrokerBus, FollowerLink), a class storing an instance of an owner is
    transitively one (Replicator holds FollowerLinks, FiloServer holds
    BrokerBuses), and every class that INSTANTIATES an owner into a self
    attribute must have a ``close()``/``stop()`` for that attribute
    reachable in the class — a replication link pool with no teardown is
    a socket leak per failover, invisible until the fd limit.

The class-level rules use the shared PackageIndex (analysis/callgraph.py)
so a release that lives in a helper (``stop()`` -> ``_teardown()``) still
counts. Pure stdlib ``ast``.
"""

from __future__ import annotations

import ast

from .callgraph import (PackageIndex, attr_root, dotted_name,
                        handler_is_observable, is_broad_handler)
from .cfg import build_cfg, releases_on_all_paths
from .findings import Finding

THREAD_CTORS = {"Thread", "threading.Thread"}
SOCKET_CTORS = {"socket.socket", "socket.create_connection",
                "create_connection"}


def _attr_root(expr: ast.expr) -> str | None:
    """self.a.b / self.a[...] -> "a" (also the socketserver ``outer``
    closure idiom)."""
    return attr_root(expr, receivers=("self", "outer"))


def _kw(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_true(expr: ast.expr | None) -> bool:
    return isinstance(expr, ast.Constant) and expr.value is True


_observable_handler = handler_is_observable   # shared definition (callgraph)


class _ClassResources:
    """Per-class acquisition/release census with interprocedural closure."""

    def __init__(self, path: str, cls: ast.ClassDef, index: PackageIndex):
        self.path = path
        self.cls = cls
        self.index = index
        # attr root -> (line, kind, extra) for acquisitions stored on self
        self.threads: list[tuple] = []    # (attr|None, line, call, qual)
        self.serves: list[tuple] = []     # (attr|None, line, server_root, qual)
        self.sockets: list[tuple] = []    # (attr, line, qual)
        # in-package class instantiations stored on self: candidates for
        # the transitive socket-owner closure (filtered in finalize once
        # the owner set is known)
        self.owned: list[tuple] = []      # (attr, line, class leaf, qual)
        # per-method direct release effects
        self.joined: dict[str, set] = {}      # method -> attr roots joined
        self.closed: dict[str, set] = {}
        self.shutdown: dict[str, set] = {}
        self.self_calls: dict[str, set] = {}  # method -> called self methods
        self._scan()
        self._close()

    def _thread_ctor_daemonizes(self, call: ast.Call) -> bool:
        """daemon=True at the ctor, or an in-package Thread subclass whose
        __init__ passes daemon=True to super().__init__ / sets self.daemon."""
        if _is_true(_kw(call, "daemon")):
            return True
        name = dotted_name(call.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        for ci in self.index.class_by_name.get(leaf, ()):
            init_key = ci.methods.get("__init__")
            if not init_key:
                continue
            init = self.index.funcs[init_key].node
            for node in ast.walk(init):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "__init__" and \
                        isinstance(node.func.value, ast.Call) and \
                        dotted_name(node.func.value.func) == "super":
                    if _is_true(_kw(node, "daemon")):
                        return True
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) and \
                                t.attr == "daemon" and _is_true(node.value):
                            return True
        return False

    def _scan(self) -> None:
        for m in self.cls.body:
            if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qual = f"{self.cls.name}.{m.name}"
            joined = self.joined.setdefault(m.name, set())
            closed = self.closed.setdefault(m.name, set())
            shut = self.shutdown.setdefault(m.name, set())
            calls = self.self_calls.setdefault(m.name, set())
            # names bound by iterating a self collection: `for c in
            # self.consumers:` lets `c.join()` credit "consumers"
            iter_alias: dict[str, str] = {}
            for node in ast.walk(m):
                if isinstance(node, ast.For) and \
                        isinstance(node.target, ast.Name):
                    root = _attr_root(node.iter)
                    if root is None and isinstance(node.iter, ast.Call):
                        root = _attr_root(node.iter.func) \
                            if _attr_root(node.iter.func) else \
                            (_attr_root(node.iter.args[0])
                             if node.iter.args else None)
                    if root:
                        iter_alias[node.target.id] = root
            for node in ast.walk(m):
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted_name(node.func) or ""
                leaf = fname.rsplit(".", 1)[-1]
                if isinstance(node.func, ast.Attribute):
                    recv = node.func.value
                    root = _attr_root(recv)
                    if root is None and isinstance(recv, ast.Name):
                        root = iter_alias.get(recv.id)
                    if root is not None:
                        if leaf == "join":
                            joined.add(root)
                        elif leaf in ("close", "server_close", "stop",
                                      "close_locked", "_close_locked"):
                            closed.add(root)
                        elif leaf == "shutdown":
                            shut.add(root)
                    if isinstance(recv, ast.Name) and recv.id in ("self",
                                                                  "outer"):
                        calls.add(node.func.attr)
                # acquisitions
                self._scan_acquire(node, qual, m)

    def _scan_acquire(self, call: ast.Call, qual: str, method) -> None:
        fname = dotted_name(call.func) or ""
        leaf = fname.rsplit(".", 1)[-1]
        target_expr = _kw(call, "target")
        is_thread = (fname in THREAD_CTORS or leaf == "Thread"
                     or self._is_pkg_thread_subclass(fname))
        if is_thread and leaf != "start":
            serve = isinstance(target_expr, ast.Attribute) and \
                target_expr.attr == "serve_forever"
            attr = self._store_attr(call, method)
            if serve:
                server_root = _attr_root(target_expr.value)
                self.serves.append((attr, call.lineno, server_root, qual))
            else:
                daemonized = self._thread_ctor_daemonizes(call)
                if not daemonized:
                    self.threads.append((attr, call.lineno, call, qual))
        if fname in SOCKET_CTORS:
            attr = self._store_attr(call, method)
            if attr:
                self.sockets.append((attr, call.lineno, qual))
        elif leaf and leaf[0].isupper() and leaf in self.index.class_by_name:
            # instantiation of an in-package class stored on self — a
            # candidate owned resource (meaningful once the socket-owner
            # closure says the class owns sockets)
            attr = self._store_attr(call, method)
            if attr:
                self.owned.append((attr, call.lineno, leaf, qual))

    def _is_pkg_thread_subclass(self, fname: str) -> bool:
        leaf = fname.rsplit(".", 1)[-1]
        for ci in self.index.class_by_name.get(leaf, ()):
            if f"{ci.path}::{ci.name}" in self.index._thread_subclasses():
                return True
        return False

    def _store_attr(self, call: ast.Call, method) -> str | None:
        """The self-attr root this call's result is stored under (plain
        assign, or append/add into a self collection)."""
        for node in ast.walk(method):
            if isinstance(node, ast.Assign) and node.value is call:
                for t in node.targets:
                    root = _attr_root(t)
                    if root:
                        return root
                # local var later stored? track one hop: x = Thread();
                # self.a = x / self.a.append(x)
                if len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    var = node.targets[0].id
                    for n2 in ast.walk(method):
                        if isinstance(n2, ast.Assign) and \
                                isinstance(n2.value, ast.Name) and \
                                n2.value.id == var:
                            for t2 in n2.targets:
                                root = _attr_root(t2)
                                if root:
                                    return root
                        if isinstance(n2, ast.Call) and \
                                isinstance(n2.func, ast.Attribute) and \
                                n2.func.attr in ("append", "add") and \
                                n2.args and \
                                isinstance(n2.args[0], ast.Name) and \
                                n2.args[0].id == var:
                            root = _attr_root(n2.func.value)
                            if root:
                                return root
            if isinstance(node, ast.Call) and node is not call and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("append", "add") and \
                    call in node.args:
                root = _attr_root(node.func.value)
                if root:
                    return root
        return None

    def _close(self) -> None:
        """Interprocedural closure: a method inherits the release effects of
        the self-methods it calls (stop() -> _teardown() counts)."""
        changed = True
        while changed:
            changed = False
            for m, calls in self.self_calls.items():
                for callee in calls:
                    for table in (self.joined, self.closed, self.shutdown):
                        if callee in table and \
                                not table[callee] <= table[m]:
                            table[m] |= table[callee]
                            changed = True

    def all_joined(self) -> set:
        return set().union(*self.joined.values()) if self.joined else set()

    def all_closed(self) -> set:
        return set().union(*self.closed.values()) if self.closed else set()

    def all_shutdown(self) -> set:
        return set().union(*self.shutdown.values()) if self.shutdown else set()


class ResourceChecker:
    rules = ("resource-thread-no-stop", "resource-server-no-stop",
             "resource-worker-silent-death", "resource-no-release")

    def __init__(self):
        self._modules: dict[str, ast.Module] = {}
        self.project: PackageIndex | None = None
        self.corpus = None           # shared CFG memo, set by the runner

    def check_module(self, path: str, tree: ast.Module) -> list[Finding]:
        self._modules[path] = tree
        return []

    def finalize(self) -> list[Finding]:
        index = self.project or PackageIndex(self._modules)
        findings: list[Finding] = []
        class_res: list[tuple[str, _ClassResources]] = []
        for path, tree in self._modules.items():
            for node in tree.body:
                if isinstance(node, ast.ClassDef):
                    class_res.append((path,
                                      _ClassResources(path, node, index)))
            findings += self._check_module_threads(path, tree, index)
        owners = self._socket_owner_closure(class_res)
        for path, res in class_res:
            findings += self._check_class(path, res, owners)
        findings += self._check_worker_loops(index)
        findings += self._check_local_releases(index)
        return findings

    # -- class-level thread/server/socket lifecycle --------------------------

    @staticmethod
    def _socket_owner_closure(
            class_res: list[tuple[str, "_ClassResources"]]) -> set[str]:
        """Class names that own sockets, directly (self-stored
        SOCKET_CTORS) or transitively (self-stored instantiation of an
        owner class) — the replicated-ingest link/bus shape."""
        owners = {res.cls.name for _p, res in class_res if res.sockets}
        changed = True
        while changed:
            changed = False
            for _p, res in class_res:
                if res.cls.name in owners:
                    continue
                if any(leaf in owners for _a, _l, leaf, _q in res.owned):
                    owners.add(res.cls.name)
                    changed = True
        return owners

    def _check_class(self, path: str, res: "_ClassResources",
                     owners: set[str]) -> list[Finding]:
        findings: list[Finding] = []
        joined, closed, shut = (res.all_joined(), res.all_closed(),
                                res.all_shutdown())
        for attr, line, _call, qual in res.threads:
            if attr is None:
                findings.append(Finding(
                    "resource-thread-no-stop", path, line, qual,
                    "thread:<anonymous>",
                    "starts an anonymous non-daemon Thread — it can never "
                    "be joined; store it and join in stop()/close(), or "
                    "construct with daemon=True"))
            elif attr not in joined:
                findings.append(Finding(
                    "resource-thread-no-stop", path, line, qual,
                    f"thread:{attr}",
                    f"Thread stored in self.{attr} is neither daemon nor "
                    "joined anywhere in the class — a stop() must join it "
                    "(with a timeout) or the ctor must pass daemon=True"))
        for attr, line, server_root, qual in res.serves:
            missing = []
            if server_root is not None and server_root not in shut:
                missing.append(f"no {server_root}.shutdown() call")
            if attr is None:
                missing.append("thread not stored (never joinable)")
            elif attr not in joined:
                missing.append(f"self.{attr} never joined")
            if missing:
                findings.append(Finding(
                    "resource-server-no-stop", path, line, qual,
                    f"server:{server_root or '<anonymous>'}",
                    "serve_forever thread without a deterministic stop: "
                    + "; ".join(missing)
                    + " — shut the server down AND join the thread with a "
                      "timeout so the listening socket is released"))
        for attr, line, qual in res.sockets:
            if attr not in closed:
                findings.append(Finding(
                    "resource-no-release", path, line, qual,
                    f"socket:{attr}",
                    f"socket stored in self.{attr} has no close() reachable "
                    "from this class — a close()/stop() must release it"))
        seen: set[tuple[str, str]] = set()
        for attr, line, leaf, qual in res.owned:
            if leaf not in owners or attr in closed \
                    or (attr, leaf) in seen:
                continue
            seen.add((attr, leaf))
            findings.append(Finding(
                "resource-no-release", path, line, qual,
                f"owned:{attr}",
                f"socket-owning {leaf} stored in self.{attr} has no "
                "close()/stop() reachable from this class — every "
                "instantiated link/bus needs a teardown path or its "
                "sockets leak per reconnect"))
        return findings

    def _check_module_threads(self, path: str, tree: ast.Module,
                              index: PackageIndex) -> list[Finding]:
        """Module-level functions starting anonymous non-daemon threads."""
        findings: list[Finding] = []
        for fn in tree.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted_name(node.func) or ""
                if fname not in THREAD_CTORS:
                    continue
                if _is_true(_kw(node, "daemon")):
                    continue
                # stored/returned threads are the caller's responsibility;
                # only the start-and-forget shape is flagged here
                stored = any(isinstance(n, ast.Assign) and n.value is node
                             for n in ast.walk(fn))
                ret = any(isinstance(n, ast.Return) and n.value is node
                          for n in ast.walk(fn))
                if not stored and not ret:
                    findings.append(Finding(
                        "resource-thread-no-stop", path, node.lineno,
                        fn.name, "thread:<anonymous>",
                        "starts an anonymous non-daemon Thread — it can "
                        "never be joined; store/return it or pass "
                        "daemon=True"))
        return findings

    # -- worker loops must fail loud -----------------------------------------

    def _check_worker_loops(self, index: PackageIndex) -> list[Finding]:
        findings: list[Finding] = []
        for key in sorted(index.thread_entries):
            u = index.funcs.get(key)
            if u is None or u.path not in self._modules:
                continue
            fn = u.node
            loops = [n for n in ast.walk(fn)
                     if isinstance(n, (ast.While, ast.For))]
            if not loops:
                continue
            guarded = self._has_guarded_loop(fn)
            if not guarded:
                findings.append(Finding(
                    "resource-worker-silent-death", u.path, fn.lineno,
                    u.qualname, "worker-loop",
                    "thread worker loop has no broad exception handler with "
                    "an observable action — an unexpected exception kills "
                    "the thread silently and the pipeline stalls hours "
                    "later; wrap the loop in try/except that logs, counts "
                    "(filodb_swallowed_errors) or stores the error for the "
                    "consumer"))
        return findings

    @staticmethod
    def _has_guarded_loop(fn: ast.AST) -> bool:
        """Some loop in fn is enclosed by — or contains — a try with a
        broad, observable handler."""
        broad_trys = [n for n in ast.walk(fn) if isinstance(n, ast.Try)
                      and any(is_broad_handler(h) and _observable_handler(h)
                              for h in n.handlers)]
        if not broad_trys:
            return False
        loops = [n for n in ast.walk(fn)
                 if isinstance(n, (ast.While, ast.For))]
        for t in broad_trys:
            inside_t = set(map(id, ast.walk(t)))
            for lp in loops:
                if id(lp) in inside_t:
                    return True             # loop under the try
                if id(t) in set(map(id, ast.walk(lp))):
                    return True             # try inside the loop body
        return False

    # -- local file/socket handles: all-paths release -------------------------

    _LOCAL_ACQUIRES = {"open"} | SOCKET_CTORS

    def _check_local_releases(self, index: PackageIndex) -> list[Finding]:
        findings: list[Finding] = []
        for key, u in sorted(index.funcs.items()):
            if u.path not in self._modules:
                continue
            findings += self._check_func_releases(u)
        return findings

    def _check_func_releases(self, u) -> list[Finding]:
        fn = u.node
        acquires: list[tuple[ast.stmt, str, int]] = []  # (stmt, var, line)
        with_managed: set = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    with_managed.add(id(item.context_expr))
        body = getattr(fn, "body", [])
        for stmt in ast.walk(fn):
            if not isinstance(stmt, ast.Assign):
                continue
            call = stmt.value
            if not isinstance(call, ast.Call):
                continue
            fname = dotted_name(call.func) or ""
            if fname not in self._LOCAL_ACQUIRES or id(call) in with_managed:
                continue
            if len(stmt.targets) == 1 and isinstance(stmt.targets[0],
                                                     ast.Name):
                acquires.append((stmt, stmt.targets[0].id, stmt.lineno))
        if not acquires:
            return []
        # returned or stored on self -> ownership escapes this function
        escaped: set = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and \
                    isinstance(node.value, ast.Name):
                escaped.add(node.value.id)
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Name) and \
                    any(_attr_root(t) for t in node.targets):
                escaped.add(node.value.id)
        cfg = self.corpus.cfg(fn) if self.corpus is not None \
            else build_cfg(fn)
        findings = []
        for stmt, var, line in acquires:
            if var in escaped:
                continue
            idx = cfg.node_of(stmt)
            if idx is None:
                continue

            def _releases(s, _var=var):
                for n in ast.walk(s):
                    if isinstance(n, ast.Call) and \
                            isinstance(n.func, ast.Attribute) and \
                            n.func.attr in ("close", "shutdown") and \
                            isinstance(n.func.value, ast.Name) and \
                            n.func.value.id == _var:
                        return True
                return False

            if not releases_on_all_paths(cfg, idx, _releases):
                findings.append(Finding(
                    "resource-no-release", u.path, line, u.qualname,
                    f"handle:{var}",
                    f"{var} acquired here is not released on every path to "
                    "function exit (including exceptional ones) — use "
                    "`with` or close it in a finally block"))
        return findings
