"""CLI: ``python -m filodb_tpu.analysis [paths...]``.

Exit status: 0 when no NEW findings (inline-suppressed and baselined
findings are reported but don't fail); 1 otherwise; 2 on usage errors.

Output modes (``--format``): ``text`` (default, per-finding lines +
summary), ``json`` (full report for CI annotators / the bench harness),
``sarif`` (SARIF 2.1.0 for GitHub code scanning / editor viewers).

``--changed-only`` analyzes only files ``git status`` reports as modified
(plus the spec anchor modules the cross-file rules compare against) — the
fast pre-commit mode. ``--update-baseline`` rewrites the baseline with the
current NEW findings and REFUSES to run without ``--reason``: a baseline
entry is a promise, not a TODO.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path

from .corpus import parse_corpus
from .findings import (Baseline, is_suppressed, load_suppressions,
                       report_json, report_sarif)
from .livecheck import LiveChecker
from .runner import ALL_RULES, DEFAULT_BASELINE, run_analysis

# modules the cross-file rules need in scope even when unchanged: the wire
# codec + its HTTP classifier, the typed-error bases, the broker op spec,
# the declared-surface dicts, the epoch-visibility spec (EPOCH_SPEC — the
# epoch rules judge every mutator against it), and the liveness contract
# (LATENCY_SPEC in utils/diagnostics.py — livecheck judges lock-held spans
# and waits against it)
ANCHOR_MODULES = (
    "filodb_tpu/config.py",
    "filodb_tpu/core/memstore.py",
    "filodb_tpu/utils/metrics.py",
    "filodb_tpu/utils/diagnostics.py",
    "filodb_tpu/query/wire.py",
    "filodb_tpu/query/rangevector.py",
    "filodb_tpu/http/api.py",
    "filodb_tpu/ingest/broker.py",
)

# a change to any of these invalidates every scoped judgement: the checkers
# themselves, or the fixture twins that pin their behavior — escalate a
# --changed-only run to a full one instead of lint-checking the new rules
# against a partial corpus
_ESCALATE_PREFIXES = ("filodb_tpu/analysis/", "tests/fixtures/filolint/")


def _porcelain_paths(root: Path) -> list[str] | None:
    """Root-relative paths git reports changed (staged, unstaged or
    untracked), any extension/location. None on git failure. Porcelain
    paths are TOPLEVEL-relative; when ``root`` sits below the git toplevel
    (a vendored checkout), they are rebased via ``--show-prefix`` so a
    changed-only run never silently analyzes nothing."""
    try:
        out = subprocess.run(
            ["git", "status", "--porcelain"], cwd=root, capture_output=True,
            text=True, timeout=30, check=True).stdout
        prefix = subprocess.run(
            ["git", "rev-parse", "--show-prefix"], cwd=root,
            capture_output=True, text=True, timeout=30,
            check=True).stdout.strip()
    except (OSError, subprocess.SubprocessError):
        return None
    paths: list[str] = []
    for line in out.splitlines():
        p = line[3:].split(" -> ")[-1].strip().strip('"')
        if prefix:
            if not p.startswith(prefix):
                continue                    # outside the analysis root
            p = p[len(prefix):]
        paths.append(p)
    return paths


def _changed_files(root: Path) -> list[str] | None:
    """Root-relative changed .py paths under filodb_tpu/ (the analyzable
    subset of :func:`_porcelain_paths`)."""
    raw = _porcelain_paths(root)
    if raw is None:
        return None
    return [p for p in raw
            if p.endswith(".py") and p.startswith("filodb_tpu/")
            and (root / p).exists()]


def _tools_audit(root: Path) -> list[str]:
    """Liveness audit of the operational entry points (``stress/*.py``,
    ``scripts/*.py``) that sit OUTSIDE the package the main run analyzes.
    Tool code still deadlocks and still hangs CI, but it has no baseline
    and no fixture twins — so findings here are WARNINGS only: printed,
    never counted toward the exit status. The LATENCY_SPEC anchor module
    rides along so the livecheck rules have a contract in scope; findings
    in the anchor itself are the main run's business and are dropped."""
    anchor_rel = "filodb_tpu/utils/diagnostics.py"
    files: list[Path] = []
    for sub in ("stress", "scripts"):
        d = root / sub
        if d.is_dir():
            files.extend(sorted(d.glob("*.py")))
    if (root / anchor_rel).exists():
        files.append(root / anchor_rel)
    pairs = []
    for p in files:
        try:
            rel = p.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = p.as_posix()
        pairs.append((rel, p))
    corpus, errors = parse_corpus(pairs)
    checker = LiveChecker()
    findings = []
    for rel, tree in corpus.modules.items():
        findings += checker.check_module(rel, tree)
    if hasattr(checker, "project"):
        checker.project = corpus.index
    if hasattr(checker, "corpus"):
        checker.corpus = corpus
    findings += checker.finalize()
    lines = [f"filolint: tools-audit parse error in {rel}: {e}"
             for rel, e in errors]
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        if f.path == anchor_rel:
            continue
        supp = load_suppressions(corpus.sources.get(f.path, ""))
        if is_suppressed(f, supp):
            continue
        lines.append(f"filolint: tools-audit warning: {f.render()}")
    return lines


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m filodb_tpu.analysis",
        description="filolint: project-invariant static analysis "
                    "(lock discipline, JIT hygiene, wire exhaustiveness, "
                    "resource lifecycle, except-flow, declared surface)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the filodb_tpu "
                         "package next to this module)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: parent of the filodb_tpu "
                         "package)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: <root>/{DEFAULT_BASELINE})")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text", help="output format (default: text)")
    ap.add_argument("--changed-only", action="store_true",
                    help="analyze only git-modified files under filodb_tpu/ "
                         "(plus the cross-file anchor modules) — fast "
                         "pre-commit mode; *-unused rules are skipped")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline: keep entries that still "
                         "match, add the current NEW findings (requires "
                         "--reason), then exit 0")
    ap.add_argument("--reason", default=None,
                    help="why the findings being baselined are intentional "
                         "(required by --update-baseline)")
    ap.add_argument("--quiet", action="store_true",
                    help="summary only, no per-finding lines (text format)")
    ap.add_argument("--stats", action="store_true",
                    help="print per-rule-family timings and shared-corpus "
                         "build/hit counters to stderr")
    ap.add_argument("--include-tools", action="store_true",
                    help="additionally audit stress/ and scripts/ entry "
                         "points with the liveness rules — warnings only, "
                         "never affects the exit status")
    ap.add_argument("--no-shared-corpus", action="store_true",
                    help="re-parse the package and rebuild the index per "
                         "rule family (the pre-sharing cost model; findings "
                         "are identical — kept for benchmarking)")
    args = ap.parse_args(argv)

    root = Path(args.root) if args.root else \
        Path(__file__).resolve().parents[2]
    baseline_path = Path(args.baseline) if args.baseline \
        else root / DEFAULT_BASELINE

    paths = args.paths or None
    if args.changed_only:
        if paths:
            ap.error("--changed-only and explicit paths are exclusive")
        raw = _porcelain_paths(root)
        changed = None if raw is None else [
            p for p in raw if p.endswith(".py")
            and p.startswith("filodb_tpu/") and (root / p).exists()]
        if changed is None:
            print("filolint: git unavailable; falling back to a full run",
                  file=sys.stderr)
        elif any(p.startswith(_ESCALATE_PREFIXES) for p in raw):
            print("filolint: analysis code or fixture twins changed — "
                  "escalating --changed-only to a full run", file=sys.stderr)
        elif not changed:
            print("filolint: no changed files under filodb_tpu/ — nothing "
                  "to analyze")
            return 0
        else:
            anchors = [a for a in ANCHOR_MODULES if (root / a).exists()]
            paths = sorted(set(changed) | set(anchors))

    report = run_analysis(root, paths, baseline_path=baseline_path,
                          shared_corpus=not args.no_shared_corpus)
    if args.include_tools:
        for line in _tools_audit(root):
            print(line, file=sys.stderr)
    if args.stats:
        for line in report.stats_lines():
            print(line, file=sys.stderr)

    if args.update_baseline:
        if report.new and not (args.reason and args.reason.strip()):
            print("filolint: --update-baseline refuses entries without a "
                  "--reason (a baseline entry is a promise that the finding "
                  "is intentional)", file=sys.stderr)
            return 2
        # keep existing entries that still correspond to a live finding, so
        # stale promises age out of the file instead of accreting — but only
        # judge entries for files THIS run analyzed: a narrow scope
        # (--changed-only / explicit paths) must not delete out-of-scope
        # promises it never re-checked
        analyzed = set(report.analyzed_paths)
        live = {f.fingerprint for f in report.baselined}
        old = Baseline.load(baseline_path)
        keep = [e for e in old.entries
                if e["file"] not in analyzed
                or (e["rule"], e["file"], e["symbol"], e["detail"]) in live]
        Baseline.write(baseline_path, report.new, reason=args.reason,
                       keep=keep)
        print(f"baseline updated: {len(keep)} kept, {len(report.new)} added "
              f"-> {baseline_path}")
        return 0

    if args.format == "json":
        print(report_json(report))
    elif args.format == "sarif":
        print(report_sarif(report, ALL_RULES))
    else:
        if not args.quiet:
            for f in sorted(report.new, key=lambda f: (f.path, f.line)):
                print(f.render())
        print(report.summary())
    return 1 if report.new else 0


if __name__ == "__main__":
    sys.exit(main())
