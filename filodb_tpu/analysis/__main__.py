"""CLI: ``python -m filodb_tpu.analysis [paths...]``.

Exit status: 0 when no NEW findings (inline-suppressed and baselined
findings are reported but don't fail); 1 otherwise; 2 on usage errors.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .findings import Baseline
from .runner import DEFAULT_BASELINE, run_analysis


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m filodb_tpu.analysis",
        description="filolint: project-invariant static analysis "
                    "(lock discipline, JIT hygiene, wire exhaustiveness)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the filodb_tpu "
                         "package next to this module)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: parent of the filodb_tpu "
                         "package)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: <root>/{DEFAULT_BASELINE})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write all current NEW findings to the baseline "
                         "file (then hand-edit the reasons) and exit 0")
    ap.add_argument("--quiet", action="store_true",
                    help="summary only, no per-finding lines")
    args = ap.parse_args(argv)

    root = Path(args.root) if args.root else \
        Path(__file__).resolve().parents[2]
    baseline_path = Path(args.baseline) if args.baseline \
        else root / DEFAULT_BASELINE

    report = run_analysis(root, args.paths or None,
                          baseline_path=baseline_path)

    if args.write_baseline:
        Baseline.write(baseline_path, report.new)
        print(f"wrote {len(report.new)} entries to {baseline_path} — "
              "fill in the reason for each")
        return 0

    if not args.quiet:
        for f in sorted(report.new, key=lambda f: (f.path, f.line)):
            print(f.render())
    print(report.summary())
    return 1 if report.new else 0


if __name__ == "__main__":
    sys.exit(main())
