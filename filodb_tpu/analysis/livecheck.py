"""Liveness & bounded-wait contracts: no blocking under locks, deadline-
bounded I/O, bounded retry loops.

The store's "real-time" claim is a liveness claim: one slow peer, hung
socket, or unbounded retry must not wedge a shard lock, a drain loop, or
a tenant's admission slot. PR 6 and PR 10 each caught such stalls by
hand-review (teardown behind a 30 s recv; a whole-log rewrite under all
group-flush locks); this family makes the discipline structural.
``utils/diagnostics.py`` declares the surface as ``LATENCY_SPEC`` (a
pure-literal dict, read from the AST like EPOCH_SPEC): the lock classes,
the blocking-call taxonomy, the blocking protocol surface of the sink
(``self.sink.*`` is unresolvable by the call graph, so it is declared
the way EPOCH_SPEC declares visible_calls), and the sanctioned sites —
each with a REQUIRED reason string saying why it is allowed to block.

Rules (interprocedural — PackageIndex closure + shared per-function
CFGs):

  * ``live-block-under-lock`` — no socket connect/recv/send/accept, file
    open, ``time.sleep``, ``Thread.join``, subprocess, or HTTP call on
    any CFG path while a shard/group/sink lock is held (lexical
    ``with``, ``_locked``-suffix contract on a lock-owner class,
    ``enter_context``, or ``assert_owned``). Blocking propagates through
    undeclared helpers exactly like epochcheck's obligations: a helper
    that sleeps taints every undeclared caller, and the finding lands
    where the lock is held. A declared site caps its subtree — whatever
    it does is its stated responsibility.
  * ``live-unbounded-io`` — every socket created or connected must be
    deadline-bounded before its first blocking op on ALL CFG paths:
    ``create_connection`` needs a timeout argument (which the stdlib
    applies to the socket itself, so it bounds later recv/send too);
    a raw ``socket.socket()`` needs a ``settimeout`` that dominates
    every path from creation to the first connect/accept/recv/send.
    A socket that never blocks (bind-and-inspect, like free_port) is
    vacuously fine.
  * ``live-unbounded-retry`` — a retry loop (a ``while`` whose body
    retries a failed operation via try/except, or a ``for … in
    range(…)`` attempt loop) must carry a statically visible bound AND a
    backoff. Bound evidence is value-flow: a counter compared in the
    loop test and advanced in the body, a deadline (``time.monotonic``
    or a deadline-named value) in the test, a stop-event ``.wait(t)`` /
    ``.is_set()`` pacing test, or a guard (``if attempt >= max: raise``)
    that DOMINATES the loop back edge — a guard a path can skip bounds
    nothing. Backoff evidence: a sleep (direct or through a resolved
    helper — the taint fixpoint above), a timed ``.wait``/``.get``, or
    a backoff-named call. Serve loops on thread entries that reference
    a shutdown signal are exempt: they are bounded by shutdown and the
    resource family already requires them to survive faults.
  * ``live-wait-no-timeout`` — ``Condition.wait``/``Event.wait`` with no
    timeout, ``Queue.get()`` with neither timeout nor block=False, and
    zero-argument ``thread.join()`` park a thread on a wakeup that one
    lost notify, dead producer, or wedged peer cancels forever. Every
    such wait needs a timeout operand (re-check your predicate; you were
    going to loop anyway) or a declared shutdown-aware wrapper in
    LATENCY_SPEC's ``wait_ok``.

Sanctions: ``sites`` (rule 1) and ``wait_ok`` (rule 4) entries are
``{name: {"fn": "Class.method", "reason": "..."}}``; an entry with no
reason is itself a finding. Sanction extends down reverse-call chains
via ``reachable_only_from`` — a helper only callable from declared
sites inherits their sanction.

Fixture twins: bad/good_live_{block,io,retry,wait}.py. Pure stdlib
``ast``; no jax import.
"""

from __future__ import annotations

import ast
import re
import time

from .callgraph import dotted_name, leaf_name
from .cfg import CFG, backedge_dominated, guarded_between
from .findings import Finding

_SPEC_NAME = "LATENCY_SPEC"

_SOCK_BLOCKING_OPS = ("connect", "accept", "recv", "recv_into", "recvfrom",
                      "send", "sendall", "makefile")
_SLEEP_LEAVES = ("sleep", "_sleep")
_CLOCK_LEAVES = ("monotonic", "time", "perf_counter")
_SHUTDOWN_RE = re.compile(
    r"stop|shutdown|closed?|running|done|cancel|alive|quit|halt",
    re.IGNORECASE)
_DEADLINE_RE = re.compile(r"deadline|until|budget|expir", re.IGNORECASE)
_QUEUE_RECV_RE = re.compile(r"(?:^|_)q(?:ueue)?s?\d*$|queue", re.IGNORECASE)


def _own_nodes(fn: ast.AST):
    """Walk a function's body without descending into nested defs (nested
    functions are their own FuncUnits)."""
    todo = list(getattr(fn, "body", []))
    while todo:
        node = todo.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            todo.append(child)


def _subtree_no_defs(root: ast.AST):
    """Walk a subtree (including ``root``) without entering nested defs."""
    todo = [root]
    while todo:
        node = todo.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            todo.append(child)


def _stmt_map_of(cfg: CFG) -> dict[int, int]:
    """id(node) -> index of the innermost CFG statement containing it
    (compound statements are CFG nodes too, so the smallest subtree
    wins). One walk per CFG; lookups are O(1) after that."""
    best: dict[int, tuple[int, int]] = {}       # id -> (size, index)
    for i, s in enumerate(cfg.stmts):
        subs = list(ast.walk(s))
        size = len(subs)
        for sub in subs:
            got = best.get(id(sub))
            if got is None or size < got[0]:
                best[id(sub)] = (size, i)
    return {k: i for k, (_sz, i) in best.items()}


def _names_in(node: ast.AST):
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            yield n.id
        elif isinstance(n, ast.Attribute):
            yield n.attr


def _target_names(t: ast.AST):
    """Plain names an assignment target binds — recursing through tuple/
    list/star unpacking (``ready, _, _ = select.select(...)``) but NOT
    into attribute/subscript targets, whose value names aren't bindings."""
    if isinstance(t, ast.Name):
        yield t.id
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _target_names(e)
    elif isinstance(t, ast.Starred):
        yield from _target_names(t.value)


def _end_line(node: ast.AST) -> int:
    return max((s.lineno for s in ast.walk(node) if hasattr(s, "lineno")),
               default=getattr(node, "lineno", 0))


def _extract_spec(tree: ast.Module) -> tuple[dict, int] | None:
    """The module's ``LATENCY_SPEC`` literal and its line, or None.
    literal_eval keeps the contract honest: a computed spec cannot be
    statically checked."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == _SPEC_NAME:
            try:
                spec = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                return None
            return (spec, node.lineno) if isinstance(spec, dict) else None
    return None


def _lock_class_of(expr: ast.expr, locks: dict) -> str | None:
    """``self.lock`` / ``self._group_flush_locks[g]`` / a bare spec-named
    Name -> the declared lock class, else None."""
    node = expr
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return locks.get(node.attr)
    if isinstance(node, ast.Name):
        return locks.get(node.id)
    return None


def _is_thread_join(call: ast.Call) -> bool:
    """A join that can PARK the calling thread: zero args, or one numeric
    timeout. ``",".join(parts)`` / ``os.path.join(a, b)`` take non-numeric
    arguments and never match."""
    if any(kw.arg not in ("timeout",) for kw in call.keywords):
        return False
    if not call.args:
        return True
    if len(call.args) == 1 and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, (int, float)) \
            and not isinstance(call.args[0].value, bool):
        return True
    return False


def _timed_call(call: ast.Call) -> bool:
    """Does this wait/get carry a bound (positional timeout or kwarg)?"""
    if call.args:
        return True
    return any(kw.arg in ("timeout", "block") for kw in call.keywords)


class LiveChecker:
    rules = ("live-block-under-lock", "live-unbounded-io",
             "live-unbounded-retry", "live-wait-no-timeout")

    # the one module whose spec governs cross-file analysis in a full run;
    # a fixture twin's own spec governs only itself
    GLOBAL_SPEC_PATH = re.compile(r"(?:^|/)utils/diagnostics\.py$")

    def __init__(self):
        self.project = None
        self.corpus = None
        self.sub_timings: dict[str, float] = {}
        self._modules: dict[str, ast.Module] = {}
        self._specs: dict[str, tuple[dict, int]] = {}
        self._stmt_maps: dict[int, dict[int, int]] = {}
        self._owner_cache: dict[tuple, str | None] = {}

    def check_module(self, path: str, tree: ast.Module) -> list[Finding]:
        self._modules[path] = tree
        got = _extract_spec(tree)
        if got is not None:
            self._specs[path] = got
        return []

    # -- spec resolution ------------------------------------------------------

    def _global_spec(self) -> tuple[str, dict] | None:
        for path, (spec, _line) in self._specs.items():
            if self.GLOBAL_SPEC_PATH.search(path):
                return path, spec
        if len(self._specs) == 1:
            path, (spec, _line) = next(iter(self._specs.items()))
            return path, spec
        return None

    def _spec_for(self, path: str) -> tuple[str, dict] | None:
        if path in self._specs:
            return path, self._specs[path][0]
        return self._global_spec()

    def _cfg(self, fn: ast.AST) -> CFG:
        if self.corpus is not None:
            return self.corpus.cfg(fn)
        from .cfg import build_cfg
        return build_cfg(fn)

    def _stmt_idx(self, cfg: CFG, node: ast.AST) -> int | None:
        m = self._stmt_maps.get(id(cfg))
        if m is None:
            m = self._stmt_maps[id(cfg)] = _stmt_map_of(cfg)
        return m.get(id(node))

    # -- finalize -------------------------------------------------------------

    def finalize(self) -> list[Finding]:
        findings: list[Finding] = []
        if self.project is None:
            return findings
        t0 = time.perf_counter()
        prep = self._prepare()
        self.sub_timings["prep"] = time.perf_counter() - t0
        findings += prep["spec_errors"]
        for name, fn in (("block", self._block_pass),
                         ("io", self._io_pass),
                         ("retry", self._retry_pass),
                         ("wait", self._wait_pass)):
            t0 = time.perf_counter()
            findings += fn(prep)
            self.sub_timings[name] = time.perf_counter() - t0
        return findings

    # -- shared preparation ---------------------------------------------------

    def _prepare(self) -> dict:
        """Per-spec declared sets (with reason validation), per-function
        direct blocking events, and the transitive blocking-kinds fixpoint
        that both the block-under-lock and retry-backoff queries consume."""
        spec_errors: list[Finding] = []
        declared: dict[str, set] = {}       # spec path -> sanctioned keys
        declared_retry: set = set()         # retry_ok keys (rule 3 only)
        for spec_path, (spec, line) in self._specs.items():
            keys: set = set()
            for section, rule in (("sites", "live-block-under-lock"),
                                  ("wait_ok", "live-wait-no-timeout"),
                                  ("retry_ok", "live-unbounded-retry")):
                for name, site in (spec.get(section) or {}).items():
                    if not isinstance(site, dict) or not site.get("fn"):
                        continue
                    resolved = self._resolve_site(spec_path,
                                                  str(site["fn"]))
                    if not resolved and getattr(self, "full_scope", True):
                        spec_errors.append(Finding(
                            rule, spec_path, line, _SPEC_NAME,
                            f"site:{name}:unresolved",
                            f"declared sanction {name!r} names "
                            f"{site['fn']!r}, which matches no function "
                            "in the analyzed corpus — a stale sanction "
                            "silently re-sanctions whatever takes that "
                            "name next; fix or delete it"))
                    if section == "retry_ok":
                        # rule-3 only: a sanctioned serve loop is still
                        # forbidden to block under a lock
                        declared_retry.update(resolved)
                    else:
                        keys.update(resolved)
                    if not str(site.get("reason") or "").strip():
                        spec_errors.append(Finding(
                            rule, spec_path, line, _SPEC_NAME,
                            f"site:{name}",
                            f"declared sanction {name!r} ({site['fn']}) "
                            "has no reason string — every site allowed to "
                            "block must say why (what bounds it, who "
                            "guarantees progress)"))
            declared[spec_path] = keys
        declared_all = set().union(*declared.values()) if declared else set()

        scoped: dict[str, dict] = {}        # key -> governing spec
        events: dict[str, list] = {}        # key -> direct blocking events
        kinds: dict[str, set] = {}          # key -> transitive kinds
        nodes: dict[str, list] = {}         # key -> own-node list (cached
        #                                     once; every pass re-iterates
        #                                     it instead of re-walking)
        for key, u in self.project.funcs.items():
            got = self._spec_for(u.path)
            if got is None:
                continue
            spec_path, spec = got
            scoped[key] = {"spec_path": spec_path, "spec": spec}
            own = list(_own_nodes(u.node))
            nodes[key] = own
            evs = self._direct_events(u, spec, own)
            events[key] = evs
            kinds[key] = {e["kind"] for e in evs}
        changed = True
        while changed:
            changed = False
            for key in scoped:
                u = self.project.funcs[key]
                mine = kinds[key]
                for site in u.calls:
                    if site.callee_key in declared_all:
                        continue            # a declared site caps its subtree
                    add = kinds.get(site.callee_key, set()) - mine
                    if add:
                        mine |= add
                        changed = True
        return {"spec_errors": spec_errors, "declared": declared,
                "declared_all": declared_all,
                "declared_retry": declared_retry, "scoped": scoped,
                "events": events, "kinds": kinds, "nodes": nodes}

    def _resolve_site(self, spec_path: str, fn: str) -> set:
        """Keys a declared sanction covers: an explicit ``path::qualname``
        verbatim, else every function in the corpus whose qualname matches
        (the spec names sites in OTHER modules — resolution must not be
        anchored to the spec's own path)."""
        if "::" in fn:
            return {fn} if fn in self.project.funcs else set()
        return {k for k, u in self.project.funcs.items()
                if u.qualname == fn}

    def _direct_events(self, u, spec: dict, own: list) -> list[dict]:
        blocking = spec.get("blocking") or {}
        attr_calls = {k: tuple(v) for k, v in
                      (spec.get("blocking_attr_calls") or {}).items()}
        aliases: dict[str, str] = {}
        for node in own:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Attribute) \
                    and node.value.attr in attr_calls:
                aliases[node.targets[0].id] = node.value.attr
        out: list[dict] = []
        for node in own:
            if not isinstance(node, ast.Call):
                continue
            leaf = leaf_name(node.func)
            if leaf in blocking:
                if leaf == "join" and not _is_thread_join(node):
                    continue
                out.append({"line": node.lineno, "kind": blocking[leaf],
                            "detail": leaf})
                continue
            if isinstance(node.func, ast.Attribute):
                recv = node.func.value
                attr = recv.attr if isinstance(recv, ast.Attribute) \
                    else aliases.get(recv.id) \
                    if isinstance(recv, ast.Name) else None
                if attr in attr_calls and node.func.attr in attr_calls[attr]:
                    out.append({"line": node.lineno, "kind": f"{attr}-io",
                                "detail": f"{attr}.{node.func.attr}"})
        return out

    def _lock_owner_class(self, path: str, cls: str | None,
                          locks: dict) -> str | None:
        """The lock class a ``_locked``-suffix method holds by contract:
        the class must actually OWN a spec lock (``self.<attr> = …``
        somewhere in its body) — a private object mutex named ``_lock``
        on a non-owner class is not a latency-spec lock."""
        if cls is None:
            return None
        # fixture twins carry their own specs: the lock table is part of
        # the answer's identity, not just the class
        ck = (path, cls, frozenset(locks.items()))
        if ck in self._owner_cache:
            return self._owner_cache[ck]
        out = None
        ci = self.project.classes.get(f"{path}::{cls}")
        if ci is not None:
            for node in ast.walk(ci.node):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self" \
                                and t.attr in locks:
                            out = locks[t.attr]
                            break
                    if out:
                        break
        self._owner_cache[ck] = out
        return out

    def _held_spans(self, u, locks: dict,
                    own: list) -> list[tuple[int, int, str]]:
        """(start_line, end_line, lock_class) regions where ``u`` holds a
        declared lock: lexical ``with``, ``enter_context`` (including the
        ExitStack-over-all-group-locks idiom), ``assert_owned``, and the
        ``_locked`` caller-holds contract on lock-owner classes."""
        spans: list[tuple[int, int, str]] = []
        if u.name.endswith("_locked"):
            cls = self._lock_owner_class(u.path, u.cls, locks)
            if cls:
                spans.append((0, 10 ** 9, cls))
        lockish_names: dict[str, str] = {}      # for lk in self.<locks>: …
        for node in own:
            if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
                cls = _lock_class_of(node.iter, locks)
                if cls:
                    lockish_names[node.target.id] = cls
        for node in own:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    cls = _lock_class_of(item.context_expr, locks)
                    if cls:
                        spans.append((node.lineno, _end_line(node), cls))
            elif isinstance(node, ast.Call):
                leaf = leaf_name(node.func)
                if leaf in ("enter_context", "assert_owned") and node.args:
                    arg = node.args[0]
                    cls = _lock_class_of(arg, locks)
                    if cls is None and isinstance(arg, ast.Name):
                        cls = lockish_names.get(arg.id)
                    if cls:
                        spans.append((node.lineno, 10 ** 9, cls))
        return spans

    # -- rule 1: live-block-under-lock ---------------------------------------

    def _block_pass(self, prep: dict) -> list[Finding]:
        findings: list[Finding] = []
        declared_all = prep["declared_all"]
        for key, scope in prep["scoped"].items():
            u = self.project.funcs[key]
            locks = scope["spec"].get("locks") or {}
            spans = self._held_spans(u, locks, prep["nodes"][key])
            if not spans:
                continue

            def held(line: int) -> str | None:
                for lo, hi, cls in spans:
                    if lo <= line <= hi:
                        return cls
                return None

            hits: list[tuple[int, str, str, str]] = []
            for ev in prep["events"][key]:
                cls = held(ev["line"])
                if cls:
                    hits.append((ev["line"], cls, ev["detail"], ev["kind"]))
            for site in u.calls:
                if site.callee_key in declared_all:
                    continue
                ck = prep["kinds"].get(site.callee_key)
                if not ck:
                    continue
                cls = held(site.line)
                if cls:
                    cu = self.project.funcs[site.callee_key]
                    hits.append((site.line, cls,
                                 f"call:{cu.qualname}",
                                 ",".join(sorted(ck))))
            if not hits:
                continue
            if key in declared_all or self.project.reachable_only_from(
                    key, declared_all):
                continue
            for line, cls, detail, kind in hits:
                findings.append(Finding(
                    "live-block-under-lock", u.path, line, u.qualname,
                    f"{cls}:{detail}",
                    f"{detail} ({kind}) can block while the {cls} lock is "
                    "held — one slow peer or hung fd wedges every reader "
                    "and writer behind this lock; move the blocking work "
                    "outside the hold (copy-out → block → swap-in) or "
                    "declare the site with its reason in LATENCY_SPEC "
                    "(utils/diagnostics.py)"))
        return findings

    # -- rule 2: live-unbounded-io -------------------------------------------

    def _io_pass(self, prep: dict) -> list[Finding]:
        findings: list[Finding] = []
        for key, _scope in prep["scoped"].items():
            u = self.project.funcs[key]
            creations: list[tuple[ast.Call, str]] = []
            for node in prep["nodes"][key]:
                if isinstance(node, ast.Call) \
                        and leaf_name(node.func) == "create_connection":
                    if len(node.args) >= 2 or any(
                            kw.arg == "timeout" for kw in node.keywords):
                        continue        # stdlib applies it to the socket
                    findings.append(Finding(
                        "live-unbounded-io", u.path, node.lineno,
                        u.qualname, "create_connection",
                        "create_connection without a timeout argument — "
                        "a SYN-blackholed peer parks this thread for the "
                        "kernel default (minutes); pass timeout= (it also "
                        "bounds every later recv/send on the socket)"))
                elif isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.value, ast.Call) \
                        and leaf_name(node.value.func) == "socket":
                    token = dotted_name(node.targets[0])
                    if token:
                        creations.append((node.value, token))
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        if isinstance(item.context_expr, ast.Call) \
                                and leaf_name(item.context_expr.func) \
                                == "socket" \
                                and item.optional_vars is not None:
                            token = dotted_name(item.optional_vars)
                            if token:
                                creations.append(
                                    (item.context_expr, token))
            if not creations:
                continue
            cfg = self._cfg(u.node)

            for call, token in creations:
                def op_on(stmt: ast.AST, attrs: tuple,
                          _token=token) -> bool:
                    for n in ast.walk(stmt):
                        if isinstance(n, ast.Call) \
                                and isinstance(n.func, ast.Attribute) \
                                and n.func.attr in attrs \
                                and dotted_name(n.func.value) == _token:
                            return True
                    return False

                idx = self._stmt_idx(cfg, call)
                if idx is None:
                    continue
                if not guarded_between(
                        cfg, idx,
                        lambda s: op_on(s, _SOCK_BLOCKING_OPS),
                        lambda s: op_on(s, ("settimeout",))):
                    findings.append(Finding(
                        "live-unbounded-io", u.path, call.lineno,
                        u.qualname, f"socket:{token}",
                        f"socket {token} reaches a blocking op on a CFG "
                        "path with no settimeout before it — the op "
                        "inherits no deadline and can hang forever; call "
                        f"{token}.settimeout(...) immediately after "
                        "creation, before any connect/accept/recv/send"))
        return findings

    # -- rule 3: live-unbounded-retry ----------------------------------------

    def _retry_pass(self, prep: dict) -> list[Finding]:
        findings: list[Finding] = []
        for key, _scope in prep["scoped"].items():
            if key in prep["declared_retry"]:
                continue        # sanctioned serve loop (reason required)
            u = self.project.funcs[key]
            loops = [n for n in prep["nodes"][key]
                     if isinstance(n, (ast.While, ast.For))]
            if not loops:
                continue
            cfg = None
            for loop in loops:
                shape = self._retry_shape(loop)
                if shape is None:
                    continue
                if isinstance(loop, ast.While) \
                        and key in self.project.thread_entries \
                        and any(_SHUTDOWN_RE.search(n)
                                for n in _names_in(loop)):
                    continue    # shutdown-bounded serve loop on a worker
                if cfg is None:
                    cfg = self._cfg(u.node)
                bounded = shape == "for-range" \
                    or self._loop_bounded(u, loop, cfg)
                if not bounded:
                    findings.append(Finding(
                        "live-unbounded-retry", u.path, loop.lineno,
                        u.qualname, f"loop:{loop.lineno}:no-bound",
                        "retry loop has no statically visible attempt "
                        "bound or deadline — a persistently failing peer "
                        "spins this path forever; compare an attempt "
                        "counter or monotonic deadline in the loop test, "
                        "or guard the back edge with one (the guard must "
                        "run on EVERY iteration)"))
                elif not self._loop_backoff(u, loop, prep["kinds"],
                                            _scope["spec"]):
                    findings.append(Finding(
                        "live-unbounded-retry", u.path, loop.lineno,
                        u.qualname, f"loop:{loop.lineno}:no-backoff",
                        "bounded retry loop has no backoff — hot "
                        "re-attempts hammer the failing peer and burn the "
                        "attempt budget in microseconds; sleep (ideally "
                        "exponentially) or pace on a timed wait between "
                        "attempts"))
        return findings

    def _retry_shape(self, loop: ast.AST) -> str | None:
        """Is this loop a RETRY of a failed operation? ``while`` + an own
        try whose handler reaches the back edge, or ``for … in range`` +
        the same try shape (bounded by construction). Iteration over a
        collection (``for addr in addrs``) is failover, not retry."""
        if isinstance(loop, ast.For):
            it = loop.iter
            if not (isinstance(it, ast.Call)
                    and leaf_name(it.func) == "range"):
                return None
        tries = []
        todo = list(loop.body) + list(getattr(loop, "orelse", []))
        while todo:
            node = todo.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.While, ast.For)):
                continue        # nested loops judge their own tries
            if isinstance(node, ast.Try):
                tries.append(node)
            todo.extend(ast.iter_child_nodes(node))
        for t in tries:
            for h in t.handlers:
                if self._handler_retries(h):
                    return "for-range" if isinstance(loop, ast.For) \
                        else "while"
        return None

    _TRANSIENT_EXC = frozenset((
        "Exception", "BaseException", "OSError", "IOError",
        "EnvironmentError", "error", "timeout"))
    _TRANSIENT_EXC_RE = re.compile(
        r"Connection|Timeout|Retry|Unavailable|Transient|BrokenPipe", re.I)

    @classmethod
    def _handler_retries(cls, handler: ast.ExceptHandler) -> bool:
        """Does the handler retry the failed operation? A ``continue``
        anywhere, or a fall-through tail (last statement is not raise/
        return/break) that caught a TRANSIENT fault class — ``except
        ValueError: x = fallback`` is value repair inside an ordinary
        loop, not a retry of a failing peer."""
        if any(isinstance(n, ast.Continue) for n in ast.walk(handler)):
            return True
        tail = handler.body[-1] if handler.body else None
        if isinstance(tail, (ast.Raise, ast.Return, ast.Break)):
            return False
        if handler.type is None:
            return True                      # bare except swallows faults
        types = handler.type.elts if isinstance(handler.type, ast.Tuple) \
            else [handler.type]
        for e in types:
            leaf = leaf_name(e)
            if leaf and (leaf in cls._TRANSIENT_EXC
                         or cls._TRANSIENT_EXC_RE.search(leaf)):
                return True
        return False

    def _loop_bounded(self, u, loop: ast.While, cfg: CFG) -> bool:
        assigned: set = set()
        for node in _subtree_no_defs(loop):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target] if isinstance(node, ast.AugAssign) \
                else []
            for t in targets:
                assigned.update(_target_names(t))
        test = loop.test
        if not (isinstance(test, ast.Constant) and test.value is True):
            tn = set(_names_in(test))
            if tn & assigned:
                return True         # counter compared in the test
            if any(_SHUTDOWN_RE.search(n) or _DEADLINE_RE.search(n)
                   for n in tn):
                return True         # shutdown- or deadline-bounded
            for n in ast.walk(test):
                if isinstance(n, ast.Call):
                    if leaf_name(n.func) in _CLOCK_LEAVES:
                        return True
                    if isinstance(n.func, ast.Attribute) \
                            and (n.func.attr == "is_set"
                                 or (n.func.attr == "wait"
                                     and _timed_call(n))):
                        return True
        # guards bound the loop only if their UNION dominates the back
        # edge: a loop with three distinct retry outcomes (fenced / shed /
        # transport-fail) is bounded when every path back to the head
        # crosses SOME counter guard, even though no single guard sits on
        # all of them
        loop_idx = self._stmt_idx(cfg, loop)
        if loop_idx is None:
            return False
        guards: list = []
        for node in _subtree_no_defs(loop):
            if not isinstance(node, ast.If):
                continue
            gn = set(_names_in(node.test))
            named = bool(gn & assigned) or any(
                _SHUTDOWN_RE.search(n) or _DEADLINE_RE.search(n)
                for n in gn)
            clocked = any(isinstance(c, ast.Call)
                          and leaf_name(c.func) in _CLOCK_LEAVES
                          for c in ast.walk(node.test))
            if not (named or clocked):
                continue
            if not any(isinstance(n, (ast.Raise, ast.Return, ast.Break))
                       for n in ast.walk(node)):
                continue
            guards.append(node)
        if not guards:
            return False
        return backedge_dominated(
            cfg, loop_idx, lambda s: any(s is g for g in guards))

    def _loop_backoff(self, u, loop: ast.AST, kinds: dict,
                      spec: dict) -> bool:
        pacing = set(spec.get("pacing_calls") or ())
        for node in _subtree_no_defs(loop):
            if not isinstance(node, ast.Call):
                continue
            leaf = leaf_name(node.func)
            if leaf in _SLEEP_LEAVES or leaf in pacing:
                return True
            if leaf and "backoff" in leaf.lower():
                return True
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("wait", "get") \
                    and _timed_call(node):
                return True
        lo, hi = loop.lineno, _end_line(loop)
        for site in u.calls:
            if lo <= site.line <= hi \
                    and "sleep" in kinds.get(site.callee_key, ()):
                return True         # backoff through a resolved helper
        return False

    # -- rule 4: live-wait-no-timeout ----------------------------------------

    def _wait_pass(self, prep: dict) -> list[Finding]:
        findings: list[Finding] = []
        declared_all = prep["declared_all"]
        for key, _scope in prep["scoped"].items():
            u = self.project.funcs[key]
            events: list[tuple[int, str, str]] = []
            for node in prep["nodes"][key]:
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                meth = node.func.attr
                recv = leaf_name(node.func.value) or "<expr>"
                if meth in ("wait", "wait_for") and not _timed_call(node):
                    events.append((node.lineno, f"{recv}.{meth}()",
                                   "one lost notify or a dead producer "
                                   "parks this thread forever"))
                elif meth == "get" and not node.args and not node.keywords \
                        and _QUEUE_RECV_RE.search(recv):
                    events.append((node.lineno, f"{recv}.get()",
                                   "a producer that dies without its "
                                   "sentinel parks this consumer forever"))
                elif meth == "join" and not node.args \
                        and not node.keywords \
                        and isinstance(node.func.value,
                                       (ast.Name, ast.Attribute)):
                    if _is_thread_join(node):
                        events.append((node.lineno, f"{recv}.join()",
                                       "a wedged worker blocks shutdown "
                                       "indefinitely"))
            if not events:
                continue
            if key in declared_all or self.project.reachable_only_from(
                    key, declared_all):
                continue
            for line, what, why in events:
                findings.append(Finding(
                    "live-wait-no-timeout", u.path, line, u.qualname,
                    what,
                    f"{what} has no timeout — {why}; pass a timeout and "
                    "re-check your predicate (you were looping anyway), "
                    "or declare a shutdown-aware wrapper in "
                    "LATENCY_SPEC['wait_ok'] (utils/diagnostics.py)"))
        return findings
