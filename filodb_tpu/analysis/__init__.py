"""filolint: project-invariant static analysis for filodb_tpu.

The port leans on conventions the language never checks, exactly like the
reference FiloDB leans on its per-shard ingest threads + ChunkMap read locks
(SURVEY §0). v2 is INTERPROCEDURAL: a per-module call graph plus
per-function CFGs (analysis/callgraph.py, analysis/cfg.py) propagate
holds-lock / owns-resource / may-raise facts through helper calls, and six
rule families run on top. The original three:

  * **lock discipline** — ``*_locked`` methods must run under the owning
    object's lock (core/memstore.py's shard ``TimedRLock``); state mutated by
    ``*_locked`` methods must not be written from non-holders; and the three
    lock classes (group-flush, sink, shard) have one global acquisition order
    (utils/diagnostics.LOCK_ORDER) — a cycle is a potential deadlock.
  * **JIT hygiene** — inside ``jax.jit``-compiled functions a stray
    ``float()``/``.item()``/``np.asarray``/``jax.device_get`` is a silent
    device→host sync, a Python branch on a traced value is a trace error (or
    a retrace per value when made static), and a closure over mutable module
    state bakes stale values into the compiled program. An unhashable or
    float-typed static argument retraces per call / per distinct value —
    a 100x perf cliff tier-1 latency tests cannot see.
  * **wire exhaustiveness** — query/wire.py's tagged-binary result codec must
    enumerate the same envelope tags on the encode and decode side, bound
    plan nesting by ONE shared constant on both sides, and every typed query
    error must be classified by the HTTP dispatch table (http/api.py) so a
    peer failure maps to the right status code instead of a bare 500.

And the v2 families (PR 5 — the ingest plane is thread/socket-heavy):

  * **resource lifecycle** — every acquired thread/server/socket/file needs
    a shutdown story on ALL CFG paths: started threads are daemon or
    joined, ``serve_forever`` servers get shutdown+join, worker loops fail
    loud instead of dying silently (analysis/resourcecheck.py).
  * **except-flow** — broad handlers must not silently swallow
    (``filodb_swallowed_errors`` is the observable alternative), must not
    degrade the typed QueryError protocol the HTTP layer classifies, and
    must restore claimed two-phase-commit state (analysis/exceptcheck.py).
  * **declared surface** — every dotted config key lives in
    config.py::CONFIG_SPEC, every filodb_* metric name is a declared
    constant in utils/metrics.py::METRICS_SPEC, and the README tables are
    generated from those dicts (analysis/surfacecheck.py).

Everything is pure ``ast`` — no jax import, no device, safe under
``JAX_PLATFORMS=cpu`` and in CI. Findings are suppressible inline with
an inline ``filolint: ignore[<rule>]`` comment on the flagged line, or via the checked-in
baseline file (``filolint_baseline.json`` at the repo root, one entry per
intentionally-kept finding with a reason).

Run it:

    python -m filodb_tpu.analysis            # analyze filodb_tpu/, exit 1 on new findings
    python scripts/filolint.py               # same, with per-rule summary
    pytest tests/test_static_analysis.py     # tier-1 self-enforcement

See ANALYSIS.md for each rule, the invariant behind it, and how to add one.
"""

from .findings import Baseline, Finding, load_suppressions
from .runner import ALL_RULES, AnalysisReport, analyze_file, run_analysis

__all__ = [
    "ALL_RULES",
    "AnalysisReport",
    "Baseline",
    "Finding",
    "analyze_file",
    "load_suppressions",
    "run_analysis",
]
