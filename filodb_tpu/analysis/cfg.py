"""Per-function control-flow graph + all-paths release analysis.

The resource-lifecycle rules need path sensitivity the lexical checkers
never had: "this file handle is closed" is not a fact about the function,
it is a fact about every path from the ``open()`` to the function exit —
including the exceptional ones. The CFG here is statement-granular and
deliberately small:

  * nodes are statements; EXIT is a synthetic sink;
  * ``if``/``while``/``for`` contribute both arms (loops: body + skip +
    back edge; ``break``/``continue`` resolve to the innermost loop);
  * ``return`` / ``raise`` route through every enclosing ``finally`` block
    (inner to outer) and then to EXIT;
  * inside a ``try`` body, every statement that contains a call (or other
    raise-capable expression) gets an edge to each handler entry and to
    the ``finally`` entry — the "any statement may raise" approximation;
  * OUTSIDE any try, a raise-capable statement gets an edge toward the
    enclosing ``finally`` chain and EXIT, so an unguarded exception path
    is visible to the analysis.

``releases_on_all_paths`` then answers the rule's question directly: from
the acquire statement, can EXIT be reached without passing a release
statement?  Over-approximated paths (a finally entered from contexts that
cannot mix) can only produce false *findings*, never false silence, and
in practice the repo's release idioms (``with``, ``try/finally``) are
exactly the shapes the approximation models faithfully.
"""

from __future__ import annotations

import ast

EXIT = -1


def _may_raise_stmt(stmt: ast.stmt) -> bool:
    """Can evaluating this statement plausibly raise? Calls, subscripts and
    attribute loads are the realistic sources; constants/pass/simple name
    rebinds are not."""
    for node in ast.walk(stmt):
        if isinstance(node, (ast.Call, ast.Subscript, ast.BinOp, ast.Raise)):
            return True
        if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            return True
    return False


class CFG:
    def __init__(self):
        self.stmts: list[ast.stmt] = []
        self.succ: dict[int, set[int]] = {EXIT: set()}
        # exceptional edges kept separate: the must-release query ignores
        # them for the ACQUIRE node itself (if the acquisition raises, the
        # resource was never acquired) but follows them everywhere else
        self.exc_succ: dict[int, set[int]] = {}

    def _node(self, stmt: ast.stmt) -> int:
        idx = len(self.stmts)
        self.stmts.append(stmt)
        self.succ[idx] = set()
        self.exc_succ[idx] = set()
        return idx

    def _link(self, frm: set[int], to: int) -> None:
        for f in frm:
            self.succ[f].add(to)

    def node_of(self, stmt: ast.stmt) -> int | None:
        for i, s in enumerate(self.stmts):
            if s is stmt:
                return i
        return None


_BROAD = {"Exception", "BaseException"}


def _frame_is_terminal(handlers: list) -> bool:
    """Does some handler in this try frame catch EVERYTHING (bare except /
    Exception / BaseException)? Only then can an exception not continue
    outward."""
    for h in handlers:
        t = h.type
        if t is None:
            return True
        names = t.elts if isinstance(t, ast.Tuple) else [t]
        for n in names:
            leaf = n.attr if isinstance(n, ast.Attribute) else \
                (n.id if isinstance(n, ast.Name) else None)
            if leaf in _BROAD:
                return True
    return False


class _Builder:
    def __init__(self):
        self.g = CFG()
        # innermost-first stacks
        self._loops: list[tuple[set[int], int]] = []   # (break-outs, head)
        # (handler entry nodes, frame catches-everything?) per enclosing try
        self._handlers: list[tuple[list[int], bool]] = []
        self._finals: list[int] = []                   # finally entry nodes

    # every raise-capable stmt gets edges to the active handler entries of
    # EVERY enclosing frame up to (and including) the first terminal one —
    # an exception of a type a frame doesn't catch continues outward; with
    # no terminal frame it escapes through the finally chain to EXIT
    def _exceptional_edges(self, idx: int,
                           edges: dict | None = None) -> None:
        edges = self.g.exc_succ if edges is None else edges
        for entries, terminal in reversed(self._handlers):
            for entry in entries:
                edges[idx].add(entry)
            if terminal:
                return
        for entry in reversed(self._finals):
            edges[idx].add(entry)
            return
        edges[idx].add(EXIT)

    def _to_exit(self, frm: set[int]) -> None:
        """Route a frontier through enclosing finally blocks, then EXIT."""
        for entry in reversed(self._finals):
            self.g._link(frm, entry)
            return      # the finally subgraph's own exits continue the chain
        self.g._link(frm, EXIT)

    def seq(self, stmts: list[ast.stmt], frontier: set[int]) -> set[int]:
        for stmt in stmts:
            frontier = self.stmt(stmt, frontier)
            if not frontier:
                break               # unreachable tail
        return frontier

    def stmt(self, stmt: ast.stmt, frontier: set[int]) -> set[int]:
        g = self.g
        if isinstance(stmt, ast.If):
            n = g._node(stmt)
            g._link(frontier, n)
            out = self.seq(stmt.body, {n})
            out |= self.seq(stmt.orelse, {n}) if stmt.orelse else {n}
            return out
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            n = g._node(stmt)
            g._link(frontier, n)
            breaks: set[int] = set()
            self._loops.append((breaks, n))
            body_out = self.seq(stmt.body, {n})
            self._loops.pop()
            g._link(body_out, n)                      # back edge
            out = {n} | breaks
            out |= self.seq(stmt.orelse, {n}) if stmt.orelse else set()
            return out
        if isinstance(stmt, ast.Break):
            n = g._node(stmt)
            g._link(frontier, n)
            if self._loops:
                self._loops[-1][0].add(n)
            return set()
        if isinstance(stmt, ast.Continue):
            n = g._node(stmt)
            g._link(frontier, n)
            if self._loops:
                g.succ[n].add(self._loops[-1][1])
            return set()
        if isinstance(stmt, ast.Raise):
            n = g._node(stmt)
            g._link(frontier, n)
            # a raise DEFINITELY transfers control: route through every
            # enclosing non-terminal handler frame (normal edges — the
            # must-release query must always follow them)
            self._exceptional_edges(n, edges=g.succ)
            return set()
        if isinstance(stmt, ast.Return):
            n = g._node(stmt)
            g._link(frontier, n)
            self._to_exit({n})
            return set()
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            n = g._node(stmt)
            g._link(frontier, n)
            if _may_raise_stmt(stmt):
                self._exceptional_edges(n)
            return self.seq(stmt.body, {n})
        if isinstance(stmt, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            return self._try(stmt, frontier)
        # simple statement
        n = g._node(stmt)
        g._link(frontier, n)
        if _may_raise_stmt(stmt):
            self._exceptional_edges(n)
        return {n}

    def _try(self, stmt: ast.Try, frontier: set[int]) -> set[int]:
        g = self.g
        fin_entry = None
        fin_out: set[int] = set()
        if stmt.finalbody:
            # the finally block is built TWICE — one copy per entry context.
            # This (exceptional) copy is what raise statements and implicit
            # exception edges route into; after it runs, the in-flight
            # exception CONTINUES outward (outer frames / EXIT), never into
            # the code after the try. A separate normal-flow copy is built
            # below, so the two contexts can't contaminate each other's
            # paths (a shared copy gave the normal path a phantom EXIT edge)
            fin_entry = g._node(stmt.finalbody[0])
            fin_out = self.seq(stmt.finalbody[1:], {fin_entry})
            self._finals.append(fin_entry)
        handler_entries: list[int] = []
        handler_nodes: list[tuple[ast.ExceptHandler, int]] = []
        for h in stmt.handlers:
            entry = g._node(h)
            handler_entries.append(entry)
            handler_nodes.append((h, entry))
        if handler_entries:
            self._handlers.append((handler_entries,
                                   _frame_is_terminal(stmt.handlers)))
        body_out = self.seq(stmt.body, frontier)
        if handler_entries:
            self._handlers.pop()
        out: set[int] = set()
        for h, entry in handler_nodes:
            h_out = self.seq(h.body, {entry})
            # an exception inside a handler propagates outward
            out |= h_out
        body_out = self.seq(stmt.orelse, body_out) if stmt.orelse \
            else body_out
        out |= body_out
        if fin_entry is not None:
            self._finals.pop()
            # the exceptional copy's exit continues the in-flight exception
            # outward (definite transfer: normal edges, like a raise)
            for n in sorted(fin_out or {fin_entry}):
                self._exceptional_edges(n, edges=g.succ)
            # normal-flow copy: body/handler completions run it, then
            # control proceeds to the statements after the try
            fin_entry_norm = g._node(stmt.finalbody[0])
            fin_out_norm = self.seq(stmt.finalbody[1:], {fin_entry_norm})
            g._link(out, fin_entry_norm)
            out = fin_out_norm or {fin_entry_norm}
        return out


def build_cfg(fn: ast.AST) -> CFG:
    b = _Builder()
    out = b.seq(getattr(fn, "body", []), set())
    b.g._link(out, EXIT)
    return b.g


def releases_on_all_paths(cfg: CFG, acquire_idx: int, release) -> bool:
    """True iff every CFG path from ``acquire_idx`` to EXIT passes a
    statement for which ``release(stmt)`` is True. The acquire node's OWN
    exceptional edge is excluded — if the acquisition raises, there is
    nothing to release — but every later node's exceptional edges count."""
    seen = set()
    todo = list(cfg.succ.get(acquire_idx, ()))
    while todo:
        n = todo.pop()
        if n in seen:
            continue
        seen.add(n)
        if n == EXIT:
            return False
        if release(cfg.stmts[n]):
            continue
        todo.extend(cfg.succ.get(n, ()))
        todo.extend(cfg.exc_succ.get(n, ()))
    return True


def dominated_from_entry(cfg: CFG, idx: int, pred) -> bool:
    """True iff every CFG path from function ENTRY to ``idx`` passes a
    statement for which ``pred(stmt)`` is True — classical dominance of a
    predicate over ``idx``. Walks forward from node 0 (the first statement
    is always node 0: the builder numbers statements in visit order),
    stopping at pred-satisfying nodes; if ``idx`` is still reachable, some
    path avoids the predicate."""
    if not cfg.stmts:
        return False
    if pred(cfg.stmts[0]) and idx != 0:
        return True
    if idx == 0:
        return False
    seen = {0}
    todo = list(cfg.succ.get(0, ())) + list(cfg.exc_succ.get(0, ()))
    while todo:
        n = todo.pop()
        if n in seen or n == EXIT:
            continue
        seen.add(n)
        if n == idx:
            return False
        if pred(cfg.stmts[n]):
            continue
        todo.extend(cfg.succ.get(n, ()))
        todo.extend(cfg.exc_succ.get(n, ()))
    return True


def backedge_dominated(cfg: CFG, head: int, pred) -> bool:
    """True iff every CFG path from the loop statement at ``head`` BACK to
    itself passes a statement for which ``pred(stmt)`` is True — the
    value-flow question behind bounded-retry checking: an attempt-count
    guard (``if attempt >= max: raise``) bounds the loop only when the
    guard is evaluated on every iteration, i.e. it dominates the back
    edge. Paths that LEAVE the loop (break / return / the loop-exit
    continuation) never re-reach ``head`` and are vacuously fine. A loop
    whose back edge is unreachable (every iteration returns or raises)
    is vacuously dominated."""
    seen: set = set()
    todo = list(cfg.succ.get(head, ())) + list(cfg.exc_succ.get(head, ()))
    while todo:
        n = todo.pop()
        if n == head:
            return False            # completed an iteration pred-free
        if n in seen or n == EXIT:
            continue
        seen.add(n)
        if pred(cfg.stmts[n]):
            continue
        todo.extend(cfg.succ.get(n, ()))
        todo.extend(cfg.exc_succ.get(n, ()))
    return True


def guarded_between(cfg: CFG, frm: int, target_pred, guard_pred) -> bool:
    """True iff every CFG path from ``frm`` to a target-matching statement
    passes a guard statement first — the deadline-bounds-the-socket query:
    from the socket's creation, every path to its first blocking op must
    cross a ``settimeout``. Unreachable targets are vacuously guarded."""
    seen: set = set()
    todo = list(cfg.succ.get(frm, ())) + list(cfg.exc_succ.get(frm, ()))
    while todo:
        n = todo.pop()
        if n in seen or n == EXIT:
            continue
        seen.add(n)
        if guard_pred(cfg.stmts[n]):
            continue
        if target_pred(cfg.stmts[n]):
            return False
        todo.extend(cfg.succ.get(n, ()))
        todo.extend(cfg.exc_succ.get(n, ()))
    return True


def covered_on_all_paths(cfg: CFG, idx: int, pred) -> bool:
    """True iff the statement at ``idx`` is *fenced* by the predicate: every
    path from ENTRY to ``idx`` passes a pred statement, OR every path from
    ``idx`` to EXIT does. This is the epoch-bump coverage query — a
    visibility mutation is safe whether the bump precedes it (flush stages:
    bump, then scatter) or follows it (compaction: compact, then bump), as
    long as both run under one lock hold. Mixed coverage (some paths fenced
    before, the rest after) is deliberately NOT accepted: it would be
    correct only if no path exists that misses both, and proving that
    needs a per-path product the repo's idioms never require — the
    over-approximation can only produce findings, never silence."""
    return dominated_from_entry(cfg, idx, pred) \
        or releases_on_all_paths(cfg, idx, pred)
