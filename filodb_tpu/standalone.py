"""Standalone server: config -> cluster -> shards -> ingestion -> HTTP.

Reference: standalone/.../FiloServer.scala:15-38 (bootstraps the cluster, creates
datasets from config, starts HTTP) + coordinator/.../IngestionActor.scala:57
(per-shard ingestion lifecycle: resync on shard assignment, recovery from
checkpoints, then live consumption with status events).
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time

import numpy as np

from .config import Config, parse_duration_ms
from .core.memstore import TimeSeriesMemStore
from .core.store import FileColumnStore
from .http.api import FiloHttpServer
from .ingest.bus import FileBus
from .parallel.cluster import ShardManager, ShardStatus
from .parallel.shardmapper import ShardMapper
from .query.engine import QueryEngine
from .query.rangevector import QueryError
from .utils.metrics import (FILODB_INGEST_DECODE_ERRORS,
                            FILODB_INGESTED_ROWS, FILODB_SWALLOWED_ERRORS,
                            ShardHealthStats, registry)
from .utils.tracing import SPAN_INGEST_CONSUME, span, tracer

log = logging.getLogger("filodb_tpu.server")


class _DecodeAhead:
    """Double-buffered container decode: a daemon thread pulls (offset,
    container) pairs from the bus iterator into a bounded queue, so the
    host-side decode (network read + ``RecordContainer.from_bytes``) of batch
    N+1 overlaps the shard's device scatter of batch N. Offsets are committed
    by the CONSUMER after ingest exactly as before — decoded-but-undelivered
    containers are simply re-fetched after a fault, so checkpoint/durability
    semantics are unchanged."""

    _END = object()

    def __init__(self, it, depth: int = 2):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, int(depth)))
        self._err: BaseException | None = None
        self._closed = False
        self._thread = threading.Thread(target=self._fill, args=(it,),
                                        daemon=True, name="ingest-decode")
        self._thread.start()

    def _fill(self, it) -> None:
        try:
            for item in it:
                while not self._closed:
                    try:
                        self._q.put(item, timeout=0.5)
                        break
                    except queue.Full:
                        continue
                if self._closed:
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised on the consumer
            # fail LOUD: the consumer re-raises on its next __next__, and the
            # counter makes a recurring decode fault visible even when the
            # consumer's retry loop keeps absorbing it
            self._err = e
            registry.counter(FILODB_INGEST_DECODE_ERRORS).increment()
        while not self._closed:
            try:
                self._q.put(self._END, timeout=0.5)
                return
            except queue.Full:
                continue

    def __iter__(self):
        return self

    def __next__(self):
        # timed get + liveness check: _fill guarantees the _END sentinel on
        # every normal exit path, but a fill thread killed uncleanly (or a
        # bug there) must not park this consumer forever on a bare get
        # (filolint: live-wait-no-timeout)
        while True:
            try:
                item = self._q.get(timeout=1.0)
            except queue.Empty:
                if not self._thread.is_alive():
                    if self._err is not None:
                        raise self._err
                    raise StopIteration
                continue
            if item is self._END:
                if self._err is not None:
                    raise self._err
                raise StopIteration
            return item

    def close(self) -> None:
        """Unblock and retire the fill thread after an early exit."""
        self._closed = True
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass


class IngestionConsumer(threading.Thread):
    """Per-shard bus consumer (ref: IngestionActor drives memStore.ingestStream /
    recoverStream with RecoveryInProgress -> IngestionStarted events)."""

    def __init__(self, shard, bus: FileBus, schemas, manager: ShardManager,
                 dataset: str, poll_s: float = 0.5, purge_interval_s: float = 600.0,
                 decode_ahead: int = 2, accept=None):
        super().__init__(daemon=True, name=f"ingest-{dataset}-{shard.shard_num}")
        self.shard = shard
        self.bus = bus
        self.schemas = schemas
        self.manager = manager
        self.dataset = dataset
        self.poll_s = poll_s
        self.purge_interval_s = purge_interval_s
        self.decode_ahead = decode_ahead
        # shared-partition demux: with fewer broker partitions than shards
        # several shards replay one partition; ``accept(container)`` keeps
        # only this shard's containers (offsets still advance past skips)
        self.accept = accept
        self._stop_ev = threading.Event()
        self._offset = 0

    def _seed_downsampler(self, sh) -> None:
        """Resume the streaming downsampler after recovery: the durable
        publish floor comes from the fine family's meta, and buckets left
        open across the restart rebuild from the recovered store (replay
        alone would re-emit them with only post-watermark samples)."""
        if sh.downsample is None:
            return
        target = sh.downsample[1]
        if not hasattr(target, "seed_from_store"):
            return
        pub = target.publish
        floor = -1
        sink = getattr(pub, "sink", None)
        fam = getattr(pub, "family", None)
        if sink is not None and fam and hasattr(sink, "read_meta"):
            floor = int(sink.read_meta(fam, sh.shard_num)
                        .get("published_through", -1))
        target.floor_ms = floor
        if floor >= 0 and hasattr(pub, "published_max"):
            pub.published_max[sh.shard_num] = floor
        target.seed_from_store(sh)

    def run(self):
        sh = self.shard
        try:
            # recovery prelude retries transient bus outages too — a broker
            # restarting while we start must not permanently kill the shard
            backoff = 0.0
            while True:
                try:
                    if sh.sink is not None:
                        self.manager.set_status(self.dataset, sh.shard_num,
                                                ShardStatus.RECOVERY)
                        sh.recover(self.bus, self.schemas,
                                   on_chunks_loaded=lambda: self._seed_downsampler(sh),
                                   accept=self.accept)
                        # resume at the offset replay actually reached —
                        # reading end_offset here instead would skip frames
                        # published between the replay's end snapshot and
                        # this line (a real gap on a shard adopted under
                        # live publish load)
                        self._offset = int(getattr(sh, "recovered_through",
                                                   self.bus.end_offset))
                    break
                except (ConnectionError, OSError):
                    backoff = min(max(1.0, backoff * 2), 30.0)
                    log.warning("bus unavailable for shard %s recovery; "
                                "retrying in %.0fs", sh.shard_num, backoff)
                    self.manager.set_status(self.dataset, sh.shard_num,
                                            ShardStatus.ERROR)
                    if self._stop_ev.wait(backoff):
                        return
            self.manager.set_status(self.dataset, sh.shard_num, ShardStatus.ACTIVE)
            rows = registry.counter(FILODB_INGESTED_ROWS,
                                    {"dataset": self.dataset, "shard": str(sh.shard_num)})
            last_purge = time.monotonic()
            backoff = 0.0
            while not self._stop_ev.wait(backoff or self.poll_s):
                # transient bus outages (e.g. a broker restart) must not kill
                # the shard: back off and retry, ERROR only while disconnected
                # (ref: IngestionError events -> resync, not actor death).
                # Only network faults count as transient — a broker-reported
                # error (RuntimeError, e.g. bad partition) or an ingest fault
                # is permanent and fails the shard loudly via the outer handler
                try:
                    src = self.bus.consume(self.schemas, self._offset)
                    # peek before spinning up the decode thread: an idle
                    # poll (the common case) must not create a thread
                    first = next(src, None)
                    if first is not None:
                        if self.decode_ahead:
                            src = _DecodeAhead(src, self.decode_ahead)
                        # one span per consumer DRAIN (not per container):
                        # the scatter leg of the ingest path, tagged with
                        # how much it moved
                        n_rows = 0
                        try:
                            with span(SPAN_INGEST_CONSUME,
                                      dataset=self.dataset,
                                      shard=sh.shard_num) as tags:
                                for off, container in itertools.chain(
                                        [first], src):
                                    if self.accept is None or \
                                            self.accept(container):
                                        sh.ingest(container, off)
                                        rows.increment(len(container))
                                        n_rows += len(container)
                                    self._offset = off + 1
                                tags["rows"] = n_rows
                        finally:
                            if isinstance(src, _DecodeAhead):
                                src.close()
                except (ConnectionError, OSError):
                    backoff = min(max(1.0, backoff * 2), 30.0)
                    log.warning("bus unavailable for shard %s; retrying in %.0fs",
                                sh.shard_num, backoff)
                    self.manager.set_status(self.dataset, sh.shard_num,
                                            ShardStatus.ERROR)
                    continue
                if backoff:
                    backoff = 0.0
                    self.manager.set_status(self.dataset, sh.shard_num,
                                            ShardStatus.ACTIVE)
                sh.flush()
                if sh.sink is not None:
                    sh.flush_all_groups()
                if time.monotonic() - last_purge >= self.purge_interval_s:
                    last_purge = time.monotonic()
                    lead = int(sh.store.last_ts.max(initial=0)) if sh.store is not None else 0
                    if lead > 0:
                        n = sh.purge_expired_partitions(lead - sh.config.retention_ms)
                        if n:
                            log.info("purged %d expired partitions from shard %d",
                                     n, sh.shard_num)
        except Exception:  # noqa: BLE001
            log.exception("ingestion failed for shard %s", sh.shard_num)
            self.manager.set_status(self.dataset, sh.shard_num, ShardStatus.ERROR)

    def stop(self):
        self._stop_ev.set()


class FiloServer:
    def __init__(self, config: Config | None = None, node_name: str = "local"):
        self.config = config or Config()
        self.node = node_name
        self.memstore = TimeSeriesMemStore()
        self.manager = ShardManager()
        self.manager.add_node(node_name)
        self.consumers: list[IngestionConsumer] = []
        self.http: FiloHttpServer | None = None
        self.gateway = None
        self._gw_buses: dict[int, object] = {}
        self._gw_flush_stop: threading.Event | None = None
        self.scheduler = None
        self.engines: dict[str, QueryEngine] = {}
        self.rules = None
        self._rules_buses: dict[int, object] = {}
        self.profiler = None
        self.membership = None
        self.gossip = None              # cluster/membership.py GossipAgent
        self.failures = None            # buddy-routing FailureProvider
        self._fence = None              # cluster/epoch.py StoreFence
        self.last_failover: dict = {}   # operator surface: the most recent
        # node-down / takeover / rebalance event on this node
        self._registrar = None
        self._running: set[int] = set()
        self._buses: dict[int, object] = {}
        self._quarantined = False
        # guards _running/_buses/consumers/_quarantined: mutated by the
        # membership-monitor thread (resync/quarantine) while HTTP writers
        # snapshot them and resync events start consumers
        self._shards_lock = threading.Lock()
        self._sink = None
        self._store_cfg = None
        self._ds_publish = None
        self._ds_res: list[int] = []
        self._cascade_stop = None
        self._cascade_wm: dict[int, int] = {}
        self._ds_serve_stop = None
        self._retention_stop = None
        self._endpoints: dict[str, str] = {}
        self._endpoints_at = 0.0
        self._zipkin = None

    def _start_shard(self, dataset: str, shard_num: int) -> None:
        """Bring up one owned shard: store + (optionally) its bus consumer
        (ref: IngestionActor.startIngestion per assigned shard)."""
        # claim the shard atomically: a resync event racing quarantine (or a
        # duplicate event) must not start a consumer that quarantine already
        # stopped — or never saw
        with self._shards_lock:
            if self._quarantined or shard_num in self._running:
                return
            self._running.add(shard_num)
        try:
            self._start_shard_claimed(dataset, shard_num)
        except Exception:
            # a failed start (disk error, broker refused) releases the claim
            # so a later resync can retry — a leaked claim would silently
            # no-op every retry and accept writes for a shard with no store
            with self._shards_lock:
                self._running.discard(shard_num)
            raise

    def _shard_device(self, shard_num: int):
        """Mesh placement: with multiple local devices, shard stores go
        round-robin so aggregate queries can execute via shard_map/psum
        (the reference's per-shard data nodes; here devices ARE the nodes)."""
        try:
            import jax
            devs = jax.devices()
        except Exception:  # noqa: BLE001 — no usable backend: single-device
            # placement is the correct fallback, but count the probe failure
            # so a mis-provisioned multi-chip node is visible in /metrics
            registry.counter(FILODB_SWALLOWED_ERRORS,
                             {"site": "shard-device-probe"}).increment()
            return None
        return devs[shard_num % len(devs)] if len(devs) > 1 else None

    def _start_shard_claimed(self, dataset: str, shard_num: int) -> None:
        cfg = self.config
        try:
            shard = self.memstore.setup(dataset, cfg["schema"], shard_num,
                                        self._store_cfg, sink=self._sink,
                                        device=self._shard_device(shard_num))
        except ValueError:
            # a retried start after a partial failure: the store exists
            shard = self.memstore.shard(dataset, shard_num)
        # cardinality governance + durable index time buckets, wired BEFORE
        # the consumer starts (recovery adopts tenants and reads index.log)
        shard.governor = self._governor
        shard.index_bucket_ms = self._index_bucket_ms
        if self._fence is not None:
            # epoch-fence the store ring BEFORE the consumer starts: our
            # claim supersedes any deposed owner's, and its straggler
            # flushes now raise FencedWriteError instead of corrupting the
            # shard we are warming
            self._fence.claim(shard_num)
        if self._ds_publish is not None and not shard.schema.is_histogram:
            from .core.downsample import InlineDownsampler
            shard.downsample = (self._ds_res[0],
                                InlineDownsampler(self._ds_res[0],
                                                  self._ds_publish))
        if self._bus_addrs() or cfg.get("bus_dir"):
            accept = None
            if self._bus_addrs():
                # remote broker: shard N consumes partition N mod
                # ingest.partitions (ref: Kafka PartitionStrategy; the
                # default keeps 1 shard == 1 partition). With shared
                # partitions each consumer keeps only its own shard's
                # containers, re-deriving the shard from the container's
                # hashes (gateway containers are single-shard by build).
                from .ingest.broker import BrokerBus
                parts = self._num_partitions()
                bus = BrokerBus(self._bus_addrs(), shard_num % parts,
                                publish_window=cfg.get("ingest.publish_window",
                                                       64),
                                retry_backoff_ms=parse_duration_ms(
                                    cfg["ingest.retry_backoff"]),
                                max_retries=cfg["ingest.publish_retries"],
                                epoch_fencing=cfg["ingest.epoch_fencing"])
                if parts < len(self.manager.map[dataset]):
                    accept = self._shard_accept(shard_num)
            else:
                bus = FileBus(f"{cfg['bus_dir']}/shard{shard_num}.log")
            c = IngestionConsumer(shard, bus, self.memstore.schemas,
                                  self.manager, dataset,
                                  purge_interval_s=parse_duration_ms(
                                      cfg.get("store.purge_interval", "10m")) / 1000.0,
                                  decode_ahead=cfg.get("ingest.decode_ahead", 2),
                                  accept=accept)
            with self._shards_lock:
                if self._quarantined:       # raced quarantine: do not start
                    self._running.discard(shard_num)
                    return
                self._buses[shard_num] = bus
                self.consumers.append(c)
            c.start()
        else:
            self.manager.set_status(dataset, shard_num, ShardStatus.ACTIVE)

    def _bus_addrs(self) -> list[str]:
        """Broker replica addresses: ``bus_addrs`` (the replicated tier) or
        the single legacy ``bus_addr``."""
        cfg = self.config
        addrs = cfg.get("bus_addrs")
        if addrs:
            return list(addrs)
        return [cfg["bus_addr"]] if cfg.get("bus_addr") else []

    def _num_partitions(self) -> int:
        cfg = self.config
        return int(cfg.get("ingest.partitions")
                   or _pow2(cfg["num_shards"]))

    def _make_shard_buses(self, num_shards: int) -> dict[int, object]:
        """Per-shard PUBLISH buses over the configured ingest plane —
        BrokerBus against the replicated broker tier (shard s publishes to
        partition s mod partitions) or FileBus per shard; empty when
        neither is configured (callers then ingest directly). One
        construction shared by the gateway and rules publishers so their
        wiring can never drift."""
        cfg = self.config
        if self._bus_addrs():
            from .ingest.broker import BrokerBus
            parts = self._num_partitions()
            return {s: BrokerBus(self._bus_addrs(), s % parts,
                                 publish_window=cfg["ingest.publish_window"],
                                 retry_backoff_ms=parse_duration_ms(
                                     cfg["ingest.retry_backoff"]),
                                 max_retries=cfg["ingest.publish_retries"],
                                 epoch_fencing=cfg["ingest.epoch_fencing"])
                    for s in range(num_shards)}
        if cfg.get("bus_dir"):
            return {s: FileBus(f"{cfg['bus_dir']}/shard{s}.log")
                    for s in range(num_shards)}
        return {}

    def _shard_accept(self, shard_num: int):
        """Demux predicate for shared broker partitions: keep containers
        whose (single-shard, by gateway build) records route to this
        shard."""
        cfg = self.config
        mapper = ShardMapper(_pow2(cfg["num_shards"]), cfg["spread"])

        def accept(container, _s=shard_num, _m=mapper):
            if not len(container.ts):
                return False
            return _m.shard_of(int(container.shard_hash[0]),
                               int(container.part_hash[0])) == _s
        return accept

    def _resolve_endpoint(self, node: str) -> str | None:
        """HTTP endpoint of a peer node, from registrar heartbeats (each node
        publishes its own with MembershipMonitor.http_addr). A short TTL cache
        keeps per-query registrar reads off the query path."""
        if self._registrar is None or not hasattr(self._registrar, "endpoints"):
            return None
        now = time.monotonic()
        if now - self._endpoints_at > 1.0:
            try:
                self._endpoints = self._registrar.endpoints()
                self._endpoints_at = now
            except Exception:
                log.exception("registrar endpoint read failed")
        return self._endpoints.get(node)

    def _quarantine(self) -> None:
        """Our heartbeat lapsed past stale_after: peers have declared us dead
        and reassigned our shards, so continuing to consume would double-own
        them. Fail-stop ingestion; an operator restart rejoins cleanly
        (ref: Akka quarantine — a removed-but-alive node must restart)."""
        log.error("node %s quarantined (heartbeat lapsed); stopping ingestion — "
                  "restart to rejoin", self.node)
        with self._shards_lock:
            self._quarantined = True        # no further _start_shard succeeds
            consumers = list(self.consumers)
            stopped = sorted(self._running)
            self._running.clear()
            buses = list(self._buses.values())
            self._buses.clear()
        for c in consumers:
            c.stop()                # flag FIRST: a woken consumer exits
        for b in buses:
            try:
                b.close()           # unblocks any consumer mid-recv
            except OSError:
                log.warning("bus close failed during quarantine",
                            exc_info=True)
        for c in consumers:
            c.join(timeout=3)
        for b in buses:
            try:
                b.close()           # re-sever: a consumer that raced the
                                    # first close and reconnected is now
                                    # joined, so this one sticks
            except OSError:
                log.warning("bus close failed during quarantine",
                            exc_info=True)
        if self._fence is not None:
            # drop our store-ring claims: any straggler flush thread now
            # fences locally without even a durable read
            for s in stopped:
                self._fence.release(s)
        for ds in list(self.engines):
            if ds not in self.manager.map:
                continue       # downsample-family serving view, not a dataset
            for s in stopped:
                if self.manager.node_of(ds, s) == self.node:
                    self.manager.set_status(ds, s, ShardStatus.STOPPED)

    def _on_shard_event(self, ev) -> None:
        """Resync (ref: IngestionActor.resync on shard snapshots): an
        assignment targeting this node starts the shard's consumer."""
        if ev.kind == "AssignmentStarted" and ev.node == self.node \
                and ev.shard not in self._running:
            log.info("resync: starting reassigned shard %s", ev.shard)
            self._start_shard(ev.dataset, ev.shard)
            if self.membership is not None:
                # publish the takeover immediately: a node joining right now
                # must see the updated ownership claims
                self.membership.publish_now()

    # -- elastic cluster (membership, fencing, rebalance — cluster/) ---------

    def _peer_down(self, node: str) -> None:
        """A peer was declared dead (registrar staleness or gossip counted
        suspicion): reassign its shards and open a known-bad window so
        buddy routing covers the takeover gap."""
        if node not in self.manager.nodes:
            return                      # both detectors fired: already done
        self.manager.remove_node(node)
        if self.failures is not None:
            self.failures.open_window(f"node-{node}",
                                      int(time.time() * 1000))
        self.last_failover = {"event": "node-down", "node": node,
                              "at": time.time()}

    def _peer_up(self, node: str) -> None:
        self.manager.add_node(node)
        if self.failures is not None:
            self.failures.close_window(f"node-{node}",
                                       int(time.time() * 1000))

    def _ha_track(self, ev) -> None:
        """Failure-window bookkeeping for buddy routing: a shard this node
        is warming (takeover/rebalance) is known-bad until its consumer
        reaches ACTIVE, and a dead NODE's window seals once none of its
        shards remain orphaned — a permanently dead node must not steer
        every later query to the buddy forever."""
        if self.failures is None:
            return
        now_ms = int(time.time() * 1000)
        if ev.kind == "AssignmentStarted" and ev.node == self.node:
            self.failures.open_window(f"shard-{ev.dataset}-{ev.shard}",
                                      now_ms)
        elif ev.kind == "IngestionStarted" and ev.node == self.node:
            self.failures.close_window(f"shard-{ev.dataset}-{ev.shard}",
                                       now_ms)
            self._maybe_close_node_windows(now_ms)

    def _maybe_close_node_windows(self, now_ms: int) -> None:
        """Seal every open node-down window once no shard is orphaned
        (DOWN/UNASSIGNED) and this node has no shard still warming: from
        here the cluster serves complete data again, and the closed range
        keeps routing around the actual outage. Claims reconciliation
        calls this too, so non-adopting nodes converge as peers' takeovers
        publish."""
        if self.failures is None:
            return
        for shards in self.manager.map.values():
            for _s, (_n, st) in shards.items():
                if st in (ShardStatus.DOWN, ShardStatus.UNASSIGNED):
                    return
        for key in list(self.failures.open_windows()):
            if key.startswith("node-"):
                self.failures.close_window(key, now_ms)

    def _adopt_claims(self, peer: str, claims: dict) -> None:
        """Reconcile a peer's published shard claims into our map: after a
        rebalance cutover (or takeover we did not witness), every node
        converges on the new ownership without a restart. Shards we run
        live are never ceded here — losing one goes through quarantine."""
        for ds, shards in (claims or {}).items():
            if ds not in self.manager.map:
                continue
            for s in shards:
                s = int(s)
                if not 0 <= s < len(self.manager.map[ds]):
                    continue
                cur = self.manager.node_of(ds, s)
                if cur == peer:
                    continue
                with self._shards_lock:
                    mine = s in self._running
                if cur == self.node and mine:
                    continue
                self.manager.reassign(ds, s, peer)
        if self.failures is not None:
            # peers' published takeovers count toward sealing node-down
            # windows on nodes that adopted nothing themselves
            self._maybe_close_node_windows(int(time.time() * 1000))

    def _cluster_extra(self) -> dict:
        """The elasticity surface of GET /api/v1/cluster/status: gossip
        membership table, per-scope epochs, open known-bad windows, and
        the last failover/rebalance event on this node."""
        out: dict = {"node": self.node}
        if self.gossip is not None:
            out["membership"] = self.gossip.table.rows()
        if self._fence is not None:
            out["epochs"] = {"shards": {str(s): e for s, e
                                        in self._fence.owned().items()}}
        if self.failures is not None:
            out["known_bad_windows"] = self.failures.open_windows()
        if self.last_failover:
            out["last_failover"] = self.last_failover
        return out

    def rebalance_shard(self, dataset: str, shard: int, to_node: str) -> dict:
        """Operator-triggered live shard move (flush→handoff→catch-up→
        cutover). This node must own the shard; ``to_node`` warms it from
        the durable ring + broker replay and takes over ingest. The move
        is epoch-fenced: the adopter's store-ring claim supersedes ours
        before its consumer starts, so exactly one owner ever ingests."""
        import urllib.request

        from .utils.metrics import FILODB_CLUSTER_REBALANCES
        from .utils.tracing import SPAN_CLUSTER_REBALANCE
        shard = int(shard)
        if dataset not in self.manager.map \
                or not 0 <= shard < len(self.manager.map[dataset]):
            raise QueryError(f"unknown dataset/shard {dataset}/{shard}")
        owner = self.manager.node_of(dataset, shard)
        if owner != self.node:
            raise QueryError(
                f"shard {shard} is owned by {owner}, not this node — POST "
                "the rebalance to the owner")
        if to_node == self.node:
            raise QueryError("rebalance target is the current owner")
        ep = self._resolve_endpoint(to_node)
        if ep is None:
            raise QueryError(f"no HTTP endpoint known for node {to_node}")
        with span(SPAN_CLUSTER_REBALANCE, dataset=dataset, shard=shard,
                  to=to_node):
            # 1. pause ingest for the shard: stop its consumer (publishes
            # keep buffering in the broker; the adopter replays the tail)
            with self._shards_lock:
                moving = [c for c in self.consumers
                          if c.dataset == dataset
                          and c.shard.shard_num == shard]
                bus = self._buses.pop(shard, None)
                for c in moving:
                    self.consumers.remove(c)
                self._running.discard(shard)
            for c in moving:
                c.stop()
            if bus is not None:
                bus.close()             # unblocks a consumer mid-recv
            for c in moving:
                c.join(timeout=5)
            # 2. final flush: everything consumed becomes durable and
            # checkpointed — the adopter's recovery resumes exactly there
            sh = self.memstore.shard(dataset, shard)
            sh.flush()
            if sh.sink is not None:
                sh.flush_all_groups()
            # 3. release our fence claim; the adopter's claim supersedes
            if self._fence is not None:
                self._fence.release(shard)
            # 4. cutover: the adopter claims the epoch, warms from the
            # ring, replays the bus tail, and starts consuming
            try:
                req = urllib.request.Request(
                    f"http://{ep}/api/v1/cluster/adopt?dataset={dataset}"
                    f"&shard={shard}", method="POST", data=b"")
                with urllib.request.urlopen(req, timeout=60.0) as r:
                    import json as _json
                    adopted = _json.load(r)
            except (OSError, ValueError) as e:
                # aborted handoff: restart the shard locally (re-claims the
                # fence) so the cluster never has zero owners
                log.warning("rebalance adopt on %s failed; restarting "
                            "shard %s locally: %s", to_node, shard, e)
                self._start_shard(dataset, shard)
                raise QueryError(
                    f"rebalance aborted ({e}); shard restarted locally") \
                    from None
            # 5. flip our map and publish the new ownership
            self.manager.reassign(dataset, shard, to_node)
            if self.membership is not None:
                self.membership.publish_now()
            registry.counter(FILODB_CLUSTER_REBALANCES,
                             {"dataset": dataset}).increment()
            self.last_failover = {"event": "rebalance", "dataset": dataset,
                                  "shard": shard, "from": self.node,
                                  "to": to_node, "at": time.time()}
        return {"dataset": dataset, "shard": shard, "from": self.node,
                "to": to_node, "adopted": adopted.get("data")}

    def adopt_shard(self, dataset: str, shard: int) -> dict:
        """Receiving side of a live rebalance: claim the shard (epoch bump
        via _start_shard's fence claim), warm it from the durable ring,
        replay the broker tail, and start consuming. Idempotent."""
        shard = int(shard)
        if dataset not in self.manager.map \
                or not 0 <= shard < len(self.manager.map[dataset]):
            raise QueryError(f"unknown dataset/shard {dataset}/{shard}")
        with self._shards_lock:
            running = shard in self._running
        if running and self.manager.node_of(dataset, shard) == self.node:
            return {"dataset": dataset, "shard": shard, "node": self.node,
                    "already_owned": True}
        # reassign fires AssignmentStarted -> _on_shard_event starts the
        # consumer (fence claim + ring recovery + bus replay happen there)
        self.manager.reassign(dataset, shard, self.node)
        self.last_failover = {"event": "adopt", "dataset": dataset,
                              "shard": shard, "node": self.node,
                              "at": time.time()}
        return {"dataset": dataset, "shard": shard, "node": self.node}

    def start(self) -> "FiloServer":
        cfg = self.config
        # unconditional: the flag is process-global, so a later server in the
        # same process must be able to turn it back off
        from .utils import diagnostics
        diagnostics.enable(bool(cfg.get("diagnostics.enabled")))
        dataset = cfg["dataset"]
        # shard ids live in a power-of-two space (hash routing, spread); a
        # non-pow2 count would leave routable ids with no owning shard
        num_shards = _pow2(cfg["num_shards"])
        if cfg.get("cluster.registrar"):
            # multi-host join BEFORE shard assignment: wait for min_members in
            # the registrar and seed the manager with the *sorted* member list,
            # so every node computes the identical assignment (the reference
            # avoids this by putting the one ShardManager in a cluster
            # singleton; here determinism replaces the singleton)
            from .parallel.bootstrap import (ClusterBootstrap,
                                             FileRegistrarDiscovery)
            self_addr = cfg.get("cluster.self_addr") or \
                f"{cfg['http.host']}:{cfg['http.port']}"
            self._registrar = FileRegistrarDiscovery(
                cfg["cluster.registrar"],
                stale_s=parse_duration_ms(cfg["cluster.stale_after"]) / 1000.0)
            if cfg["cluster.min_members"] <= 1:
                log.warning(
                    "cluster.registrar is set but cluster.min_members=1: two "
                    "nodes cold-starting concurrently can each resolve a "
                    "single-member world and double-own shards — set "
                    "min_members to the expected cluster size")
            world = ClusterBootstrap(self._registrar, self_addr).resolve_world(
                min_members=cfg["cluster.min_members"],
                timeout_s=parse_duration_ms(cfg["cluster.join_timeout"]) / 1000.0)
            self.manager.nodes.remove(self.node)
            self.node = self_addr
            for m in world.members:
                self.manager.add_node(m)
            # adopt incumbent ownership published in peers' heartbeats: a
            # node (re)joining an established cluster must not recompute a
            # fresh full assignment (the survivors keep their takeover state;
            # ref: the cluster-singleton ShardManager avoids this upstream).
            # Settle one heartbeat first (only when live peers exist) so an
            # in-flight takeover's claims have landed in the registrar.
            if any(m != self_addr for m in self._registrar.discover()):
                time.sleep(
                    parse_duration_ms(cfg["cluster.heartbeat_interval"]) / 1000.0)
            claimed: dict[int, str] = {}
            for peer, peer_claims in self._registrar.claims().items():
                if peer == self_addr:
                    continue
                for s in peer_claims.get(dataset, ()):
                    claimed[int(s)] = peer
            self.manager.add_dataset(dataset, num_shards, claimed=claimed)
        else:
            self.manager.add_dataset(dataset, num_shards)
        if cfg.get("store_nodes"):
            # remote storage nodes with replication (the Cassandra-layer
            # deployment shape; ref: CassandraTSStoreFactory wiring) —
            # links get bounded connect/read timeouts so a dead backend
            # fails over instead of stalling flush/query threads
            from .core.diststore import RemoteStore, ReplicatedColumnStore
            store_tmo = parse_duration_ms(
                cfg["retention.store_timeout"]) / 1000.0
            self._sink = ReplicatedColumnStore(
                [RemoteStore(a, timeout_s=store_tmo,
                             connect_timeout_s=min(store_tmo, 5.0))
                 for a in cfg["store_nodes"]],
                replication=cfg.get("store_replication") or 2)
        else:
            self._sink = FileColumnStore(cfg["data_dir"]) if cfg.get("data_dir") else None
        if cfg.get("cluster.shard_fencing") and self._sink is not None:
            # epoch-fence store-ring writers: each owned shard's leadership
            # epoch persists in the durable ring; a deposed owner's flush or
            # checkpoint raises FencedWriteError (cluster/epoch.py)
            from .cluster.epoch import StoreFence
            self._fence = StoreFence(self._sink, self.node)
            if hasattr(self._sink, "write_guard"):
                self._sink.write_guard = self._fence
        self._store_cfg = cfg.store_config()
        # ingest cardinality governance (index.max_series_per_tenant): ONE
        # governor per dataset shared by every local shard and both ingest
        # edges — shard-level birth checks are authoritative, the edges
        # fast-shed what they can prove is a new over-quota series
        self._governor = None
        if cfg.get("index.max_series_per_tenant") is not None:
            from .core.cardinality import CardinalityGovernor
            self._governor = CardinalityGovernor(
                int(cfg["index.max_series_per_tenant"]),
                tenant_label=cfg["index.tenant_label"], dataset=dataset,
                retry_after_s=parse_duration_ms(
                    cfg["index.quota_retry_after"]) / 1000.0)
        self._index_bucket_ms = (parse_duration_ms(cfg["index.time_bucket"])
                                 if cfg.get("index.persist") else 0)

        def series_known(shard_num: int, labels, _ds=dataset) -> bool:
            """Edge probe: is this label set an EXISTING series of a LOCAL
            shard? Unknown/remote shards answer True (never shed on an
            unprovable probe — the shard-level limiter is authoritative)."""
            from .core.schemas import part_key_of as _pk_of
            try:
                sh = self.memstore.shard(_ds, shard_num)
            except KeyError:
                return True
            pk = _pk_of(dict(labels) if not isinstance(labels, dict)
                        else labels, sh.schema.options)
            with sh.lock:
                return pk in sh._part_key_to_id

        self._series_known = series_known
        health = ShardHealthStats(dataset)
        self.manager.subscribe(lambda ev: health.update(self.manager.snapshot(dataset)))
        # inline downsampling publisher (ref: ShardDownsampler at flush); the
        # first resolution publishes at every group flush, coarser ones
        # cascade periodically below
        if cfg.get("downsample.enabled") and self._sink is not None:
            from .jobs.batch_downsampler import make_inline_publisher
            self._ds_res = [parse_duration_ms(r)
                            for r in cfg["downsample.resolutions"]]
            for fine, coarse in zip(self._ds_res, self._ds_res[1:]):
                if coarse <= fine or coarse % fine:
                    raise ValueError(
                        "downsample.resolutions must ascend and each must be "
                        f"a multiple of the previous; got {cfg['downsample.resolutions']}")
            self._ds_publish = make_inline_publisher(self._sink, dataset,
                                                     self._ds_res[0])
        for shard_num in self.manager.shards_of_node(dataset, self.node):
            self._start_shard(dataset, shard_num)
        self.manager.subscribe(self._on_shard_event)
        mapper = ShardMapper(num_shards, spread=cfg["spread"])
        # shards spread round-robin over local devices (>= 1 per device) =>
        # PromQL aggregates run on the mesh (query/engine.py _try_mesh); any
        # other topology (peer-owned shards, indivisible counts) stays on the
        # in-process / cross-node dispatch paths
        mesh = None
        try:
            import jax
            devs = jax.devices()
            owned = self.manager.shards_of_node(dataset, self.node)
            if (1 < num_shards == len(owned) and len(devs) > 1
                    and num_shards % len(devs) == 0):
                from .parallel.distributed import make_mesh
                mesh = make_mesh(devs)
        except Exception:
            mesh = None
        # cluster + endpoint resolver: leaves for peer-owned shards dispatch
        # over HTTP /exec (query/wire.py RemoteLeafExec) instead of erroring
        self.engines[dataset] = QueryEngine(
            self.memstore, dataset, mapper, cfg.query_config(), mesh=mesh,
            cluster=self.manager, node=self.node,
            endpoint_resolver=self._resolve_endpoint)
        if cfg.get("retention.routing"):
            # downsample-aware routing on the RAW engine: long-range /
            # coarse-step queries serve from the ds_family whose resolution
            # best covers [start,end,step], stitching the recent raw tail
            # (query/retention.py; family engines resolve live from
            # self.engines — the serving refresh below keeps them fresh)
            from .core.downsample import ds_family as _fam_of
            from .query.retention import RetentionPolicy, RetentionRouter
            policy = RetentionPolicy.from_config(
                cfg.get("retention.resolutions") or [], list(self._ds_res),
                raw_window_ms=self._store_cfg.retention_ms)
            self.engines[dataset].retention = RetentionRouter(
                policy,
                lambda res_ms, _ds=dataset: self.engines.get(
                    _fam_of(_ds, res_ms)),
                dataset=dataset)
        if cfg.get("cluster.buddy_endpoint"):
            # failure-aware query routing: time ranges overlapping a
            # known-bad window (node dead, shard warming) steer sub-queries
            # to the buddy cluster over its Prometheus HTTP API and stitch
            # with local results — the reference's FailureProvider/
            # PromQlExec dual-datacenter, no-SPOF design
            from .parallel.cluster import (FailureProvider,
                                           HighAvailabilityEngine,
                                           RemotePromExec)
            self.failures = FailureProvider()
            self.engines[dataset] = HighAvailabilityEngine(
                self.engines[dataset], self.failures,
                RemotePromExec(cfg["cluster.buddy_endpoint"], dataset))
            self.manager.subscribe(self._ha_track)

        # remote-write sink: durable bus publish when configured, else direct
        # ingest. The whole batch is validated against owned shards BEFORE
        # anything publishes, so a rejected batch is all-or-nothing.
        def writer(per_shard: dict, _ds=dataset):
            with self._shards_lock:
                buses = dict(self._buses)
                owned = set(buses) if buses else set(self._running)
            unowned = sorted(set(per_shard) - owned)
            if unowned:
                raise QueryError(f"shards {unowned} are not owned by this node")
            for shard, container in per_shard.items():
                if buses:
                    buses[shard].publish(container)
                else:
                    self.memstore.ingest(_ds, shard, container)
        from .query.scheduler import QueryScheduler
        self.scheduler = QueryScheduler(
            num_threads=cfg["query.num_threads"],
            max_queue=cfg["query.queue_size"],
            timeout_s=parse_duration_ms(cfg["query.timeout"]) / 1000.0)
        self.http = FiloHttpServer(self.engines, host=cfg["http.host"],
                                   port=cfg["http.port"], cluster=self.manager,
                                   writers={dataset: writer},
                                   scheduler=self.scheduler,
                                   cluster_ops={
                                       "extra": self._cluster_extra,
                                       "rebalance": self.rebalance_shard,
                                       "adopt": self.adopt_shard},
                                   subscribe_poll_s=parse_duration_ms(
                                       cfg["query.subscribe_poll"]) / 1000.0,
                                   governors=(
                                       {dataset: (self._governor,
                                                  self._series_known)}
                                       if self._governor is not None else None)
                                   ).start()
        if cfg.get("ingest.gateway_port") is not None:
            # Influx line-protocol gateway, config-wired: lines route to ALL
            # broker partitions (owned or not — the broker is global), or
            # straight into the local memstore when no bus is configured.
            # Broker publishes ride the windowed PUBLISH_BATCH path; sub-
            # window remainders drain on the gateway's flush cadence.
            from .ingest.gateway import GatewayServer
            self._gw_buses = self._make_shard_buses(num_shards)

            def gw_publish(shard, container, _ds=dataset):
                bus = self._gw_buses.get(shard)
                if bus is None:
                    self.memstore.ingest(_ds, shard, container)
                elif hasattr(bus, "publish_async"):
                    bus.publish_async(container)
                else:
                    bus.publish(container)

            gw_iv_ms = parse_duration_ms(cfg["ingest.gateway_flush_interval"])
            self.gateway = GatewayServer(
                gw_publish, num_shards=num_shards, spread=cfg["spread"],
                schema=self.memstore.schemas[cfg["schema"]],
                host=cfg["http.host"], port=cfg["ingest.gateway_port"],
                flush_lines=cfg["ingest.gateway_flush_lines"],
                flush_interval_ms=gw_iv_ms,
                governor=self._governor,
                series_known=self._series_known).start()

            def gw_drain():
                # gateway.stop() parity: the windowed publishers' sub-window
                # remainders drain with the final builder flush
                for b in list(self._gw_buses.values()):
                    if hasattr(b, "flush_publishes"):
                        b.flush_publishes()

            self.gateway.bus_drain = gw_drain
            if gw_iv_ms > 0 and any(hasattr(b, "flush_publishes")
                                    for b in self._gw_buses.values()):
                # interval 0 disables the timed flusher — starting the bus
                # drain loop anyway would busy-spin on wait(0)
                self._gw_flush_stop = threading.Event()

                def gw_bus_flush():
                    # broad on purpose: ANY fault must not kill the drain
                    # loop for the server's lifetime — sub-window remainders
                    # would never flush again (filolint:
                    # resource-worker-silent-death)
                    while not self._gw_flush_stop.wait(gw_iv_ms / 1000.0):
                        for b in list(self._gw_buses.values()):
                            try:
                                b.flush_publishes()
                            except Exception:  # noqa: BLE001
                                log.warning("gateway publish flush failed",
                                            exc_info=True)

                threading.Thread(target=gw_bus_flush, daemon=True,
                                 name="gw-bus-flush").start()
        if cfg.get("rules.groups"):
            # streaming recording rules & alerting: a scheduler evaluates
            # rule groups through THIS node's engine and publishes derived
            # series back through the broker plane with deterministic
            # (rule, eval_ts) pub-ids — crash/failover re-evaluation is
            # exactly-once (rules/; ARCHITECTURE "Rules & alerting")
            from .rules import DerivedSeriesPublisher, RulesManager
            schema_obj = self.memstore.schemas[cfg["schema"]]
            if schema_obj.is_histogram:
                raise ValueError(
                    "rules.groups requires a scalar ingest schema: "
                    "recording rules emit scalar derived samples")
            self._rules_buses = self._make_shard_buses(num_shards)

            def rules_publish(shard, container, pub_id, _ds=dataset):
                bus = self._rules_buses.get(shard)
                if bus is None:
                    # in-process deployment: the store's out-of-order drop
                    # dedupes a same-timestamp replay
                    self.memstore.ingest(_ds, shard, container)
                elif hasattr(bus, "publish_with_id"):
                    bus.publish_with_id(container, pub_id)
                else:
                    # FileBus has no id journal: at-least-once transport,
                    # deduped at the store like the direct path
                    bus.publish(container)

            publisher = DerivedSeriesPublisher(
                schema_obj, mapper, rules_publish, dataset=dataset)
            self.rules = RulesManager.from_config(
                cfg, self.engines[dataset], publisher, self._sink, dataset)
            self.rules.start()
            self.http.rules = self.rules
        if cfg.get("cluster.registrar"):
            # watch peers: a silent peer's shards are reassigned to survivors,
            # whose _on_shard_event resync starts the consumers
            # (ref: gossip deathwatch -> ShardManager auto-reassignment)
            from .parallel.bootstrap import MembershipMonitor
            self.membership = MembershipMonitor(
                self._registrar, self.node, on_down=self._peer_down,
                on_up=self._peer_up, on_self_stale=self._quarantine,
                interval_s=parse_duration_ms(cfg["cluster.heartbeat_interval"]) / 1000.0)
            # steady-state ownership reconciliation: peers' published claims
            # (rebalance cutovers, takeovers) fold into our map each poll
            self.membership.on_claims = self._adopt_claims
            # publish current ownership with each heartbeat so late joiners
            # adopt the incumbent assignment (rejoin without split-brain)
            # only manager-known datasets claim shards: downsample-family
            # engines (ds:ds_1m) are serving views, not assignable datasets
            self.membership.claims_fn = lambda: {
                ds: [int(s) for s in self.manager.shards_of_node(ds, self.node)]
                for ds in list(self.engines) if ds in self.manager.map}
            # publish OUR http endpoint so peers can dispatch plan subtrees
            # here; the bound port is authoritative (config may say port 0).
            # A wildcard bind address is not dialable by peers: advertise the
            # cluster self_addr's host instead (or the explicit
            # http.advertise override for NAT/multi-homed hosts)
            adv = cfg.get("http.advertise")
            if not adv:
                adv = cfg["http.host"]
                if adv in ("0.0.0.0", "::", ""):
                    adv = self.node.rsplit(":", 1)[0]
            self.membership.http_addr = f"{adv}:{self.http.port}"
            if cfg.get("cluster.gossip_port") is not None:
                # membership gossip: counted (not timed) failure detection
                # over the broker wire framing, alongside the registrar
                # heartbeats (which remain the discovery/claims substrate).
                # The agent's bound address publishes with our heartbeat so
                # peers' agents can probe it.
                from .cluster.membership import GossipAgent, MembershipTable
                table = MembershipTable(
                    self.node,
                    suspect_after=cfg["cluster.suspect_after"],
                    dead_after=cfg["cluster.dead_after"],
                    http=self.membership.http_addr,
                    on_down=self._peer_down, on_up=self._peer_up,
                    on_claims=self._adopt_claims)

                def gossip_peers(_reg=self._registrar):
                    return _reg.gossips() if hasattr(_reg, "gossips") else {}

                self.gossip = GossipAgent(
                    self.node, gossip_peers, table, host=cfg["http.host"],
                    port=cfg["cluster.gossip_port"],
                    interval_s=parse_duration_ms(
                        cfg["cluster.gossip_interval"]) / 1000.0)
                self.gossip.claims_fn = self.membership.claims_fn
                self.gossip.start()
                self.membership.gossip_addr = f"{adv}:{self.gossip.port}"
            self.membership.poll_once()
            self.membership.start()
        if self._ds_publish is not None:
            # serve the downsample families over HTTP: a background refresh
            # loads each resolution's published chunks from the sink into a
            # serving memstore and swaps the family's engine atomically, so
            # /promql/{ds}:ds_1m/... answers PromQL over dMin/dMax/dAvg/...
            # columns (ref: the reference's separate downsample cluster
            # reading the downsample tables; here the same process serves
            # both). Full reload per refresh — family sizes are 1/res of raw.
            self._ds_serve_stop = threading.Event()
            serve_s = parse_duration_ms(
                cfg.get("downsample.serve_interval", "30s")) / 1000.0

            def ds_serve_loop(_ds=dataset, _mapper=mapper):
                from .core.downsample import ds_family
                from .jobs.batch_downsampler import load_downsampled
                while True:
                    try:
                        with self._shards_lock:
                            owned = sorted(self._running)
                        for res in self._ds_res:
                            fam = ds_family(_ds, res)
                            ms = TimeSeriesMemStore()
                            for s in owned:
                                try:
                                    load_downsampled(self._sink, _ds, s, res,
                                                     "dAvg", ms)
                                except KeyError:
                                    continue      # not yet published
                                except Exception:  # noqa: BLE001
                                    log.exception(
                                        "downsample load failed for %s "
                                        "shard %s", fam, s)
                            if ms.shards_of(fam):
                                # loaded-state fingerprint: when the durable
                                # family data is UNCHANGED since the last
                                # refresh, keep the serving engine (and its
                                # warm result/fragment caches — the stitched
                                # downsampled body stays cached across
                                # dashboard ticks; a swap would reset the
                                # epoch baseline and void every entry). The
                                # value SUM makes it sensitive to in-place
                                # bucket rewrites (late raw samples
                                # re-downsampled into existing buckets keep
                                # counts and lead unchanged); any surprise
                                # reading it falls back to a plain swap —
                                # staleness is the failure mode to avoid,
                                # a dropped cache is just a warm-up
                                fp = None
                                try:
                                    fp = tuple(sorted(
                                        (s.shard_num, s.num_series,
                                         int(getattr(s, "lead_ms", 0)),
                                         int(s.store.n_host.sum()),
                                         float(np.nansum(np.asarray(
                                             s.store.snapshot_arrays()[1],
                                             np.float64))))
                                        for s in ms.shards_of(fam)
                                        if s.store is not None))
                                except Exception:  # noqa: BLE001 — see above
                                    fp = None
                                cur = self.engines.get(fam)
                                if fp is not None and cur is not None \
                                        and getattr(cur, "_serve_fingerprint",
                                                    None) == fp:
                                    continue
                                # cluster-aware like the raw engine: leaves
                                # for peer-owned shards dispatch to the peer's
                                # serving view of the same family
                                eng = QueryEngine(
                                    ms, fam, _mapper, cfg.query_config(),
                                    cluster=self.manager, node=self.node,
                                    endpoint_resolver=self._resolve_endpoint,
                                    route_dataset=_ds)
                                eng._serve_fingerprint = fp
                                self.engines[fam] = eng
                    except Exception:  # noqa: BLE001
                        log.exception("downsample serving refresh failed")
                    if self._ds_serve_stop.wait(serve_s):
                        return

            threading.Thread(target=ds_serve_loop, daemon=True,
                             name="ds-serving").start()
        if self._ds_publish is not None and len(self._ds_res) > 1:
            # periodic cascade to coarser resolutions (ref: DownsamplerMain's
            # 6-hourly batch job). Windows advance to the last COMPLETE coarse
            # bucket of the DURABLY PUBLISHED finer data (never in-memory
            # ingest state), and watermarks persist in the sink's meta so a
            # restart or shard takeover resumes instead of re-appending.
            self._cascade_stop = threading.Event()
            interval_s = parse_duration_ms(cfg["downsample.cascade_interval"]) / 1000.0

            def cascade_loop(_ds=dataset):
                from .core.downsample import ds_family
                from .jobs.batch_downsampler import run_cascade_downsample
                while not self._cascade_stop.wait(interval_s):
                    try:
                        with self._shards_lock:
                            owned = sorted(self._running)
                        for sh_num in owned:
                            pub_max = self._ds_publish.published_max.get(sh_num)
                            if pub_max is None:
                                continue
                            for i in range(1, len(self._ds_res)):
                                coarse = self._ds_res[i]
                                fam = ds_family(_ds, coarse)
                                # one-coarse-bucket lateness margin: series
                                # whose fine buckets publish a little behind
                                # the shard's fastest are still included
                                # (the reference's late-data widening analog)
                                hi = ((pub_max - coarse) // coarse) * coarse - 1
                                key = (sh_num, i)
                                lo = self._cascade_wm.get(key)
                                if lo is None:   # durable watermark survives
                                    meta = self._sink.read_meta(fam, sh_num) \
                                        if hasattr(self._sink, "read_meta") else {}
                                    lo = int(meta.get("cascade_wm", -1))
                                if hi <= lo:
                                    self._cascade_wm[key] = lo
                                    continue
                                run_cascade_downsample(
                                    self._sink, _ds, sh_num,
                                    self._ds_res[i - 1], coarse,
                                    start_ms=lo + 1, end_ms=hi)
                                self._cascade_wm[key] = hi
                                if hasattr(self._sink, "write_meta"):
                                    # merge: the cascade job records the
                                    # family's column order in the same meta
                                    m = self._sink.read_meta(fam, sh_num) or {}
                                    m["cascade_wm"] = hi
                                    self._sink.write_meta(fam, sh_num, m)
                    except Exception:
                        log.exception("cascade downsample pass failed")

            threading.Thread(target=cascade_loop, daemon=True,
                             name="cascade-downsampler").start()
        if cfg.get("retention.raw_ttl") is not None and self._sink is not None:
            # durable raw age-out: drop sink samples older than raw_ttl on a
            # cadence; each pass bumps the shard's data_epoch so cached
            # results over the aged-out range invalidate (the downsample
            # families keep the history at their resolutions)
            self._retention_stop = threading.Event()
            raw_ttl_ms = parse_duration_ms(cfg["retention.raw_ttl"])
            compact_s = parse_duration_ms(
                cfg["retention.compact_interval"]) / 1000.0

            def retention_loop(_ds=dataset):
                # broad on purpose: ANY fault must not kill the age-out
                # loop for the server's lifetime (filolint:
                # resource-worker-silent-death)
                while not self._retention_stop.wait(compact_s):
                    try:
                        with self._shards_lock:
                            owned = sorted(self._running)
                        for s in owned:
                            sh = self.memstore.shard(_ds, s)
                            # O(1) per-shard data-lead watermark (the same
                            # one the router reads) — not an O(max_series)
                            # last_ts scan per pass
                            lead = int(getattr(sh, "lead_ms", 0))
                            if lead > 0:
                                n = sh.age_out_durable(lead - raw_ttl_ms)
                                if n:
                                    log.info("retention: aged %d raw "
                                             "samples out of shard %d", n, s)
                    except Exception:  # noqa: BLE001
                        log.exception("retention age-out pass failed")

            threading.Thread(target=retention_loop, daemon=True,
                             name="retention-ageout").start()
        if cfg.get("profiler.enabled"):
            from .utils.profiler import SimpleProfiler
            self.profiler = SimpleProfiler(
                parse_duration_ms(cfg["profiler.interval"]) / 1000.0).start()
        # hand the profiler to the HTTP debug plane: /api/v1/debug/profile
        # start/stop/report drives this one instance (or lazily creates its
        # own when the config didn't start one)
        self.http.profiler = self.profiler
        tracer.log_spans = bool(cfg.get("tracing.log_spans"))
        # distributed tracing: sampling decided at trace roots on THIS node;
        # the decision propagates to peers in the trace context
        tracer.enabled = bool(cfg.get("trace.enabled", True))
        tracer.sample_rate = float(cfg.get("trace.sample_rate", 1.0))
        from .query.engine import slow_query_log
        slow_query_log.resize(int(cfg["query.slow_log_size"]))
        # fused compressed-resident kernel tier: pick the backend BEFORE the
        # warmup thread starts, so warmed programs are the ones that serve
        from .ops import fusedresident
        fusedresident.set_mode(str(cfg["query.fused_kernels"]))
        # mesh-program mode next, same reasoning: the warmup below may
        # pre-trace mesh dist_* programs and they must be the serving ones
        from .parallel import distributed
        distributed.set_mesh_mode(str(cfg["query.mesh_programs"]))
        distributed.set_mesh_donation(bool(cfg["query.mesh_donation"]))
        # serving fast path: bound the process-global compiled-plan cache
        # and pre-trace the configured hot shapes in the background — the
        # server accepts traffic immediately; warmed dashboards simply stop
        # paying first-query compiles as each program lands
        from .query.plancache import plan_cache
        from .query.plancache import warmup as plan_warmup
        plan_cache.resize(int(cfg["query.plan_cache_size"]))
        shapes = cfg.get("query.warmup_shapes") or []
        if shapes:
            def warmup_once(_shapes=list(shapes)):
                try:
                    info = plan_warmup(_shapes)
                    log.info("query warmup: %s program(s) traced in %.0f ms",
                             info["programs"], info["ms"])
                except Exception:  # noqa: BLE001 — warmup is an optimization;
                    # a bad shape spec must not take the server down
                    log.exception("query warmup failed")

            threading.Thread(target=warmup_once, daemon=True,
                             name="query-warmup").start()
        zep = cfg.get("trace.zipkin_endpoint")
        if zep:
            from .utils.tracing import ZipkinReporter
            self._zipkin = ZipkinReporter(tracer, zep).start()
        log.info("FiloServer up: dataset=%s shards=%s port=%s",
                 dataset, num_shards, self.http.port)
        return self

    def shutdown(self) -> None:
        if self.rules is not None:
            # first: no rule evaluation may publish into a closing bus
            self.rules.stop()
        for b in self._rules_buses.values():
            try:
                if hasattr(b, "close"):
                    b.close()
            except (ConnectionError, OSError, RuntimeError):
                log.warning("rules bus close failed on shutdown",
                            exc_info=True)
        if self._cascade_stop is not None:
            self._cascade_stop.set()
        if self._ds_serve_stop is not None:
            self._ds_serve_stop.set()
        if self._retention_stop is not None:
            self._retention_stop.set()
        if self._gw_flush_stop is not None:
            self._gw_flush_stop.set()
        if self.gateway is not None:
            # stop() owns the whole drain contract: it flushes every
            # pending builder and runs bus_drain (the windowed publishers'
            # sub-window remainders) before returning
            self.gateway.stop()
        for b in self._gw_buses.values():
            try:
                if hasattr(b, "close"):
                    b.close()
            except (ConnectionError, OSError, RuntimeError):
                log.warning("gateway bus close failed on shutdown",
                            exc_info=True)
        # stop flags first, then SEVER the buses (unblocks a consumer stuck
        # in a broker recv — joining first would stall behind the socket
        # timeout), join, and re-sever to catch a reconnect that raced the
        # first close (same ordering as _quarantine)
        for c in self.consumers:
            c.stop()
        with self._shards_lock:
            for b in self._buses.values():
                try:
                    b.close()
                except OSError:
                    log.warning("bus close failed on shutdown",
                                exc_info=True)
        for c in self.consumers:
            c.join(timeout=3)
        with self._shards_lock:
            for b in self._buses.values():
                try:
                    b.close()
                except OSError:
                    log.warning("bus close failed on shutdown",
                                exc_info=True)
            self._buses.clear()
        if self.http:
            self.http.stop()
        if self.scheduler:
            self.scheduler.shutdown()
        if self.membership:
            self.membership.stop()
        if self.gossip is not None:
            self.gossip.stop()
        if self.profiler:
            self.profiler.stop()
        if self._zipkin is not None:
            self._zipkin.stop()


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p
