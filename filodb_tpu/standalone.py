"""Standalone server: config -> cluster -> shards -> ingestion -> HTTP.

Reference: standalone/.../FiloServer.scala:15-38 (bootstraps the cluster, creates
datasets from config, starts HTTP) + coordinator/.../IngestionActor.scala:57
(per-shard ingestion lifecycle: resync on shard assignment, recovery from
checkpoints, then live consumption with status events).
"""

from __future__ import annotations

import logging
import threading
import time

from .config import Config, parse_duration_ms
from .core.memstore import TimeSeriesMemStore
from .core.store import FileColumnStore
from .http.api import FiloHttpServer
from .ingest.bus import FileBus
from .parallel.cluster import ShardManager, ShardStatus
from .parallel.shardmapper import ShardMapper
from .query.engine import QueryEngine
from .query.rangevector import QueryError
from .utils.metrics import ShardHealthStats, registry
from .utils.tracing import tracer

log = logging.getLogger("filodb_tpu.server")


class IngestionConsumer(threading.Thread):
    """Per-shard bus consumer (ref: IngestionActor drives memStore.ingestStream /
    recoverStream with RecoveryInProgress -> IngestionStarted events)."""

    def __init__(self, shard, bus: FileBus, schemas, manager: ShardManager,
                 dataset: str, poll_s: float = 0.5, purge_interval_s: float = 600.0):
        super().__init__(daemon=True, name=f"ingest-{dataset}-{shard.shard_num}")
        self.shard = shard
        self.bus = bus
        self.schemas = schemas
        self.manager = manager
        self.dataset = dataset
        self.poll_s = poll_s
        self.purge_interval_s = purge_interval_s
        self._stop_ev = threading.Event()
        self._offset = 0

    def run(self):
        sh = self.shard
        try:
            if sh.sink is not None:
                self.manager.set_status(self.dataset, sh.shard_num, ShardStatus.RECOVERY)
                sh.recover(self.bus, self.schemas)
                wm = sh.group_watermarks
                self._offset = int(self.bus.end_offset)
            self.manager.set_status(self.dataset, sh.shard_num, ShardStatus.ACTIVE)
            rows = registry.counter("filodb_ingested_rows",
                                    {"dataset": self.dataset, "shard": str(sh.shard_num)})
            last_purge = time.monotonic()
            backoff = 0.0
            while not self._stop_ev.wait(backoff or self.poll_s):
                # transient bus outages (e.g. a broker restart) must not kill
                # the shard: back off and retry, ERROR only while disconnected
                # (ref: IngestionError events -> resync, not actor death)
                try:
                    for off, container in self.bus.consume(self.schemas, self._offset):
                        sh.ingest(container, off)
                        rows.increment(len(container))
                        self._offset = off + 1
                except (ConnectionError, OSError, RuntimeError):
                    backoff = min(max(1.0, backoff * 2), 30.0)
                    log.warning("bus unavailable for shard %s; retrying in %.0fs",
                                sh.shard_num, backoff)
                    self.manager.set_status(self.dataset, sh.shard_num,
                                            ShardStatus.ERROR)
                    continue
                if backoff:
                    backoff = 0.0
                    self.manager.set_status(self.dataset, sh.shard_num,
                                            ShardStatus.ACTIVE)
                sh.flush()
                if sh.sink is not None:
                    sh.flush_all_groups()
                if time.monotonic() - last_purge >= self.purge_interval_s:
                    last_purge = time.monotonic()
                    lead = int(sh.store.last_ts.max(initial=0)) if sh.store is not None else 0
                    if lead > 0:
                        n = sh.purge_expired_partitions(lead - sh.config.retention_ms)
                        if n:
                            log.info("purged %d expired partitions from shard %d",
                                     n, sh.shard_num)
        except Exception:  # noqa: BLE001
            log.exception("ingestion failed for shard %s", sh.shard_num)
            self.manager.set_status(self.dataset, sh.shard_num, ShardStatus.ERROR)

    def stop(self):
        self._stop_ev.set()


class FiloServer:
    def __init__(self, config: Config | None = None, node_name: str = "local"):
        self.config = config or Config()
        self.node = node_name
        self.memstore = TimeSeriesMemStore()
        self.manager = ShardManager()
        self.manager.add_node(node_name)
        self.consumers: list[IngestionConsumer] = []
        self.http: FiloHttpServer | None = None
        self.scheduler = None
        self.engines: dict[str, QueryEngine] = {}
        self.profiler = None

    def start(self) -> "FiloServer":
        cfg = self.config
        dataset = cfg["dataset"]
        # shard ids live in a power-of-two space (hash routing, spread); a
        # non-pow2 count would leave routable ids with no owning shard
        num_shards = _pow2(cfg["num_shards"])
        self.manager.add_dataset(dataset, num_shards)
        sink = FileColumnStore(cfg["data_dir"]) if cfg.get("data_dir") else None
        store_cfg = cfg.store_config()
        health = ShardHealthStats(dataset)
        self.manager.subscribe(lambda ev: health.update(self.manager.snapshot(dataset)))
        buses: dict[int, FileBus] = {}
        for shard_num in self.manager.shards_of_node(dataset, self.node):
            shard = self.memstore.setup(dataset, cfg["schema"], shard_num,
                                        store_cfg, sink=sink)
            if cfg.get("bus_addr") or cfg.get("bus_dir"):
                if cfg.get("bus_addr"):
                    # remote broker: shard N == broker partition N (ref: Kafka
                    # PartitionStrategy, 1 shard == 1 partition)
                    from .ingest.broker import BrokerBus
                    bus = BrokerBus(cfg["bus_addr"], shard_num)
                else:
                    bus = FileBus(f"{cfg['bus_dir']}/shard{shard_num}.log")
                buses[shard_num] = bus
                c = IngestionConsumer(shard, bus, self.memstore.schemas,
                                      self.manager, dataset,
                                      purge_interval_s=parse_duration_ms(
                                          cfg.get("store.purge_interval", "10m")) / 1000.0)
                self.consumers.append(c)
                c.start()
            else:
                self.manager.set_status(dataset, shard_num, ShardStatus.ACTIVE)
        mapper = ShardMapper(num_shards, spread=cfg["spread"])
        self.engines[dataset] = QueryEngine(self.memstore, dataset, mapper,
                                            cfg.query_config())

        # remote-write sink: durable bus publish when configured, else direct
        # ingest. The whole batch is validated against owned shards BEFORE
        # anything publishes, so a rejected batch is all-or-nothing.
        owned = set(buses) if buses else \
            {s.shard_num for s in self.memstore.shards_of(dataset)}

        def writer(per_shard: dict, _b=buses, _ds=dataset):
            unowned = sorted(set(per_shard) - owned)
            if unowned:
                raise QueryError(f"shards {unowned} are not owned by this node")
            for shard, container in per_shard.items():
                if _b:
                    _b[shard].publish(container)
                else:
                    self.memstore.ingest(_ds, shard, container)
        from .query.scheduler import QueryScheduler
        self.scheduler = QueryScheduler(
            num_threads=cfg["query.num_threads"],
            max_queue=cfg["query.queue_size"],
            timeout_s=parse_duration_ms(cfg["query.timeout"]) / 1000.0)
        self.http = FiloHttpServer(self.engines, host=cfg["http.host"],
                                   port=cfg["http.port"], cluster=self.manager,
                                   writers={dataset: writer},
                                   scheduler=self.scheduler).start()
        if cfg.get("profiler.enabled"):
            from .utils.profiler import SimpleProfiler
            self.profiler = SimpleProfiler(
                parse_duration_ms(cfg["profiler.interval"]) / 1000.0).start()
        tracer.log_spans = bool(cfg.get("tracing.log_spans"))
        log.info("FiloServer up: dataset=%s shards=%s port=%s",
                 dataset, num_shards, self.http.port)
        return self

    def shutdown(self) -> None:
        for c in self.consumers:
            c.stop()
        for c in self.consumers:
            c.join(timeout=3)
        if self.http:
            self.http.stop()
        if self.scheduler:
            self.scheduler.shutdown()
        if self.profiler:
            self.profiler.stop()


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p
