"""Cluster control plane: membership, shard assignment, status events, failure
detection and auto-reassignment — host-side logic, no device involvement.

Reference: coordinator/.../NodeClusterActor.scala:187 (cluster singleton: dataset
setup, member tracking, shard-map subscriptions), ShardManager.scala:28 (assign/
unassign, event publication, auto-reassignment on node failure with a minimum
interval), ShardAssignmentStrategy.scala (even spread to least-loaded nodes),
ShardStatus.scala (status ADT), StatusActor (event fan-out), and
queryengine2/FailureProvider.scala:11-47 + RoutingPlanner.scala (failure-aware
time-split query routing to a buddy cluster).

TPU-native reading: a "node" owns a set of shards = mesh devices/hosts; the
control plane is gossip-free here (single coordinator object; multi-host wiring
via jax.distributed arrives with the multi-host runtime), but the assignment &
event model matches the reference so operators see the same lifecycle.
"""

from __future__ import annotations

import time
from collections import defaultdict
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable


class ShardStatus(Enum):
    UNASSIGNED = "Unassigned"
    ASSIGNED = "Assigned"
    RECOVERY = "Recovery"
    ACTIVE = "Active"
    ERROR = "Error"
    DOWN = "Down"
    STOPPED = "Stopped"


@dataclass(frozen=True)
class ShardEvent:
    """Ref: ShardEvent ADT (AssignmentStarted/IngestionStarted/RecoveryInProgress/
    IngestionError/ShardDown/...)."""
    kind: str
    dataset: str
    shard: int
    node: str | None
    at: float = field(default_factory=time.time)


class ShardAssignmentStrategy:
    """Even spread, filling least-loaded nodes first (ref:
    DefaultShardAssignmentStrategy.scala:1-113)."""

    def assign(self, shards: list[int], nodes: list[str],
               load: dict[str, int]) -> dict[int, str]:
        if not nodes:
            return {}
        out = {}
        counts = {n: load.get(n, 0) for n in nodes}
        for s in shards:
            target = min(counts, key=lambda n: (counts[n], n))
            out[s] = target
            counts[target] += 1
        return out


class ShardManager:
    """Owns assignment state for all datasets (ref: ShardManager.scala:28)."""

    def __init__(self, strategy: ShardAssignmentStrategy | None = None,
                 min_reassignment_interval_s: float = 0.0):
        self.strategy = strategy or ShardAssignmentStrategy()
        self.nodes: list[str] = []
        # dataset -> shard -> (node | None, ShardStatus)
        self.map: dict[str, dict[int, tuple[str | None, ShardStatus]]] = {}
        self.events: list[ShardEvent] = []
        self._subscribers: list[Callable[[ShardEvent], None]] = []
        self._last_reassign: dict[str, float] = defaultdict(float)
        self.min_reassign_s = min_reassignment_interval_s

    # -- membership ----------------------------------------------------------

    def add_node(self, node: str) -> None:
        if node in self.nodes:
            return
        self.nodes.append(node)
        for ds in self.map:
            self._assign_unassigned(ds)

    def remove_node(self, node: str) -> None:
        """Node failure/departure: mark its shards Down, then auto-reassign
        (ref: doc/sharding.md 'Automatic Reassignment')."""
        if node not in self.nodes:
            return
        self.nodes.remove(node)
        for ds, shards in self.map.items():
            for s, (n, st) in list(shards.items()):
                if n == node:
                    shards[s] = (None, ShardStatus.DOWN)
                    self._emit(ShardEvent("ShardDown", ds, s, node))
            now = time.time()
            if now - self._last_reassign[ds] >= self.min_reassign_s:
                self._last_reassign[ds] = now
                self._assign_unassigned(ds)

    # -- datasets ------------------------------------------------------------

    def add_dataset(self, dataset: str, num_shards: int,
                    claimed: dict[int, str] | None = None) -> None:
        """Ref: NodeClusterActor SetupDataset -> ShardManager.addDataset.

        ``claimed`` seeds incumbent ownership (shard -> node) learned from
        peers' registrar heartbeats: a (re)joining node adopts the cluster's
        existing assignment — including post-takeover state — instead of
        computing a fresh full assignment that would double-own shards."""
        if dataset in self.map:
            return
        self.map[dataset] = {s: (None, ShardStatus.UNASSIGNED)
                             for s in range(num_shards)}
        for s, node in (claimed or {}).items():
            if 0 <= s < num_shards and node in self.nodes:
                self.map[dataset][s] = (node, ShardStatus.ASSIGNED)
        self._assign_unassigned(dataset)

    def _assign_unassigned(self, dataset: str) -> None:
        shards = self.map[dataset]
        todo = [s for s, (n, st) in shards.items()
                if n is None or st in (ShardStatus.UNASSIGNED, ShardStatus.DOWN)]
        load: dict[str, int] = defaultdict(int)
        for ds in self.map.values():
            for n, _ in ds.values():
                if n is not None:
                    load[n] += 1
        for s, node in self.strategy.assign(todo, self.nodes, load).items():
            shards[s] = (node, ShardStatus.ASSIGNED)
            self._emit(ShardEvent("AssignmentStarted", dataset, s, node))

    # -- status/events -------------------------------------------------------

    def set_status(self, dataset: str, shard: int, status: ShardStatus) -> None:
        node, _ = self.map[dataset][shard]
        self.map[dataset][shard] = (node, status)
        kind = {ShardStatus.ACTIVE: "IngestionStarted",
                ShardStatus.RECOVERY: "RecoveryInProgress",
                ShardStatus.ERROR: "IngestionError",
                ShardStatus.STOPPED: "IngestionStopped"}.get(status, status.value)
        self._emit(ShardEvent(kind, dataset, shard, node))

    def subscribe(self, fn: Callable[[ShardEvent], None]) -> None:
        self._subscribers.append(fn)

    def _emit(self, ev: ShardEvent) -> None:
        self.events.append(ev)
        for fn in self._subscribers:
            fn(ev)

    def reassign(self, dataset: str, shard: int, node: str) -> None:
        """Directly move ONE shard's ownership (live rebalance cutover /
        peer-claims reconciliation — vs. remove_node's bulk failure path).
        Fires AssignmentStarted for the new owner, so the owning server's
        resync starts the shard."""
        if node not in self.nodes:
            self.nodes.append(node)
        self.map[dataset][shard] = (node, ShardStatus.ASSIGNED)
        self._emit(ShardEvent("AssignmentStarted", dataset, shard, node))

    def node_of(self, dataset: str, shard: int) -> str | None:
        return self.map[dataset][shard][0]

    def shards_of_node(self, dataset: str, node: str) -> list[int]:
        return [s for s, (n, _) in self.map[dataset].items() if n == node]

    def snapshot(self, dataset: str) -> dict:
        """CurrentShardSnapshot equivalent for subscribers/HTTP."""
        return {s: {"node": n, "status": st.value}
                for s, (n, st) in self.map[dataset].items()}

    def status(self) -> dict:
        return {"nodes": list(self.nodes),
                "datasets": {ds: self.snapshot(ds) for ds in self.map}}


# ---------------------------------------------------------------------------
# Failure-aware query routing (ref: FailureProvider + QueryRoutingPlanner +
# PromQlExec HTTP federation — the dual-datacenter no-SPOF story)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class FailureTimeRange:
    """A time range where local data is known bad/missing (ref:
    FailureProvider.scala FailureTimeRange)."""
    start_ms: int
    end_ms: int
    legacy: bool = False      # failure of the *remote* cluster instead


class FailureProvider:
    def __init__(self):
        self._failures: list[FailureTimeRange] = []
        # keyed OPEN windows (node dead, shard warming): end unknown until
        # recovery closes them — queries treat an open window as extending
        # through their whole range
        self._open: dict[str, int] = {}

    def record(self, f: FailureTimeRange) -> None:
        self._failures.append(f)

    def open_window(self, key: str, start_ms: int) -> None:
        """Start a keyed known-bad window (membership on_down / shard
        takeover): local data from ``start_ms`` on is suspect until
        ``close_window`` seals it."""
        self._open.setdefault(key, int(start_ms))

    def close_window(self, key: str, end_ms: int) -> None:
        """Seal a keyed window (node recovered / shard warmed): the closed
        range stays routable-around; later data is trusted again."""
        start = self._open.pop(key, None)
        if start is not None and end_ms >= start:
            self._failures.append(FailureTimeRange(start, int(end_ms)))

    def open_windows(self) -> dict[str, int]:
        return dict(self._open)

    def failures_in(self, start_ms: int, end_ms: int) -> list[FailureTimeRange]:
        out = [f for f in self._failures
               if f.end_ms >= start_ms and f.start_ms <= end_ms]
        out += [FailureTimeRange(s, 1 << 62)
                for s in self._open.values() if s <= end_ms]
        return out


@dataclass
class TimeSplit:
    start_ms: int
    end_ms: int
    remote: bool


def plan_time_splits(start_ms: int, end_ms: int, step_ms: int,
                     failures: list[FailureTimeRange],
                     lookback_ms: int = 300_000) -> list[TimeSplit]:
    """Split [start, end] into local/remote sub-ranges around local failures
    (ref: QueryRoutingPlanner.plan — remote route covers failure windows plus
    the lookback needed to re-prime range functions after the failure)."""
    local_failures = [f for f in failures if not f.legacy]
    if not local_failures:
        return [TimeSplit(start_ms, end_ms, remote=False)]
    splits: list[TimeSplit] = []
    cur = start_ms
    for f in sorted(local_failures, key=lambda f: f.start_ms):
        # remote must cover [f.start, f.end + lookback] rounded to steps
        r_start = max(cur, f.start_ms)
        r_end = min(end_ms, f.end_ms + lookback_ms)
        if r_start > end_ms or r_end < cur:
            continue
        # align to the step grid so sub-results stitch exactly
        r_start = start_ms + ((r_start - start_ms + step_ms - 1) // step_ms) * step_ms
        r_end = min(end_ms, start_ms + ((r_end - start_ms) // step_ms + 1) * step_ms)
        if r_start > cur:
            splits.append(TimeSplit(cur, r_start - step_ms, remote=False))
        splits.append(TimeSplit(r_start, r_end, remote=True))
        cur = r_end + step_ms
    if cur <= end_ms:
        splits.append(TimeSplit(cur, end_ms, remote=False))
    return [s for s in splits if s.start_ms <= s.end_ms]


class RemotePromExec:
    """Federated sub-query against a buddy cluster's Prometheus HTTP API
    (ref: query/.../exec/PromQlExec.scala)."""

    def __init__(self, endpoint: str, dataset: str, timeout_s: float = 30.0):
        self.endpoint = endpoint.rstrip("/")
        self.dataset = dataset
        self.timeout_s = timeout_s

    def query_range(self, promql: str, start_ms: int, end_ms: int, step_ms: int):
        import json as _json
        import urllib.parse
        import urllib.request

        import numpy as np

        from ..query.rangevector import RangeVectorKey, ResultMatrix
        params = urllib.parse.urlencode({
            "query": promql, "start": start_ms / 1000.0, "end": end_ms / 1000.0,
            "step": f"{step_ms}ms"})
        url = f"{self.endpoint}/promql/{self.dataset}/api/v1/query_range?{params}"
        with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
            payload = _json.load(r)
        out_ts = np.arange(start_ms, end_ms + 1, step_ms, dtype=np.int64)
        keys, rows = [], []
        for series in payload["data"]["result"]:
            metric = dict(series["metric"])
            if "__name__" in metric:
                metric["_metric_"] = metric.pop("__name__")
            keys.append(RangeVectorKey.of(metric))
            row = np.full(len(out_ts), np.nan)
            for t, v in series["values"]:
                idx = round((t * 1000 - start_ms) / step_ms)
                if 0 <= idx < len(out_ts):
                    row[idx] = float(v)
            rows.append(row)
        vals = np.stack(rows) if rows else np.zeros((0, len(out_ts)))
        return ResultMatrix(out_ts, vals, keys)


def stitch_matrices(parts) -> "ResultMatrix":
    """Stitch sub-range results over disjoint time splits into one matrix
    (ref: query/.../exec/StitchRvsExec.scala)."""
    import numpy as np

    from ..query.rangevector import ResultMatrix
    parts = [p for p in parts if p.num_series or len(p.out_ts)]
    if not parts:
        return ResultMatrix(np.zeros(0, np.int64), np.zeros((0, 0)), [])
    out_ts = np.concatenate([p.out_ts for p in parts])
    order = np.argsort(out_ts, kind="stable")
    out_ts = out_ts[order]
    all_keys: dict = {}
    for p in parts:
        for k in p.keys:
            all_keys.setdefault(k, len(all_keys))
    vals = np.full((len(all_keys), len(out_ts)), np.nan)
    col = 0
    for p in parts:
        pv = np.asarray(p.values)
        T = len(p.out_ts)
        cols = np.searchsorted(out_ts, p.out_ts)
        for i, k in enumerate(p.keys):
            vals[all_keys[k], cols] = pv[i]
        col += T
    return ResultMatrix(out_ts, vals, list(all_keys))


class HighAvailabilityEngine:
    """Query engine wrapper: routes failure time ranges to a buddy cluster and
    stitches results (the reference's dual-cluster HA query path).

    Drop-in for a QueryEngine: every attribute/method other than
    ``query_range`` (metadata, instant queries, memstore, caches) passes
    through to the wrapped engine, so the HTTP layer, rules evaluator and
    stats scrapers serve through it unchanged."""

    def __init__(self, engine, failure_provider: FailureProvider,
                 remote: RemotePromExec | None):
        self.engine = engine
        self.failures = failure_provider
        self.remote = remote

    def __getattr__(self, name):
        # only missing attrs land here: the wrapper is transparent for
        # everything it does not explicitly override
        return getattr(self.engine, name)

    def query_range(self, promql: str, start_ms: int, end_ms: int,
                    step_ms: int, **kw):
        from ..query.rangevector import QueryResult
        fails = self.failures.failures_in(start_ms, end_ms)
        splits = plan_time_splits(start_ms, end_ms, step_ms, fails)
        if len(splits) == 1 and not splits[0].remote:
            return self.engine.query_range(promql, start_ms, end_ms, step_ms,
                                           **kw)
        parts = []
        for sp in splits:
            if sp.remote:
                if self.remote is None:
                    continue
                parts.append(self.remote.query_range(promql, sp.start_ms,
                                                     sp.end_ms, step_ms))
            else:
                r = self.engine.query_range(promql, sp.start_ms, sp.end_ms,
                                            step_ms, **kw)
                parts.append(r.matrix.to_host())
        res = QueryResult(stitch_matrices(parts))
        res.exec_path = "ha-stitched"
        return res
