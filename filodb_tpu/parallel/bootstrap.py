"""Multi-host cluster bootstrap: seed discovery + JAX distributed init +
membership with heartbeat failure detection.

Reference: akka-bootstrapper/.../AkkaBootstrapper.scala:31 (strategy-driven
seed discovery, then join-or-become-seed), WhitelistClusterSeedDiscovery.scala:18
(static seed list), DnsSrvClusterSeedDiscovery.scala / ConsulClient.scala
(registration-based discovery — nodes register themselves and discover peers
from the registrar), plus Akka Cluster gossip deathwatch feeding
ShardManager.remove_node (coordinator/.../NodeClusterActor.scala:187).

TPU-native translation: the cluster's data plane is JAX collectives over
ICI/DCN, so "joining the cluster" means agreeing on the jax.distributed
world — a coordinator address, a process count, and a stable process id per
host. Seed discovery produces exactly that tuple: the lexicographically first
member is the coordinator (deterministic without an election, the analog of
akka-bootstrapper's "lowest address becomes seed"), and each member's rank is
its index in the sorted member list. Membership liveness is heartbeat-based
(registrar timestamps), feeding ShardManager reassignment on failure.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from dataclasses import dataclass

log = logging.getLogger(__name__)


# --------------------------------------------------------------------------
# Seed discovery strategies (ref: akka-bootstrapper discovery hierarchy)
# --------------------------------------------------------------------------

class SeedDiscovery:
    """Strategy interface: produce the member list this node should join."""

    def discover(self) -> list[str]:  # pragma: no cover - interface
        raise NotImplementedError

    def register(self, addr: str) -> None:
        """Registration-based strategies record this node; static ones no-op."""


class WhitelistSeedDiscovery(SeedDiscovery):
    """Static seed list (ref: WhitelistClusterSeedDiscovery.scala:18)."""

    def __init__(self, seeds: list[str]):
        self.seeds = [s.strip() for s in seeds if s.strip()]

    def discover(self) -> list[str]:
        return list(self.seeds)


class EnvSeedDiscovery(WhitelistSeedDiscovery):
    """Seeds from an environment variable (comma-separated host:port)."""

    def __init__(self, var: str = "FILODB_SEEDS"):
        super().__init__(os.environ.get(var, "").split(","))


class FileRegistrarDiscovery(SeedDiscovery):
    """Shared-directory registrar: each node owns one member file it rewrites
    atomically on heartbeat; discovery reads all member files (the Consul/
    DNS-SRV analog for environments without either — ref: ConsulClient.scala
    registration + query). Per-node files mean no cross-process write races
    and no unbounded growth; members silent past ``stale_s`` are gone."""

    def __init__(self, path: str, stale_s: float = 30.0):
        self.path = path
        self.stale_s = stale_s
        os.makedirs(path, exist_ok=True)
        self._lock = threading.Lock()

    def _member_file(self, addr: str) -> str:
        safe = addr.replace(":", "_").replace("/", "_")
        return os.path.join(self.path, f"{safe}.member")

    def register(self, addr: str, claims: dict | None = None,
                 http: str | None = None, gossip: str | None = None) -> None:
        """Heartbeat, optionally carrying the node's shard ownership claims
        ({dataset: [shard ids]}), its HTTP endpoint ("host:port"), and its
        membership-gossip endpoint. Claims let a (re)joining node adopt the
        incumbent assignment instead of computing a fresh one; the HTTP
        endpoint lets peers dispatch query subtrees to this node
        (query/wire.py); the gossip endpoint is how peers' GossipAgents
        find each other (cluster/membership.py)."""
        tmp = self._member_file(addr) + ".tmp"
        with self._lock:
            with open(tmp, "w") as f:
                f.write(json.dumps({"addr": addr, "ts": time.time(),
                                    "claims": claims or {}, "http": http,
                                    "gossip": gossip}))
            os.replace(tmp, self._member_file(addr))

    heartbeat = register     # a re-registration refreshes the timestamp

    def _live_entries(self):
        now = time.time()
        for name in os.listdir(self.path):
            if not name.endswith(".member"):
                continue
            try:
                with open(os.path.join(self.path, name)) as f:
                    m = json.loads(f.read())
                if now - m["ts"] <= self.stale_s:
                    yield m
            except (OSError, ValueError, KeyError):
                continue     # torn read of a concurrent rewrite — skip

    def discover(self) -> list[str]:
        return sorted(m["addr"] for m in self._live_entries())

    def claims(self) -> dict[str, dict]:
        """Live members' shard-ownership claims: addr -> {dataset: [ids]}."""
        return {m["addr"]: m.get("claims") or {} for m in self._live_entries()}

    def endpoints(self) -> dict[str, str]:
        """Live members' published HTTP endpoints: addr -> "host:port"."""
        return {m["addr"]: m["http"] for m in self._live_entries()
                if m.get("http")}

    def gossips(self) -> dict[str, str]:
        """Live members' published gossip endpoints: addr -> "host:port"."""
        return {m["addr"]: m["gossip"] for m in self._live_entries()
                if m.get("gossip")}


class DnsSrvSeedDiscovery(SeedDiscovery):
    """Seeds from DNS SRV records (ref: DnsSrvClusterSeedDiscovery.scala:12,87
    — resolve ``_filodb._tcp.<domain>`` and join the returned host:port set).

    Kubernetes headless services and Consul DNS both publish peers this way.
    The stdlib has no SRV resolver, so a minimal RFC-1035 query/parse lives
    here (same dependency-free stance as utils/snappy.py); name compression
    pointers in answers are handled."""

    SRV, IN = 33, 1

    def __init__(self, srv_name: str, resolver: str | None = None,
                 timeout_s: float = 3.0):
        self.srv_name = srv_name.rstrip(".")
        self.timeout_s = timeout_s
        self.resolver = resolver or self._system_resolver()

    @staticmethod
    def _system_resolver() -> str:
        try:
            with open("/etc/resolv.conf") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2 and parts[0] == "nameserver":
                        ns = parts[1]
                        # IPv6 literals must be bracketed — "fd00::1:53"
                        # would parse as a DIFFERENT address
                        return f"[{ns}]:53" if ":" in ns else f"{ns}:53"
        except OSError:
            pass
        return "127.0.0.1:53"

    @staticmethod
    def _encode_name(name: str) -> bytes:
        out = b""
        for label in name.split("."):
            raw = label.encode()
            out += bytes([len(raw)]) + raw
        return out + b"\x00"

    @staticmethod
    def _read_name(buf: bytes, off: int) -> tuple[str, int]:
        """Domain name at ``off``; follows RFC-1035 compression pointers.
        Returns (name, offset-after-the-name-as-stored)."""
        labels, jumped, end = [], False, off
        hops = 0
        while True:
            ln = buf[off]
            if ln & 0xC0 == 0xC0:             # compression pointer
                if not jumped:
                    end = off + 2
                off = ((ln & 0x3F) << 8) | buf[off + 1]
                jumped = True
                hops += 1
                if hops > 64:
                    raise ValueError("DNS name pointer loop")
                continue
            if ln == 0:
                if not jumped:
                    end = off + 1
                return ".".join(labels), end
            off += 1
            labels.append(buf[off:off + ln].decode())
            off += ln

    def _resolver_addr(self) -> tuple[str, int, int]:
        """(host, port, socket family) — handles '[v6]:53', bare IPv6
        literals (port defaults to 53), and host:port."""
        r = self.resolver
        if r.startswith("["):                      # [v6]:port
            host, _, rest = r[1:].partition("]")
            port = int(rest.lstrip(":") or 53)
        elif r.count(":") > 1:                     # bare IPv6 literal
            host, port = r, 53
        elif ":" in r:
            host, port_s = r.rsplit(":", 1)
            port = int(port_s)
        else:
            host, port = r, 53
        fam = (socket.AF_INET6 if ":" in host else socket.AF_INET)
        return host, port, fam

    def query_srv(self) -> list[tuple[int, int, int, str]]:
        """[(priority, weight, port, target)] for the SRV name."""
        import struct as st
        qid = int.from_bytes(os.urandom(2), "big")
        msg = (st.pack(">HHHHHH", qid, 0x0100, 1, 0, 0, 0)
               + self._encode_name(self.srv_name) + st.pack(">HH", self.SRV, self.IN))
        host, port, fam = self._resolver_addr()
        with socket.socket(fam, socket.SOCK_DGRAM) as s:
            s.settimeout(self.timeout_s)
            s.sendto(msg, (host, port))
            buf, _ = s.recvfrom(4096)
        rid, flags, qd, an, _ns, _ar = st.unpack(">HHHHHH", buf[:12])
        if rid != qid:
            raise ValueError("DNS response id mismatch")
        if flags & 0x0200:
            # TC: the SRV RRset exceeded the UDP payload — a silently partial
            # peer list would bootstrap an undersized world
            raise ValueError(
                "truncated DNS response (TC): SRV record set too large for "
                "UDP; configure fewer/shorter records or a TCP-capable "
                "registrar (ConsulSeedDiscovery)")
        rcode = flags & 0x000F
        if rcode:
            # SERVFAIL/NXDOMAIN etc must not read as an empty (healthy) seed
            # list — that bootstraps a single-node world silently
            raise ValueError(
                f"DNS SRV query for {self.srv_name!r} failed with rcode "
                f"{rcode}")
        off = 12
        for _ in range(qd):                   # skip the echoed question
            _, off = self._read_name(buf, off)
            off += 4
        out = []
        for _ in range(an):
            _, off = self._read_name(buf, off)
            rtype, _cls, _ttl, rdlen = st.unpack(">HHIH", buf[off:off + 10])
            off += 10
            if rtype == self.SRV:
                prio, weight, port = st.unpack(">HHH", buf[off:off + 6])
                target, _ = self._read_name(buf, off + 6)
                out.append((prio, weight, port, target))
            off += rdlen
        return out

    def discover(self) -> list[str]:
        return sorted(f"{target}:{port}"
                      for _p, _w, port, target in self.query_srv())


class ConsulSeedDiscovery(SeedDiscovery):
    """Registration-based discovery against a Consul-compatible HTTP registry
    (ref: ConsulClusterSeedDiscovery.scala + ConsulClient.scala:5 — nodes
    register a service and discover peers from the catalog).

    Liveness: each registration stamps a heartbeat timestamp into the service
    Meta; ``discover()`` drops entries whose stamp is older than ``stale_s``
    (the FileRegistrarDiscovery expiry rule — a crashed node must not inflate
    the resolved world forever). Entries registered by other tooling (no
    stamp) are kept: their lifecycle belongs to Consul's own health checks.
    Shard-ownership ``claims`` ride Meta too, so rejoining nodes adopt the
    incumbent assignment exactly as with the file registrar."""

    def __init__(self, base_url: str, service: str = "filodb",
                 timeout_s: float = 5.0, stale_s: float = 30.0):
        self.base = base_url.rstrip("/")
        self.service = service
        self.timeout_s = timeout_s
        self.stale_s = stale_s

    def _http(self, method: str, path: str, body: dict | None = None):
        import urllib.request
        req = urllib.request.Request(
            self.base + path, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            raw = r.read()
        return json.loads(raw) if raw else None

    def register(self, addr: str, claims: dict | None = None,
                 http: str | None = None, gossip: str | None = None) -> None:
        host, port_s = addr.rsplit(":", 1)
        meta = {"filodb_ts": str(time.time()),
                "filodb_claims": json.dumps(claims or {})}
        if http:
            meta["filodb_http"] = http
        if gossip:
            meta["filodb_gossip"] = gossip
        self._http("PUT", "/v1/agent/service/register", {
            "Name": self.service, "ID": f"{self.service}-{addr}",
            "Address": host, "Port": int(port_s), "Meta": meta})

    heartbeat = register     # re-registration refreshes the timestamp

    def deregister(self, addr: str) -> None:
        self._http("PUT",
                   f"/v1/agent/service/deregister/{self.service}-{addr}")

    def _live_rows(self):
        rows = self._http("GET", f"/v1/catalog/service/{self.service}") or []
        now = time.time()
        for r in rows:
            meta = (r.get("ServiceMeta") or r.get("Meta") or {})
            ts = meta.get("filodb_ts")
            if ts is not None and now - float(ts) > self.stale_s:
                continue      # our own dead entry; foreign entries stay
            yield r, meta

    def discover(self) -> list[str]:
        out = set()
        for r, _meta in self._live_rows():
            host = r.get("ServiceAddress") or r.get("Address")
            port = r.get("ServicePort")
            if host and port:
                out.add(f"{host}:{port}")
        return sorted(out)

    def claims(self) -> dict[str, dict]:
        """Live members' shard-ownership claims (FileRegistrar API twin)."""
        out = {}
        for r, meta in self._live_rows():
            host = r.get("ServiceAddress") or r.get("Address")
            port = r.get("ServicePort")
            if host and port:
                try:
                    out[f"{host}:{port}"] = json.loads(
                        meta.get("filodb_claims") or "{}")
                except ValueError:
                    out[f"{host}:{port}"] = {}
        return out

    def endpoints(self) -> dict[str, str]:
        """Live members' published HTTP endpoints (FileRegistrar API twin)."""
        out = {}
        for r, meta in self._live_rows():
            host = r.get("ServiceAddress") or r.get("Address")
            port = r.get("ServicePort")
            if host and port and meta.get("filodb_http"):
                out[f"{host}:{port}"] = meta["filodb_http"]
        return out

    def gossips(self) -> dict[str, str]:
        """Live members' published gossip endpoints (FileRegistrar twin)."""
        out = {}
        for r, meta in self._live_rows():
            host = r.get("ServiceAddress") or r.get("Address")
            port = r.get("ServicePort")
            if host and port and meta.get("filodb_gossip"):
                out[f"{host}:{port}"] = meta["filodb_gossip"]
        return out


# --------------------------------------------------------------------------
# Bootstrap: discovery -> jax.distributed world
# --------------------------------------------------------------------------

@dataclass
class ClusterWorld:
    """The agreed jax.distributed topology."""
    coordinator: str          # host:port of process 0
    num_processes: int
    process_id: int
    members: list[str]        # sorted member addresses

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


class ClusterBootstrap:
    """Join-or-become-seed (ref: AkkaBootstrapper.bootstrap): discover peers,
    derive a deterministic world, and (optionally) bring up jax.distributed."""

    def __init__(self, discovery: SeedDiscovery, self_addr: str,
                 settle_s: float = 0.0):
        self.discovery = discovery
        self.self_addr = self_addr
        self.settle_s = settle_s

    def resolve_world(self, min_members: int = 1,
                      timeout_s: float = 30.0) -> ClusterWorld:
        """Register, wait for at least ``min_members`` peers to appear (the
        akka-bootstrapper expected-contact-points analog), and compute the
        world. Deterministic across members: everyone sorts the same member
        list, so everyone agrees on coordinator and ranks without an election."""
        self.discovery.register(self.self_addr)
        if self.settle_s:
            time.sleep(self.settle_s)
        deadline = time.monotonic() + timeout_s
        while True:
            members = self.discovery.discover()
            if self.self_addr not in members:
                members = sorted(members + [self.self_addr])
            if len(members) >= min_members or time.monotonic() >= deadline:
                break
            time.sleep(0.2)
        if len(members) < min_members:
            raise TimeoutError(
                f"only {len(members)}/{min_members} members after {timeout_s}s")
        return ClusterWorld(coordinator=members[0], num_processes=len(members),
                            process_id=members.index(self.self_addr),
                            members=members)

    def initialize_jax(self, world: ClusterWorld | None = None) -> ClusterWorld:
        """Bring up the JAX distributed runtime for a >1-process world
        (single-process worlds skip it — local jax.devices() is the mesh)."""
        import jax
        world = world or self.resolve_world()
        if world.num_processes > 1:
            jax.distributed.initialize(
                coordinator_address=world.coordinator,
                num_processes=world.num_processes,
                process_id=world.process_id)
        return world


# --------------------------------------------------------------------------
# Membership + heartbeat failure detection -> ShardManager reassignment
# --------------------------------------------------------------------------

class MembershipMonitor(threading.Thread):
    """Heartbeats this node into the registrar and watches peers' timestamps;
    a silent peer is reported down (ref: Akka gossip deathwatch ->
    ShardManager.remove_node auto-reassignment, doc/sharding.md
    'Automatic Reassignment')."""

    def __init__(self, registrar: FileRegistrarDiscovery, self_addr: str,
                 on_down, on_up=None, on_self_stale=None, interval_s: float = 5.0):
        super().__init__(daemon=True, name="membership-monitor")
        self.registrar = registrar
        self.self_addr = self_addr
        self.on_down = on_down
        self.on_up = on_up
        # optional provider of this node's shard-ownership claims, published
        # with every heartbeat so late joiners adopt the incumbent assignment
        self.claims_fn = None
        # this node's HTTP endpoint ("host:port"), published with heartbeats
        # so peers can dispatch query subtrees here (query/wire.py)
        self.http_addr: str | None = None
        # this node's membership-gossip endpoint, published the same way so
        # peers' GossipAgents can probe it (cluster/membership.py)
        self.gossip_addr: str | None = None
        # fired when OUR OWN heartbeat gap exceeded stale_s — peers have
        # declared us dead and reassigned our shards, so we must fail-stop
        # (the Akka quarantine analog: a removed-but-alive node restarts)
        self.on_self_stale = on_self_stale
        # optional per-poll claims reconciliation: fired with (peer, claims)
        # for every live peer's published shard ownership, so a rebalance
        # cutover on two nodes propagates to every other node's map
        self.on_claims = None
        self.interval_s = interval_s
        self._stop_ev = threading.Event()
        self._known: set[str] = set()
        self._last_beat: float | None = None

    def poll_once(self) -> None:
        now = time.monotonic()
        if (self._last_beat is not None
                and now - self._last_beat > self.registrar.stale_s
                and self.on_self_stale is not None):
            # do NOT heartbeat: peers already consider us dead — re-announcing
            # while still holding shards would create double ownership
            self._stop_ev.set()
            self.on_self_stale()
            return
        self._beat()
        self._last_beat = now
        live = set(self.registrar.discover())
        for gone in sorted(self._known - live - {self.self_addr}):
            self.on_down(gone)
        if self.on_up is not None:
            for fresh in sorted(live - self._known):
                self.on_up(fresh)
        self._known = live
        if self.on_claims is not None and hasattr(self.registrar, "claims"):
            for peer, peer_claims in sorted(self.registrar.claims().items()):
                if peer != self.self_addr:
                    self.on_claims(peer, peer_claims)

    def _beat(self) -> None:
        claims = self.claims_fn() if self.claims_fn is not None else None
        if self.gossip_addr is not None:
            try:
                self.registrar.heartbeat(self.self_addr, claims,
                                         http=self.http_addr,
                                         gossip=self.gossip_addr)
                return
            except TypeError:
                pass     # registrar predating gossip publication
        try:
            self.registrar.heartbeat(self.self_addr, claims,
                                     http=self.http_addr)
            return
        except TypeError:
            pass     # custom registrar predating endpoint/claims publication
        if claims is not None:
            try:
                self.registrar.heartbeat(self.self_addr, claims)
                return
            except TypeError:
                pass
        self.registrar.heartbeat(self.self_addr)

    def publish_now(self) -> None:
        """Push a fresh heartbeat (with current claims) immediately — called
        on assignment changes so joiners reading the registrar see takeover
        state without waiting out the heartbeat interval."""
        try:
            self._beat()
        except Exception:
            log.exception("claim publish failed")

    def run(self) -> None:
        # a transient registrar error (e.g. OSError on a shared/NFS heartbeat
        # file) must not silently kill the monitor thread: the node would stop
        # heartbeating but never reach the self-stale check, so peers would
        # reassign its shards WHILE it keeps ingesting — the exact double-
        # ownership the quarantine exists to prevent. Failed polls leave
        # _last_beat unset, so a lapse long enough trips on_self_stale above.
        while not self._stop_ev.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:
                log.exception("membership poll failed; treating as a missed "
                              "heartbeat")

    def stop(self) -> None:
        self._stop_ev.set()


def free_port(host: str = "127.0.0.1") -> int:
    """A free TCP port for the jax.distributed coordinator service."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]
