"""Distributed query execution over a jax device mesh.

Reference: the Akka scatter-gather plane — ExecPlans Kryo-dispatched to per-shard
QueryActors, partial aggregates reduced on the calling node
(coordinator/.../queryengine2/QueryEngine.scala:59-67, query/.../exec/ExecPlan.scala
NonLeafExecPlan.dispatchRemotePlan, client/Serializer.scala Kryo wire).

TPU-native replacement: shards live on mesh devices ("shard" axis); one
``shard_map``-compiled program evaluates the range function on every shard's
resident block and reduces partial aggregates with ``psum`` over ICI — the
collective *is* the scatter-gather. No serialization, no per-shard dispatch.

The same partial-aggregate format as the in-process path (ops/aggregators.py)
crosses the collective, so single-chip and multi-chip execution share semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import aggregators, fusedgrid, rangefns


def make_mesh(devices=None, axis: str = "shard") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


class DistributedStore:
    """Global sharded view over per-shard device stores.

    Each TimeSeriesShard's SeriesStore already lives on one mesh device; this
    assembles the per-device blocks into global arrays [NSHARD, S, C] sharded on
    the "shard" mesh axis with ``make_array_from_single_device_arrays`` — zero
    copy, the shards' HBM blocks become one logical array.
    """

    def __init__(self, mesh: Mesh, shards):
        self.mesh = mesh
        self.shards = shards
        ns = len(shards)
        assert ns == mesh.devices.size, "one shard per mesh device"
        s0 = shards[0].store
        self.S, self.C = s0.S, s0.C
        self.sharding = NamedSharding(mesh, P("shard"))

    def _global(self, per_shard_arrays, extra_shape, dtype):
        ns = len(self.shards)
        shape = (ns,) + extra_shape
        arrs = [a.reshape((1,) + extra_shape) for a in per_shard_arrays]
        return jax.make_array_from_single_device_arrays(
            shape, self.sharding, arrs)

    def arrays(self):
        ts = self._global([s.store.ts for s in self.shards], (self.S, self.C), jnp.int64)
        val = self._global([s.store.val for s in self.shards], (self.S, self.C), None)
        n = self._global([s.store.n for s in self.shards], (self.S,), jnp.int32)
        return ts, val, n


@functools.partial(jax.jit, static_argnames=("fn", "op", "num_groups", "mesh"))
def dist_aggregate(ts_g, val_g, n_g, gids_g, out_ts, window_ms, a0, a1,
                   fn: str, op: str, num_groups: int, mesh: Mesh):
    """One compiled distributed query step: range function per shard block +
    segment partials + psum over the shard axis; every shard ends with the same
    [G, T] final matrix (taken from shard 0 by the caller)."""

    def per_shard(ts, val, n, gids):
        acc = jnp.float64 if val.dtype == jnp.float64 else jnp.float32
        mat = rangefns._periodic(fn, ts[0], val[0], n[0], out_ts, window_ms,
                                 a0, a1, w_cap=256, acc=acc)
        parts = aggregators.partial_aggregate(op, mat, gids[0], num_groups)
        parts = {k: jax.lax.psum(v, "shard") if k not in ("min", "max")
                 else (jax.lax.pmin(v, "shard") if k == "min" else jax.lax.pmax(v, "shard"))
                 for k, v in parts.items()}
        return aggregators.present_partials(op, parts)[None]

    return jax.shard_map(
        per_shard, mesh=mesh,
        in_specs=(P("shard"), P("shard"), P("shard"), P("shard")),
        out_specs=P("shard"),
    )(ts_g, val_g, n_g, gids_g)


@functools.partial(jax.jit, static_argnames=("fn", "op", "num_groups", "mesh",
                                             "window_ms", "interval_ms",
                                             "S", "C", "Tp", "c0", "Ck"))
def dist_fused_aggregate(val_g, n_g, gids_g, band, ohlo, lo, hi, rel,
                         fn: str, op: str, num_groups: int, mesh: Mesh,
                         window_ms: int, interval_ms: int,
                         S: int, C: int, Tp: int, c0: int = 0, Ck: int = 0):
    """Fused single-pass map phase on every shard + psum of its partial-state
    layout over the shard axis — the multi-chip twin of
    ``fusedgrid.fused_grid_aggregate`` (ref: AggrOverRangeVectors.scala:62 —
    the same AggregateMapReduce map phase runs identically on every data
    node; the psum IS the reduce node). Band/edge operands are replicated;
    each shard streams only its resident [S, C] block."""
    needs_sumsq = op in ("stddev", "stdvar")
    Sb = 512 if S % 512 == 0 else S
    call = fusedgrid.build_pallas(fn, needs_sumsq, window_ms, interval_ms,
                                  S, Sb, C, Tp, num_groups,
                                  jax.default_backend() != "tpu",
                                  c0=c0, Ck=Ck)

    def per_shard(val, n, gids, band, ohlo, lo, hi, rel):
        outs = call(val[0].astype(jnp.float32),
                    n[0].astype(jnp.int32).reshape(S, 1),
                    gids[0].astype(jnp.int32).reshape(S, 1),
                    band, ohlo, lo, hi, rel)
        parts = ({"count": jax.lax.psum(outs[1], "shard")}
                 if op in ("count", "group") else
                 {k: jax.lax.psum(v, "shard")
                  for k, v in zip(("sum", "count", "sumsq"), outs)})
        return aggregators.present_partials(op, parts)[None]

    return jax.shard_map(
        per_shard, mesh=mesh,
        in_specs=(P("shard"), P("shard"), P("shard"), P(), P(), P(), P(), P()),
        out_specs=P("shard"),
        # pallas_call emits ShapeDtypeStructs without varying-mesh-axis
        # annotations; the kernel is per-shard-local so vma checking adds
        # nothing here
        check_vma=False,
    )(val_g, n_g, gids_g, band, ohlo, lo, hi, rel)


class LazyMeshResult:
    """Device-resident distributed result; ``resolve()`` does the blocking
    host fetch. The engine dispatches under the shard locks but fetches
    outside them (same contract as the in-process leaf: a slow collective
    must not stall ingest on every shard for its full wall time)."""

    def __init__(self, out, num_groups: int, T: int | None):
        self._out = out
        self._ng = num_groups
        self._T = T

    def resolve(self) -> np.ndarray:
        # all shards hold identical presented results; take shard 0's block
        r = np.asarray(self._out.addressable_shards[0].data[0])[:self._ng]
        return r[:, :self._T] if self._T is not None else r


class MeshQueryExecutor:
    """Runs aggregation queries over a DistributedStore (used by the engine when
    a mesh is configured; falls back to in-process scatter-gather otherwise).

    Routing: when the query is fusable (rate/increase/delta into
    sum/avg/count/group/stddev/stdvar), every shard store is f32,
    grid-aligned to one common (base, interval) with a single uniform start
    cohort, and the shapes fit the fused kernel's VMEM gate, the per-shard
    map phase runs the single-pass fused Pallas kernel; otherwise the
    general two-step kernels. ``last_path`` records the route taken."""

    def __init__(self, dstore: DistributedStore):
        self.dstore = dstore
        self.last_path: str | None = None

    def _fused_grid(self):
        """Common (base_ts, interval_ms) when every shard qualifies for the
        fused map phase, else None."""
        grids = set()
        for sh in self.dstore.shards:
            st = sh.store
            if st is None or st.dtype != jnp.float32:
                return None
            gi = st.grid_info()
            if gi is None:
                return None
            kind, off = st.grid_cohorts()
            if kind != "uniform" or off != 0:
                return None
            grids.add(gi)
        return grids.pop() if len(grids) == 1 else None

    def aggregate(self, fn: str, op: str, out_ts: np.ndarray, window_ms: int,
                  group_ids_per_shard: list[np.ndarray], num_groups: int,
                  args=(0.0, 0.0), fetch: bool = True):
        ts_g, val_g, n_g = self.dstore.arrays()
        devs = list(self.dstore.mesh.devices.ravel())
        gids = self.dstore._global(
            [jax.device_put(jnp.asarray(g, jnp.int32), d)
             for g, d in zip(group_ids_per_shard, devs)], (self.dstore.S,), jnp.int32)
        G = _pow2(num_groups)
        S, C, T = self.dstore.S, self.dstore.C, len(out_ts)
        grid = (self._fused_grid()
                if fn in fusedgrid.FUSED_FNS and op in fusedgrid.FUSED_OPS
                and fusedgrid.fusable(S, C, T, G) else None)
        if grid is not None:
            base_ts, interval_ms = grid
            Tp = (max(T, 1) + 127) // 128 * 128
            # cached per query shape — repeated [C, Tp] band uploads would
            # dominate on a tunneled device link (same cache as single-chip)
            band, ohlo, lo, hi, rel, c0, Ck = fusedgrid._device_operands(
                C, Tp, np.ascontiguousarray(np.asarray(out_ts, np.int64)).tobytes(),
                int(window_ms), base_ts, int(interval_ms))
            with jax.enable_x64(False):
                out = dist_fused_aggregate(
                    val_g, n_g, gids, band, ohlo, lo, hi, rel,
                    fn, op, G, self.dstore.mesh, int(window_ms),
                    int(interval_ms), S, C, Tp, c0, Ck)
            self.last_path = "fused"
            res = LazyMeshResult(out, num_groups, T)
            return res.resolve() if fetch else res
        # bucket the step count (pad to a multiple of 32, repeating the last
        # step): dist_aggregate jit-compiles per output shape and ad-hoc
        # dashboards would otherwise recompile per query — the same compile-
        # space bucketing as the in-process path
        from ..query.exec import _pad_steps
        out_eval, T = _pad_steps(np.asarray(out_ts, np.int64))
        out = dist_aggregate(ts_g, val_g, n_g, gids, jnp.asarray(out_eval),
                             jnp.int64(window_ms), jnp.float64(args[0]),
                             jnp.float64(args[1]), fn, op, G, self.dstore.mesh)
        self.last_path = "twostep"
        res = LazyMeshResult(out, num_groups, T)
        return res.resolve() if fetch else res


def _pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p
