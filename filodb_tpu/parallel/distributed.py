"""Distributed query execution over a jax device mesh.

Reference: the Akka scatter-gather plane — ExecPlans Kryo-dispatched to per-shard
QueryActors, partial aggregates reduced on the calling node
(coordinator/.../queryengine2/QueryEngine.scala:59-67, query/.../exec/ExecPlan.scala
NonLeafExecPlan.dispatchRemotePlan, client/Serializer.scala Kryo wire).

TPU-native replacement: shards live on mesh devices ("shard" axis); one
``shard_map``-compiled program evaluates the range function on every shard's
resident block and reduces partial aggregates with ``psum`` over ICI — the
collective *is* the scatter-gather. No serialization, no per-shard dispatch.

The same partial-aggregate format as the in-process path (ops/aggregators.py)
crosses the collective, so single-chip and multi-chip execution share semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import aggregators, rangefns


def make_mesh(devices=None, axis: str = "shard") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


class DistributedStore:
    """Global sharded view over per-shard device stores.

    Each TimeSeriesShard's SeriesStore already lives on one mesh device; this
    assembles the per-device blocks into global arrays [NSHARD, S, C] sharded on
    the "shard" mesh axis with ``make_array_from_single_device_arrays`` — zero
    copy, the shards' HBM blocks become one logical array.
    """

    def __init__(self, mesh: Mesh, shards):
        self.mesh = mesh
        self.shards = shards
        ns = len(shards)
        assert ns == mesh.devices.size, "one shard per mesh device"
        s0 = shards[0].store
        self.S, self.C = s0.S, s0.C
        self.sharding = NamedSharding(mesh, P("shard"))

    def _global(self, per_shard_arrays, extra_shape, dtype):
        ns = len(self.shards)
        shape = (ns,) + extra_shape
        arrs = [a.reshape((1,) + extra_shape) for a in per_shard_arrays]
        return jax.make_array_from_single_device_arrays(
            shape, self.sharding, arrs)

    def arrays(self):
        ts = self._global([s.store.ts for s in self.shards], (self.S, self.C), jnp.int64)
        val = self._global([s.store.val for s in self.shards], (self.S, self.C), None)
        n = self._global([s.store.n for s in self.shards], (self.S,), jnp.int32)
        return ts, val, n


@functools.partial(jax.jit, static_argnames=("fn", "op", "num_groups", "mesh"))
def dist_aggregate(ts_g, val_g, n_g, gids_g, out_ts, window_ms, a0, a1,
                   fn: str, op: str, num_groups: int, mesh: Mesh):
    """One compiled distributed query step: range function per shard block +
    segment partials + psum over the shard axis; every shard ends with the same
    [G, T] final matrix (taken from shard 0 by the caller)."""

    def per_shard(ts, val, n, gids):
        acc = jnp.float64 if val.dtype == jnp.float64 else jnp.float32
        mat = rangefns._periodic(fn, ts[0], val[0], n[0], out_ts, window_ms,
                                 a0, a1, w_cap=256, acc=acc)
        parts = aggregators.partial_aggregate(op, mat, gids[0], num_groups)
        parts = {k: jax.lax.psum(v, "shard") if k not in ("min", "max")
                 else (jax.lax.pmin(v, "shard") if k == "min" else jax.lax.pmax(v, "shard"))
                 for k, v in parts.items()}
        return aggregators.present_partials(op, parts)[None]

    return jax.shard_map(
        per_shard, mesh=mesh,
        in_specs=(P("shard"), P("shard"), P("shard"), P("shard")),
        out_specs=P("shard"),
    )(ts_g, val_g, n_g, gids_g)


class MeshQueryExecutor:
    """Runs aggregation queries over a DistributedStore (used by the engine when
    a mesh is configured; falls back to in-process scatter-gather otherwise)."""

    def __init__(self, dstore: DistributedStore):
        self.dstore = dstore

    def aggregate(self, fn: str, op: str, out_ts: np.ndarray, window_ms: int,
                  group_ids_per_shard: list[np.ndarray], num_groups: int,
                  args=(0.0, 0.0)):
        ts_g, val_g, n_g = self.dstore.arrays()
        devs = list(self.dstore.mesh.devices.ravel())
        gids = self.dstore._global(
            [jax.device_put(jnp.asarray(g, jnp.int32), d)
             for g, d in zip(group_ids_per_shard, devs)], (self.dstore.S,), jnp.int32)
        G = _pow2(num_groups)
        out = dist_aggregate(ts_g, val_g, n_g, gids, jnp.asarray(out_ts),
                             jnp.int64(window_ms), jnp.float64(args[0]),
                             jnp.float64(args[1]), fn, op, G, self.dstore.mesh)
        # all shards hold identical presented results; take shard 0's block
        return np.asarray(out.addressable_shards[0].data[0])[:num_groups]


def _pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p
