"""Distributed query execution over a jax device mesh.

Reference: the Akka scatter-gather plane — ExecPlans Kryo-dispatched to per-shard
QueryActors, partial aggregates reduced on the calling node
(coordinator/.../queryengine2/QueryEngine.scala:59-67, query/.../exec/ExecPlan.scala
NonLeafExecPlan.dispatchRemotePlan, client/Serializer.scala Kryo wire).

TPU-native replacement: shards live on mesh devices ("shard" axis); every
``dist_*`` collective below is a thin wrapper over ONE global-view sharded
executable per padded query shape — select -> decode -> window -> segment
reduce -> cross-shard fold lower as a single program, so XLA overlaps decode
compute against the reduce collectives. The collective *is* the
scatter-gather: no serialization, no per-shard dispatch.

Two execution modes (config ``query.mesh_programs``):

  * ``pjit``      — the per-shard body (PR 9's fused tiling plan / the
                    two-step kernels, unchanged) wraps in ``shard_map`` and
                    jits with EXPLICIT ``in_shardings``/``out_shardings``
                    (``NamedSharding`` per operand) plus donation of the
                    per-query group-id globals. Declaring both sides is
                    mandatory: implicit propagation would silently re-gather
                    sharded store operands (filolint
                    ``mesh-sharding-undeclared`` enforces this statically).
  * ``shard_map`` — the plain jitted ``shard_map`` path (no declared
                    boundary shardings); the fallback for single-device CPU
                    CI, per the jax_graft fallback pattern (SNIPPETS.md [2]).
  * ``auto``      — ``pjit`` on a multi-device non-CPU backend, else
                    ``shard_map``.

Reduction schedule: float partial sums do NOT psum — psum's fold order is
implementation-defined and may reassociate per shape, and an in-program f32
fold rounds differently from the host reduce's float64 accumulator. Instead
each device returns its stacked per-slot partial state and the caller folds
on host in SHARD order (slot-major, device-minor) with the same float64
accumulation and presenter as the scatter-gather merge
(exec._merge_partials) — the mesh result is bit-equal to the host path, and
stable across padded-T step buckets (the PR 13 fold-order caveat, closed
here together with exec.py's stable segment reduce). Sketch counts remain
psum'd: they are small integers in f32, exact under any summation order.

The same partial-aggregate format as the in-process path (ops/aggregators.py)
crosses the collective, so single-chip and multi-chip execution share semantics.

Deliberately NOT lowered here: count_values — its partial state is keyed by
rendered value strings (no fixed-size device layout to all_gather), and the
host merge it rides measures at 1.1% of total query time at bench scale
(bench_suite `count_values`, BENCH_SUITE_r07.json), so a hashed-value-bucket
device layout would optimize a rounding error. Cross-HOST peers (shards owned
by other OS processes) take the HTTP data plane instead: query/wire.py ships
per-peer batched envelopes and co-located reduces (see query/planner.py
_collapse_remote) — the collectives below cover co-resident shards only.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import aggregators, fusedgrid, rangefns
from ..utils import shard_map as _shard_map
from ..utils.metrics import (FILODB_QUERY_MESH_FALLBACK,
                             FILODB_QUERY_MESH_SERVED, registry)


def make_mesh(devices=None, axis: str = "shard") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


# ---------------------------------------------------------------------------
# mesh-program mode (config: query.mesh_programs / query.mesh_donation) —
# the same module-level dial pattern as ops/fusedresident.set_mode
# ---------------------------------------------------------------------------

MESH_MODES = ("auto", "pjit", "shard_map")
_mesh_mode = "auto"
_mesh_donation = True


def mesh_mode() -> str:
    """The configured mesh-program mode ("auto" | "pjit" | "shard_map")."""
    return _mesh_mode


def set_mesh_mode(m: str) -> None:
    """Select the mesh-program mode (config: ``query.mesh_programs``)."""
    global _mesh_mode
    if m not in MESH_MODES:
        raise ValueError(f"query.mesh_programs must be one of {MESH_MODES}, "
                         f"got {m!r}")
    _mesh_mode = m


def set_mesh_donation(flag: bool) -> None:
    """Enable/disable operand donation (config: ``query.mesh_donation``)."""
    global _mesh_donation
    _mesh_donation = bool(flag)


def resolved_mesh_mode(mesh: Mesh | None = None) -> str:
    """The mode a dispatch will actually use: ``auto`` resolves to ``pjit``
    on a multi-device non-CPU backend and falls back to ``shard_map`` on
    single-device / CPU CI (the SNIPPETS.md fallback rule)."""
    if _mesh_mode != "auto":
        return _mesh_mode
    ndev = mesh.devices.size if mesh is not None else len(jax.devices())
    return "pjit" if ndev > 1 and jax.default_backend() != "cpu" \
        else "shard_map"


def _donate_argnums(donate: tuple) -> tuple:
    """Donation is declared only where XLA can honor it: the CPU backend
    lacks buffer donation (jax warns and ignores it), so CI keeps clean
    logs while TPU/GPU runs reuse the per-query group-id buffers."""
    if not _mesh_donation or jax.default_backend() == "cpu":
        return ()
    return donate


def count_mesh_served(route: str, mode: str) -> None:
    registry.counter(FILODB_QUERY_MESH_SERVED,
                     {"route": route, "mode": mode}).increment()


def count_mesh_fallback(reason: str) -> None:
    """A mesh-eligible dispatch fell back to the host scatter-gather path
    AFTER eligibility (cold data paging, order-stat caps, ...)."""
    registry.counter(FILODB_QUERY_MESH_FALLBACK,
                     {"reason": reason}).increment()


def _is_pspec(x) -> bool:
    return isinstance(x, P)


def _sharded_jit(mesh: Mesh, in_specs, out_specs, donate: tuple = ()):
    """The pjit-mode jit applicator: every ``PartitionSpec`` leaf in the
    operand trees becomes an explicit ``NamedSharding`` on ``mesh`` and BOTH
    ``in_shardings`` and ``out_shardings`` are declared (the jax_graft
    pattern — SNIPPETS.md [2]/[3]: pjit requires both or falls back to
    shard_map; an implicit side would silently re-gather sharded store
    operands through host memory)."""
    def to_shardings(tree):
        return jax.tree.map(lambda s: NamedSharding(mesh, s), tree,
                            is_leaf=_is_pspec)
    in_shardings = to_shardings(in_specs)
    out_shardings = to_shardings(out_specs)
    donate = _donate_argnums(donate)

    def wrap(fn):
        return jax.jit(fn, in_shardings=in_shardings,
                       out_shardings=out_shardings, donate_argnums=donate)
    return wrap


class DistributedStore:
    """Global sharded view over per-shard device stores.

    Each TimeSeriesShard's SeriesStore already lives on one mesh device; this
    assembles the per-device blocks into global arrays [NDEV, S, C] sharded on
    the "shard" mesh axis with ``make_array_from_single_device_arrays`` — zero
    copy, the shards' HBM blocks become one logical array.

    Shards-per-device >= 1: with ``ns == slots * ndev`` shards placed
    round-robin (shard i on device i % ndev — standalone's placement), slot j
    assembles the global array of shards ``[j*ndev + d for d]``; programs
    loop the per-device slot blocks at trace time and reduce locally before
    the collective. No concatenation — per-slot views stay zero-copy."""

    def __init__(self, mesh: Mesh, shards):
        self.mesh = mesh
        self.shards = shards
        ns = len(shards)
        ndev = mesh.devices.size
        assert ns % ndev == 0, "shards must divide evenly over mesh devices"
        self.slots = ns // ndev
        self.ndev = ndev
        s0 = shards[0].store
        self.S, self.C = s0.S, s0.C
        self.sharding = NamedSharding(mesh, P("shard"))

    def _global(self, per_shard_arrays, extra_shape, dtype):
        ndev = len(per_shard_arrays)
        shape = (ndev,) + extra_shape
        arrs = [a.reshape((1,) + extra_shape) for a in per_shard_arrays]
        return jax.make_array_from_single_device_arrays(
            shape, self.sharding, arrs)

    def _slot(self, j: int):
        return [self.shards[j * self.ndev + d] for d in range(self.ndev)]

    def arrays(self):
        """Per-slot tuples of (ts, val, n) global arrays. Narrow-resident
        shards contribute TRANSIENT decodes (ts_block/value_block run on the
        shard's own device, so placement is unchanged) — the general
        collectives read the same f32/i64 view either way; the fused route
        streams the compressed state instead via :meth:`narrow_arrays`."""
        out = []
        for j in range(self.slots):
            ss = self._slot(j)
            out.append((
                self._global([s.store.ts_block() for s in ss],
                             (self.S, self.C), jnp.int64),
                self._global([s.store.value_block() for s in ss],
                             (self.S, self.C), None),
                self._global([s.store.n for s in ss], (self.S,), jnp.int32)))
        return out

    def value_arrays(self):
        """Per-slot (val, n) global arrays — the fused route never reads ts,
        so narrow-resident shards skip the i64 grid derivation entirely."""
        out = []
        for j in range(self.slots):
            ss = self._slot(j)
            out.append((
                self._global([s.store.value_block() for s in ss],
                             (self.S, self.C), None),
                self._global([s.store.n for s in ss], (self.S,), jnp.int32)))
        return out

    def narrow_arrays(self):
        """``(kind, slots)`` where slots are per-slot (block, row_operands, n)
        global arrays of the narrow-resident state, or None unless EVERY
        shard is narrow-resident with the SAME decode variant and no live
        cohort-pool rows (a pool row would need a per-shard row-wise fix,
        and a mixed-variant fleet would need one program per kind — those
        stores take the transient-decode fused route instead). ``kind`` is
        the decode-variant name (ops/decodereg.py: quant16/delta16/delta8)
        and ``row_operands`` its per-series rows (vmin/scale or anchor)."""
        per_shard, kinds = [], set()
        for sh in self.shards:
            nd = sh.store.narrow_operands()
            if nd is None:
                return None
            kind, ops, ok = nd
            if (~ok & (sh.store.n_host > 0)).any():
                return None
            kinds.add(kind)
            per_shard.append(ops)
        if len(kinds) != 1:
            return None
        kind = kinds.pop()
        nrows = len(per_shard[0]) - 1
        out = []
        for j in range(self.slots):
            ss = self._slot(j)
            ops = per_shard[j * self.ndev:(j + 1) * self.ndev]
            out.append((
                self._global([o[0] for o in ops], (self.S, self.C), None),
                tuple(self._global([o[r] for o in ops], (self.S,), None)
                      for r in range(1, nrows + 1)),
                self._global([s.store.n for s in ss], (self.S,), jnp.int32)))
        return kind, tuple(out)

    def global_gids(self, group_ids_per_shard):
        """Per-slot global [NDEV, S] gid arrays, device_put to each shard's
        device (caller passes one [S] array per shard, shard order). Built
        fresh per dispatch, so pjit-mode programs may DONATE them."""
        out = []
        for j in range(self.slots):
            arrs = []
            for d in range(self.ndev):
                sh = self.shards[j * self.ndev + d]
                g = group_ids_per_shard[j * self.ndev + d]
                # n is resident under every residency state (ts may be elided)
                dev = list(sh.store.n.devices())[0]
                arrs.append(jax.device_put(jnp.asarray(g, jnp.int32), dev))
            out.append(self._global(arrs, (self.S,), jnp.int32))
        return out


def _slot_matrix(fn, slot_tvn, slot_gids, out_ts, window_ms, a0, a1):
    """Yield the per-slot [S, T] matrix + [S] gids of THIS device's blocks."""
    for (ts, val, n), gids in zip(slot_tvn, slot_gids):
        acc = jnp.float64 if val.dtype == jnp.float64 else jnp.float32
        mat = rangefns._periodic(fn, ts[0], val[0], n[0], out_ts, window_ms,
                                 a0, a1, w_cap=256, acc=acc)
        yield mat, gids[0]


def _stack_parts(slot_parts):
    """Per-device partial state, stacked [NSLOT, G, T] under a unit shard
    axis; ``out_specs=P("shard")`` concatenates the devices into one
    [NDEV, NSLOT, G, T] global per partial key. The cross-shard fold is
    deliberately NOT a device collective: psum's reduction order is
    implementation-defined (and shape-dependent), and an in-program f32
    fold rounds differently from the host reduce's f64 accumulator. The
    caller (LazyMeshResult.resolve) folds these blocks on host in SHARD
    order — slot-major, device-minor, shard ``j*ndev + d`` — with the same
    float64 accumulation as the scatter-gather merge (exec._merge_partials),
    so the mesh answer is bit-EQUAL to the host-loop path, not merely
    allclose, and invariant across mesh program shapes."""
    return {k: jnp.stack([p[k] for p in slot_parts])[None]
            for k in slot_parts[0]}


def _dist_program(kernel: str, statics: tuple, slot_shapes: tuple, build,
                  mesh: Mesh, in_specs=None, out_specs=None,
                  donate: tuple = ()):
    """Mesh twin of the in-process kernel routing: every ``dist_*``
    collective below is a per-key program in the SAME process-global
    compiled-plan cache (query/plancache.py), keyed on its statics plus the
    global-array slot shapes plus the mesh axes AND the resolved mode — a
    pjit program never aliases a shard_map one, and neither aliases the
    per-shard in-process entries (distinct kernel names). A dashboard's
    first mesh query compiles here, every repeat (and every warmup-covered
    shape) hits.

    In ``pjit`` mode the entry jits with the explicit boundary shardings
    (and donation) from ``_sharded_jit`` — both spec trees are REQUIRED, the
    runtime twin of filolint's ``mesh-sharding-undeclared`` rule."""
    from ..query.plancache import plan_cache
    mode = resolved_mesh_mode(mesh)
    wrap = None
    if mode == "pjit":
        if in_specs is None or out_specs is None:
            raise ValueError(
                f"{kernel}: pjit mode requires both in_specs and out_specs "
                "(implicit propagation would re-gather sharded operands)")
        wrap = _sharded_jit(mesh, in_specs, out_specs, donate)
    key = statics + slot_shapes + ("mesh", mesh.axis_names,
                                   mesh.devices.size, mode)
    return plan_cache.program(kernel, key, build, wrap=wrap)


def _tvn_shapes(slot_tvn) -> tuple:
    return tuple((tuple(ts.shape), tuple(n.shape), str(val.dtype))
                 for ts, val, n in slot_tvn)


# in_shardings prefix trees for the two-step collectives: the call signature
# is (slot_tvn, slot_gids, out_ts, window_ms, a0, a1) — store operands ride
# the "shard" axis, step grid and window args replicate
_TWOSTEP_IN_SPECS = (P("shard"), P("shard"), P(), P(), P(), P())


def dist_aggregate(slot_tvn, slot_gids, out_ts, window_ms, a0, a1,
                   fn: str, op: str, num_groups: int, mesh: Mesh):
    return _dist_program(
        "dist-agg", (fn, op, num_groups, mesh, int(out_ts.shape[0])),
        _tvn_shapes(slot_tvn),
        lambda: functools.partial(_dist_aggregate_impl, fn, op, num_groups,
                                  mesh),
        mesh, in_specs=_TWOSTEP_IN_SPECS, out_specs=P("shard"), donate=(1,)
    )(slot_tvn, slot_gids, out_ts, window_ms, a0, a1)


def _dist_aggregate_impl(fn: str, op: str, num_groups: int, mesh: Mesh,
                         slot_tvn, slot_gids, out_ts, window_ms, a0, a1):
    """One compiled distributed query step: range function per resident slot
    block + STABLE segment partials per device, stacked for the host-order
    fold (LazyMeshResult.resolve presents them with the SAME reduce + host
    presenter the scatter-gather path uses — bit parity by construction)."""

    def per_device(slot_tvn, slot_gids):
        slot_parts = []
        for mat, gids in _slot_matrix(fn, slot_tvn, slot_gids, out_ts,
                                      window_ms, a0, a1):
            slot_parts.append(aggregators.partial_aggregate(
                op, mat, gids, num_groups, stable=True))
        return _stack_parts(slot_parts)

    return _shard_map(
        per_device, mesh=mesh,
        in_specs=(P("shard"), P("shard")),
        out_specs=P("shard"),
    )(slot_tvn, slot_gids)


def dist_quantile_sketch(slot_tvn, slot_gids, out_ts, window_ms, a0, a1,
                         fn: str, num_groups: int, mesh: Mesh):
    return _dist_program(
        "dist-sketch", (fn, num_groups, mesh, int(out_ts.shape[0])),
        _tvn_shapes(slot_tvn),
        lambda: functools.partial(_dist_quantile_sketch_impl, fn, num_groups,
                                  mesh),
        mesh, in_specs=_TWOSTEP_IN_SPECS, out_specs=P("shard"), donate=(1,)
    )(slot_tvn, slot_gids, out_ts, window_ms, a0, a1)


def _dist_quantile_sketch_impl(fn: str, num_groups: int, mesh: Mesh,
                               slot_tvn, slot_gids, out_ts, window_ms,
                               a0, a1):
    """Distributed quantile map phase: per-slot range function -> DDSketch
    log-bucket counts scattered on device -> psum over the shard axis.
    Bucketing matches ops/aggregators.quantile_sketch bit-for-bit (same
    gamma/width/edge rules) so the psum'd counts present identically to the
    host merge (ref: AggrOverRangeVectors t-digest partials crossing the
    reduce, :244). Counts are small integers in f32 — exact under ANY
    summation order, so psum needs no ordered-fold replacement here."""
    B = aggregators.SKETCH_BUCKETS
    W = aggregators.SKETCH_WIDTH
    lg = float(np.log(aggregators.SKETCH_GAMMA))

    def per_device(slot_tvn, slot_gids):
        T = out_ts.shape[0]
        counts = jnp.zeros((num_groups * W, T), jnp.float32)
        for mat, gids in _slot_matrix(fn, slot_tvn, slot_gids, out_ts,
                                      window_ms, a0, a1):
            matf = mat.astype(jnp.float64)
            mag = jnp.abs(matf)
            bi = jnp.ceil(jnp.log(mag / aggregators.SKETCH_MIN) / lg)
            bi = jnp.nan_to_num(bi, nan=1.0, posinf=B - 1, neginf=1.0)
            bi = jnp.clip(bi, 1, B - 1).astype(jnp.int32)
            idx = jnp.where(mag <= aggregators.SKETCH_MIN, B,
                            jnp.where(matf > 0, B + bi, B - bi))
            idx = jnp.where(jnp.isposinf(matf), 2 * B, idx)
            idx = jnp.where(jnp.isneginf(matf), 0, idx)
            # rows outside the selection carry an out-of-range gid; mask
            # BEFORE the id arithmetic (gid * W would overflow/wrap back
            # into range) and zero their scatter weight
            sel = gids < num_groups
            g = jnp.where(sel, gids, 0)
            w = jnp.where(jnp.isnan(matf) | ~sel[:, None], 0.0,
                          1.0).astype(jnp.float32)
            comb = g[:, None] * W + idx
            tix = jnp.broadcast_to(jnp.arange(T)[None, :], comb.shape)
            counts = counts.at[comb, tix].add(w)
        counts = jax.lax.psum(counts, "shard")
        return counts.reshape(1, num_groups, W, T)

    return _shard_map(
        per_device, mesh=mesh,
        in_specs=(P("shard"), P("shard")),
        out_specs=P("shard"),
    )(slot_tvn, slot_gids)


def dist_topk(slot_tvn, slot_gids, out_ts, window_ms, a0, a1,
              fn: str, k: int, bottom: bool, num_groups: int, mesh: Mesh,
              ndev: int):
    return _dist_program(
        "dist-topk",
        (fn, k, bottom, num_groups, mesh, ndev, int(out_ts.shape[0])),
        _tvn_shapes(slot_tvn),
        lambda: functools.partial(_dist_topk_impl, fn, k, bottom, num_groups,
                                  mesh, ndev),
        mesh, in_specs=_TWOSTEP_IN_SPECS,
        out_specs=(P("shard"), P("shard"), P("shard"), P("shard")),
        donate=(1,)
    )(slot_tvn, slot_gids, out_ts, window_ms, a0, a1)


def _dist_topk_impl(fn: str, k: int, bottom: bool, num_groups: int,
                    mesh: Mesh, ndev: int,
                    slot_tvn, slot_gids, out_ts, window_ms, a0, a1):
    """Distributed topk/bottomk: per-slot local top-k candidates, then ONE
    all_gather of the fixed-size [G, T, slots*k] candidate blocks and a
    global re-select — only k*shards candidates cross the ICI, never the
    [S, T] matrices (ref: TopKPartial crossing the reduce node). all_gather
    is device-ordered, so the candidate block order equals the host merge's
    shard order and ties resolve identically (top_k is index-stable).
    Returns (values, rows, shard_ids, present) each [G, T, k]; rows are
    store rows on the owning shard."""
    fmax = float(np.finfo(np.float64).max)
    fill = np.inf if bottom else -np.inf

    def per_device(slot_tvn, slot_gids):
        T = out_ts.shape[0]
        dev = jax.lax.axis_index("shard")
        vs, rs, ss, oks = [], [], [], []
        for j, (mat, gids) in enumerate(_slot_matrix(
                fn, slot_tvn, slot_gids, out_ts, window_ms, a0, a1)):
            matf = mat.astype(jnp.float64)
            valid = ~jnp.isnan(matf)
            # real +/-Inf must outrank empty (fill) slots on ties: clamp to
            # +/-DBL_MAX in the sort domain only (same rule as _map_topk)
            sortable = jnp.clip(matf, -fmax, fmax)
            kk = min(k, matf.shape[0])
            gv_l, gr_l, gok_l = [], [], []
            for gi in range(num_groups):
                m = (gids == gi)[:, None] & valid
                sv = jnp.where(m, sortable, fill)
                sv = -sv if bottom else sv
                _, topi = jax.lax.top_k(sv.T, kk)            # [T, kk]
                gv_l.append(jnp.take_along_axis(matf.T, topi, axis=1))
                gr_l.append(topi)
                gok_l.append(jnp.take_along_axis(m.T, topi, axis=1))
            vs.append(jnp.stack(gv_l))                       # [G, T, kk]
            rs.append(jnp.stack(gr_l))
            oks.append(jnp.stack(gok_l))
            ss.append(jnp.full((num_groups, T, kk),
                               j * ndev, jnp.int32) + dev)
        lv = jnp.concatenate(vs, axis=2)
        lr = jnp.concatenate(rs, axis=2).astype(jnp.int32)
        lsh = jnp.concatenate(ss, axis=2)
        lok = jnp.concatenate(oks, axis=2)
        gv = jnp.moveaxis(jax.lax.all_gather(lv, "shard"), 0, 2)
        gr = jnp.moveaxis(jax.lax.all_gather(lr, "shard"), 0, 2)
        gsh = jnp.moveaxis(jax.lax.all_gather(lsh, "shard"), 0, 2)
        gok = jnp.moveaxis(jax.lax.all_gather(lok, "shard"), 0, 2)
        C = gv.shape[2] * gv.shape[3]
        gv = gv.reshape(num_groups, T, C)
        gr = gr.reshape(num_groups, T, C)
        gsh = gsh.reshape(num_groups, T, C)
        gok = gok.reshape(num_groups, T, C)
        sv = jnp.where(gok, jnp.clip(gv, -fmax, fmax), fill)
        sv = -sv if bottom else sv
        kk2 = min(k, C)
        _, sel = jax.lax.top_k(sv, kk2)                      # [G, T, kk2]
        return (jnp.take_along_axis(gv, sel, axis=2)[None],
                jnp.take_along_axis(gr, sel, axis=2)[None],
                jnp.take_along_axis(gsh, sel, axis=2)[None],
                jnp.take_along_axis(gok, sel, axis=2)[None])

    return _shard_map(
        per_device, mesh=mesh,
        in_specs=(P("shard"), P("shard")),
        out_specs=(P("shard"), P("shard"), P("shard"), P("shard")),
    )(slot_tvn, slot_gids)


def _fused_map_call(fn: str, needs_sumsq: bool, window_ms: int,
                    interval_ms: int, S: int, Sb: int, C: int, Tp: int,
                    G: int, residency: str, c0: int, Ck: int, variant: str):
    """The per-shard fused map-phase program by backend variant — the
    Pallas kernel or its XLA-fused scan twin (same tiling plan, same
    tile_contrib math; ops/fusedgrid.py). ``residency`` names the decode
    variant streamed through the kernel (ops/decodereg.py);
    ``query.fused_kernels`` picks the backend and both ride the dist
    program's plan-cache key."""
    if variant == "xla":
        return fusedgrid.build_xla_tiles(fn, needs_sumsq, window_ms,
                                         interval_ms, S, Sb, C, Tp, G,
                                         residency=residency, c0=c0, Ck=Ck)
    return fusedgrid.build_pallas(fn, needs_sumsq, window_ms, interval_ms,
                                  S, Sb, C, Tp, G,
                                  jax.default_backend() != "tpu",
                                  residency=residency, c0=c0, Ck=Ck)


def _fused_parts(op: str, outs) -> dict:
    """The fused kernel's (sum, count, sumsq) tuple as a partial dict in the
    shared ops/aggregators format (count-only ops keep just the count)."""
    if op in ("count", "group"):
        return {"count": outs[1]}
    return dict(zip(("sum", "count", "sumsq"), outs))


# fused call signature: (slot_vals, slot_ns, slot_gids, band, ohlo, lo, hi,
# rel) — resident blocks and gids ride the shard axis; band/edge operands
# replicate (they are shape-cached per query, NEVER donated)
_FUSED_IN_SPECS = (P("shard"), P("shard"), P("shard"),
                   P(), P(), P(), P(), P())
# narrow call signature: (slot_blocks, slot_rows, slot_ns, slot_gids, band,
# ohlo, lo, hi, rel) — slot_rows is a NESTED tuple (one row-operand tuple
# per slot); the P("shard") spec is a pytree prefix that broadcasts over it,
# so one spec tree serves every decode variant's row count
_FUSED_NARROW_IN_SPECS = (P("shard"), P("shard"), P("shard"), P("shard"),
                          P(), P(), P(), P(), P())


def dist_fused_aggregate(slot_vals, slot_ns, slot_gids, band, ohlo, lo, hi, rel,
                         fn: str, op: str, num_groups: int, mesh: Mesh,
                         window_ms: int, interval_ms: int,
                         S: int, C: int, Tp: int, c0: int = 0, Ck: int = 0,
                         variant: str = "pallas"):
    return _dist_program(
        "dist-fused",
        (fn, op, num_groups, mesh, window_ms, interval_ms, S, C, Tp, c0, Ck,
         variant),
        tuple(str(v.dtype) for v in slot_vals),
        lambda: functools.partial(_dist_fused_aggregate_impl, fn, op,
                                  num_groups, mesh, window_ms, interval_ms,
                                  S, C, Tp, c0, Ck, variant),
        mesh, in_specs=_FUSED_IN_SPECS, out_specs=P("shard"), donate=(2,)
    )(slot_vals, slot_ns, slot_gids, band, ohlo, lo, hi, rel)


def _dist_fused_aggregate_impl(fn: str, op: str, num_groups: int, mesh: Mesh,
                               window_ms: int, interval_ms: int,
                               S: int, C: int, Tp: int, c0: int, Ck: int,
                               variant: str,
                               slot_vals, slot_ns, slot_gids, band, ohlo,
                               lo, hi, rel):
    """Fused single-pass map phase on every resident slot block, partial
    state stacked for the host-order fold — the multi-chip twin of
    ``fusedgrid.fused_grid_aggregate`` (ref: AggrOverRangeVectors.scala:62 —
    the same AggregateMapReduce map phase runs identically on every data
    node; LazyMeshResult.resolve IS the reduce node, in the host merge's
    shard order and precision). Band/edge operands are replicated; each
    device streams only its resident [S, C] blocks, one kernel pass per
    slot."""
    needs_sumsq = op in ("stddev", "stdvar")
    Sb = 512 if S % 512 == 0 else S
    call = _fused_map_call(fn, needs_sumsq, window_ms, interval_ms,
                           S, Sb, C, Tp, num_groups, "raw", c0, Ck, variant)

    def per_device(slot_vals, slot_ns, slot_gids, band, ohlo, lo, hi, rel):
        slot_parts = []
        for val, n, gids in zip(slot_vals, slot_ns, slot_gids):
            o = call(val[0].astype(jnp.float32),
                     n[0].astype(jnp.int32).reshape(S, 1),
                     gids[0].astype(jnp.int32).reshape(S, 1),
                     band, ohlo, lo, hi, rel)
            slot_parts.append(_fused_parts(op, o))
        return _stack_parts(slot_parts)

    return _shard_map(
        per_device, mesh=mesh,
        in_specs=(P("shard"), P("shard"), P("shard"), P(), P(), P(), P(), P()),
        out_specs=P("shard"),
        # pallas_call emits ShapeDtypeStructs without varying-mesh-axis
        # annotations; the kernel is per-shard-local so vma checking adds
        # nothing here
        check_vma=False,
    )(slot_vals, slot_ns, slot_gids, band, ohlo, lo, hi, rel)


def dist_fused_aggregate_narrow(slot_blocks, slot_rows, slot_ns,
                                slot_gids, band, ohlo, lo, hi, rel,
                                fn: str, op: str, num_groups: int, mesh: Mesh,
                                window_ms: int, interval_ms: int,
                                S: int, C: int, Tp: int,
                                kind: str = "quant16", c0: int = 0,
                                Ck: int = 0, variant: str = "pallas"):
    return _dist_program(
        "dist-fused-narrow",
        (fn, op, num_groups, mesh, window_ms, interval_ms, S, C, Tp, kind,
         c0, Ck, variant),
        tuple(str(b.dtype) for b in slot_blocks),
        lambda: functools.partial(_dist_fused_narrow_impl, fn, op,
                                  num_groups, mesh, window_ms, interval_ms,
                                  S, C, Tp, kind, c0, Ck, variant),
        mesh, in_specs=_FUSED_NARROW_IN_SPECS, out_specs=P("shard"),
        donate=(3,)
    )(slot_blocks, slot_rows, slot_ns, slot_gids, band, ohlo, lo, hi, rel)


def _dist_fused_narrow_impl(fn: str, op: str, num_groups: int, mesh: Mesh,
                            window_ms: int, interval_ms: int,
                            S: int, C: int, Tp: int, kind: str,
                            c0: int, Ck: int, variant: str,
                            slot_blocks, slot_rows, slot_ns,
                            slot_gids, band, ohlo, lo, hi, rel):
    """Narrow twin of :func:`dist_fused_aggregate`: every shard's resident
    narrow state (i16 quantized, or i16/i8 integer deltas off a per-series
    anchor — ops/decodereg.py names the variant) streams straight through
    the fused map kernel (1-2 bytes per sample over the HBM bus, decode in
    VMEM — ops/narrow.py) and the partial state folds over the shard axis
    in shard order. Compressed-resident stores stay mesh-eligible without
    ever materializing their f32 blocks."""
    needs_sumsq = op in ("stddev", "stdvar")
    Sb = 512 if S % 512 == 0 else S
    call = _fused_map_call(fn, needs_sumsq, window_ms, interval_ms,
                           S, Sb, C, Tp, num_groups, kind, c0, Ck, variant)

    def per_device(slot_blocks, slot_rows, slot_ns, slot_gids,
                   band, ohlo, lo, hi, rel):
        slot_parts = []
        for blk, rows, n, gids in zip(slot_blocks, slot_rows, slot_ns,
                                      slot_gids):
            o = call(blk[0], *(r[0].reshape(S, 1) for r in rows),
                     n[0].astype(jnp.int32).reshape(S, 1),
                     gids[0].astype(jnp.int32).reshape(S, 1),
                     band, ohlo, lo, hi, rel)
            slot_parts.append(_fused_parts(op, o))
        return _stack_parts(slot_parts)

    return _shard_map(
        per_device, mesh=mesh,
        in_specs=(P("shard"), P("shard"), P("shard"), P("shard"),
                  P(), P(), P(), P(), P()),
        out_specs=P("shard"),
        check_vma=False,
    )(slot_blocks, slot_rows, slot_ns, slot_gids, band, ohlo, lo, hi, rel)


class LazyMeshResult:
    """Device-resident distributed result; ``resolve()`` does the blocking
    host fetch. The engine dispatches under the shard locks but fetches
    outside them (same contract as the in-process leaf: a slow collective
    must not stall ingest on every shard for its full wall time).

    The mesh program returns UNFOLDED partial state (dict of
    [NDEV, NSLOT, G, T] globals — each device's stacked per-slot partials);
    resolve() folds them in SHARD order (slot-major, device-minor: shard
    ``j*ndev + d``) with the same float64 accumulation as the scatter-gather
    merge (exec._merge_partials), then presents with the SAME
    ``aggregators.present_partials`` host presenter the host-loop reduce
    uses — so the presented values carry no device/host dtype-promotion or
    fold-order skew and match the host path bit-for-bit."""

    def __init__(self, parts: dict, op: str, num_groups: int, T: int | None):
        self._parts = parts
        self._op = op
        self._ng = num_groups
        self._T = T

    def resolve(self) -> np.ndarray:
        host = {k: np.asarray(v) for k, v in self._parts.items()}
        merged: dict[str, np.ndarray] = {}
        for name, g in host.items():          # g: [NDEV, NSLOT, G, T]
            ndev, nslot = g.shape[0], g.shape[1]
            acc = g[0, 0].astype(np.float64)  # shard 0 seeds, exactly as the
            for j in range(nslot):            # host merge's first base does
                for d in range(ndev):
                    if j == 0 and d == 0:
                        continue
                    a = g[d, j]               # shard j*ndev + d
                    if name == "min":
                        acc = np.minimum(acc, a)
                    elif name == "max":
                        acc = np.maximum(acc, a)
                    else:
                        acc = acc + a
            merged[name] = acc
        vals = aggregators.present_partials(self._op, merged)[:self._ng]
        return vals[:, :self._T] if self._T is not None else vals


class MeshQueryExecutor:
    """Runs aggregation queries over a DistributedStore (used by the engine when
    a mesh is configured; falls back to in-process scatter-gather otherwise).

    Routing: when the query is fusable (rate/increase/delta into
    sum/avg/count/group/stddev/stdvar), every shard store is f32,
    grid-aligned to one common (base, interval) with a single uniform start
    cohort, and the shapes fit the fused kernel's VMEM gate, the per-shard
    map phase runs the single-pass fused Pallas kernel; otherwise the
    general two-step kernels. ``last_path`` records the route taken and
    ``last_mode`` the resolved mesh-program mode (pjit / shard_map)."""

    def __init__(self, dstore: DistributedStore):
        self.dstore = dstore
        self.last_path: str | None = None
        self.last_mode: str = resolved_mesh_mode(dstore.mesh)

    def _fused_grid(self):
        """Common (base_ts, interval_ms) when every shard qualifies for the
        fused map phase, else None."""
        grids = set()
        for sh in self.dstore.shards:
            st = sh.store
            if st is None or st.dtype != jnp.float32:
                return None
            gi = st.grid_info()
            if gi is None:
                return None
            kind, off = st.grid_cohorts()
            if kind != "uniform" or off != 0:
                return None
            grids.add(gi)
        return grids.pop() if len(grids) == 1 else None

    def aggregate(self, fn: str, op: str, out_ts: np.ndarray, window_ms: int,
                  group_ids_per_shard: list[np.ndarray], num_groups: int,
                  args=(0.0, 0.0), fetch: bool = True):
        slot_gids = tuple(self.dstore.global_gids(group_ids_per_shard))
        G = _pow2(num_groups)
        S, C, T = self.dstore.S, self.dstore.C, len(out_ts)
        self.last_mode = resolved_mesh_mode(self.dstore.mesh)
        from ..ops import fusedresident
        variant = fusedresident.mode()
        grid = (self._fused_grid()
                if variant != "off"
                and fn in fusedgrid.FUSED_FNS | fusedgrid.FUSED_WINDOW_FNS
                and op in fusedgrid.FUSED_OPS
                and fusedgrid.fusable(S, C, T, G) else None)
        if grid is not None:
            base_ts, interval_ms = grid
            Tp = (max(T, 1) + 127) // 128 * 128
            # narrow-resident shards stream their 1-2B/sample state through
            # the fused kernel; stores with cohort-pool rows (or raw
            # residency) feed it the f32 view instead (a transient decode
            # per shard when compressed — bit-identical by the round-trip
            # contract). Resolved BEFORE the band operands: delta variants
            # decode via a column-prefix cumsum, so they pin full columns
            narrow = self.dstore.narrow_arrays()
            kind = narrow[0] if narrow is not None else "raw"
            from ..ops import decodereg
            # cached per query shape — repeated [C, Tp] band uploads would
            # dominate on a tunneled device link (same cache as single-chip)
            band, ohlo, lo, hi, rel, c0, Ck = fusedgrid._device_operands(
                C, Tp, np.ascontiguousarray(np.asarray(out_ts, np.int64)).tobytes(),
                int(window_ms), base_ts, int(interval_ms),
                "window" if fn in fusedgrid.FUSED_WINDOW_FNS else "rate",
                decodereg.variant(kind).full_columns)
            from ..utils import enable_x64
            with enable_x64(False):
                if narrow is not None:
                    slots = narrow[1]
                    out = dist_fused_aggregate_narrow(
                        tuple(t[0] for t in slots),
                        tuple(t[1] for t in slots),
                        tuple(t[2] for t in slots),
                        slot_gids, band, ohlo, lo, hi, rel,
                        fn, op, G, self.dstore.mesh, int(window_ms),
                        int(interval_ms), S, C, Tp, kind, c0, Ck, variant)
                else:
                    slot_vn = tuple(self.dstore.value_arrays())
                    out = dist_fused_aggregate(
                        tuple(t[0] for t in slot_vn),
                        tuple(t[1] for t in slot_vn),
                        slot_gids, band, ohlo, lo, hi, rel,
                        fn, op, G, self.dstore.mesh, int(window_ms),
                        int(interval_ms), S, C, Tp, c0, Ck, variant)
            fusedresident.count_served(
                fusedresident.scalar_shape_of(fn) or "rate_sum")
            # exec-path keeps the historical "fused"/"fused-narrow" names
            # for the default pallas backend; the xla twin is suffixed
            sfx = "" if variant == "pallas" else "-xla"
            self.last_path = ("fused-narrow" if narrow is not None
                              else "fused") + sfx
            res = LazyMeshResult(out, op, num_groups, T)
            return res.resolve() if fetch else res
        slot_tvn = tuple(self.dstore.arrays())
        # bucket the step count (pad to a multiple of 32, repeating the last
        # step): dist_aggregate jit-compiles per output shape and ad-hoc
        # dashboards would otherwise recompile per query — the same compile-
        # space bucketing as the in-process path
        from ..query.exec import _pad_steps
        out_eval, T = _pad_steps(np.asarray(out_ts, np.int64))
        out = dist_aggregate(slot_tvn, slot_gids, jnp.asarray(out_eval),
                             jnp.int64(window_ms), jnp.float64(args[0]),
                             jnp.float64(args[1]), fn, op, G, self.dstore.mesh)
        self.last_path = "twostep"
        res = LazyMeshResult(out, op, num_groups, T)
        return res.resolve() if fetch else res

    def quantile(self, fn: str, out_ts: np.ndarray, window_ms: int,
                 group_ids_per_shard: list[np.ndarray], num_groups: int,
                 q: float, args=(0.0, 0.0)):
        """Distributed quantile: sketch counts psum over the mesh; returns a
        LazySketch whose resolve() presents [G, T] on host (same presenter as
        the in-process SketchPartial merge)."""
        slot_tvn = tuple(self.dstore.arrays())
        slot_gids = tuple(self.dstore.global_gids(group_ids_per_shard))
        self.last_mode = resolved_mesh_mode(self.dstore.mesh)
        from ..query.exec import _pad_steps
        out_eval, T = _pad_steps(np.asarray(out_ts, np.int64))
        # pow2-bucket the group count: a churning by() cardinality must not
        # compile a fresh program per distinct G (same rule as aggregate())
        Gp = _pow2(num_groups)
        out = dist_quantile_sketch(slot_tvn, slot_gids, jnp.asarray(out_eval),
                                   jnp.int64(window_ms), jnp.float64(args[0]),
                                   jnp.float64(args[1]), fn, Gp,
                                   self.dstore.mesh)
        self.last_path = "sketch"

        class LazySketch:
            def resolve(self_inner) -> np.ndarray:
                counts = np.asarray(
                    out.addressable_shards[0].data[0])[:num_groups, :, :T]
                return aggregators.present_quantile_sketch(counts, q)
        return LazySketch()

    def topk(self, fn: str, out_ts: np.ndarray, window_ms: int,
             group_ids_per_shard: list[np.ndarray], num_groups: int,
             k: int, bottom: bool, args=(0.0, 0.0)):
        """Distributed topk/bottomk: local candidates + ONE all_gather of
        fixed-size blocks + global re-select, all on the mesh. Returns a lazy
        handle resolving to (values [G, k, T], shard_ids, rows, present) —
        the caller maps (shard, row) back to series keys."""
        slot_tvn = tuple(self.dstore.arrays())
        slot_gids = tuple(self.dstore.global_gids(group_ids_per_shard))
        self.last_mode = resolved_mesh_mode(self.dstore.mesh)
        from ..query.exec import _pad_steps
        out_eval, T = _pad_steps(np.asarray(out_ts, np.int64))
        Gp = _pow2(num_groups)    # compile-space bucketing, as aggregate()
        outs = dist_topk(slot_tvn, slot_gids, jnp.asarray(out_eval),
                         jnp.int64(window_ms), jnp.float64(args[0]),
                         jnp.float64(args[1]), fn, int(k), bool(bottom),
                         Gp, self.dstore.mesh, self.dstore.ndev)
        self.last_path = "topk"

        class LazyTopK:
            def resolve(self_inner):
                v, r, sh, ok = (np.asarray(
                    o.addressable_shards[0].data[0])[:num_groups]
                    for o in outs)
                # [G, T, k] -> [G, k, T]; un-padded steps only
                mv = np.moveaxis(v, 2, 1)[:, :, :T]
                return (np.where(np.moveaxis(ok, 2, 1)[:, :, :T], mv, np.nan),
                        np.moveaxis(sh, 2, 1)[:, :, :T],
                        np.moveaxis(r, 2, 1)[:, :, :T],
                        np.moveaxis(ok, 2, 1)[:, :, :T])
        return LazyTopK()


def warm_mesh_shape(fn: str, op: str, S: int, C: int, steps: int,
                    step_ms: int, window_ms: int, interval_ms: int,
                    groups: int, dtype, grid: bool = True,
                    residency: str = "raw") -> None:
    """Pre-trace the mesh ``dist_*`` programs for one dashboard shape
    (``query.warmup_shapes`` entries with ``mesh: true`` — plancache.warmup
    calls this). Warms the general two-step program always and the fused
    program (the ACTIVE ``query.fused_kernels`` variant) when the shape
    qualifies — under the RESOLVED mesh mode, so the warmed executable is
    the serving executable. ``residency`` names a decode variant
    (ops/decodereg.py) to warm the narrow-streaming program for in addition
    to the raw one — the first dashboard hit on a compressed-resident fleet
    then compiles nothing."""
    from ..ops import fusedresident
    from ..query.exec import _pad_steps
    mesh = make_mesh()
    ndev = mesh.devices.size
    if ndev < 2:
        return
    sharding = NamedSharding(mesh, P("shard"))
    devs = list(mesh.devices.ravel())

    def gput(extra_shape, dt):
        arrs = [jax.device_put(jnp.zeros((1,) + extra_shape, dt), d)
                for d in devs]
        return jax.make_array_from_single_device_arrays(
            (ndev,) + extra_shape, sharding, arrs)

    out_ts = np.int64(window_ms) + np.arange(steps, dtype=np.int64) * step_ms
    out_eval, _T = _pad_steps(out_ts)
    Gp = _pow2(groups)
    val = gput((S, C), dtype)
    n = gput((S,), jnp.int32)
    ts = gput((S, C), jnp.int64)

    def gids():
        # gid globals are donated in pjit mode: build a fresh one per call
        return gput((S,), jnp.int32)

    dist_aggregate(((ts, val, n),), (gids(),), jnp.asarray(out_eval),
                   jnp.int64(window_ms), jnp.float64(0.0), jnp.float64(0.0),
                   fn, op, Gp, mesh)
    variant = fusedresident.mode()
    if (grid and variant != "off" and dtype == jnp.float32
            and fn in fusedgrid.FUSED_FNS | fusedgrid.FUSED_WINDOW_FNS
            and op in fusedgrid.FUSED_OPS
            and fusedgrid.fusable(S, C, steps, Gp)):
        Tp = (max(steps, 1) + 127) // 128 * 128
        band, ohlo, lo, hi, rel, c0, Ck = fusedgrid._device_operands(
            C, Tp, np.ascontiguousarray(out_ts).tobytes(), int(window_ms),
            0, int(interval_ms),
            "window" if fn in fusedgrid.FUSED_WINDOW_FNS else "rate")
        from ..utils import enable_x64
        with enable_x64(False):
            dist_fused_aggregate(
                (val,), (n,), (gids(),), band, ohlo, lo, hi, rel,
                fn, op, Gp, mesh, int(window_ms), int(interval_ms),
                S, C, Tp, c0, Ck, variant)
            if residency != "raw":
                from ..ops import decodereg
                var = decodereg.variant(residency)
                bandn, ohlon, lon, hin, reln, c0n, Ckn = (
                    fusedgrid._device_operands(
                        C, Tp, np.ascontiguousarray(out_ts).tobytes(),
                        int(window_ms), 0, int(interval_ms),
                        "window" if fn in fusedgrid.FUSED_WINDOW_FNS
                        else "rate", var.full_columns))
                blk = gput((S, C), var.block_dtype)
                rows = tuple(gput((S,), jnp.float32)
                             for _ in range(var.row_operands))
                dist_fused_aggregate_narrow(
                    (blk,), (rows,), (n,), (gids(),),
                    bandn, ohlon, lon, hin, reln,
                    fn, op, Gp, mesh, int(window_ms), int(interval_ms),
                    S, C, Tp, residency, c0n, Ckn, variant)


def _pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p
