"""Shard mapping: consistent series -> shard routing with spread.

Reference: coordinator/.../ShardMapper.scala:26 + doc/sharding.md:27-60 — the
shard-key hash (ws/ns/metric) selects a group of 2^spread shards; low bits of the
full part-key hash spread series within the group. Queries whose filters pin the
whole shard key only touch 2^spread shards.

TPU-native reading: a shard is a slice of the device mesh's "shard" axis; this
module is pure host arithmetic shared by ingest routing and the query planner.
"""

from __future__ import annotations

import numpy as np


class ShardMapper:
    def __init__(self, num_shards: int, spread: int = 0):
        assert num_shards & (num_shards - 1) == 0, "num_shards must be a power of two"
        assert (1 << spread) <= num_shards
        self.num_shards = num_shards
        self.spread = spread

    def shard_of(self, shard_hash: int, part_hash: int) -> int:
        """Upper bits from the shard-key hash pick the group; ``spread`` low bits
        from the part-key hash pick the member (ref: ShardMapper.ingestionShard)."""
        group_bits = self.num_shards.bit_length() - 1 - self.spread
        group = (shard_hash & 0xFFFFFFFF) % (1 << group_bits) if group_bits else 0
        member = part_hash & ((1 << self.spread) - 1)
        return (group << self.spread) | member

    def shards_vector(self, shard_hash: np.ndarray, part_hash: np.ndarray) -> np.ndarray:
        group_bits = self.num_shards.bit_length() - 1 - self.spread
        group = (shard_hash.astype(np.uint64) % np.uint64(1 << group_bits)) if group_bits \
            else np.zeros(len(shard_hash), np.uint64)
        member = part_hash.astype(np.uint64) & np.uint64((1 << self.spread) - 1)
        return ((group << np.uint64(self.spread)) | member).astype(np.int32)

    def shards_for_shard_key(self, shard_hash: int) -> list[int]:
        """All shards that may hold series of one shard key (query fan-out)."""
        base = self.shard_of(shard_hash, 0)
        return [base | m for m in range(1 << self.spread)]

    def all_shards(self) -> list[int]:
        return list(range(self.num_shards))
