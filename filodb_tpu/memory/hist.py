"""First-class histograms: bucket schemes + the compressed wire/storage codec.

Reference: memory/.../format/vectors/Histogram.scala (bucket schemes, quantile
:55,288), HistogramVector.scala (BinaryHistogram wire format, sectioned vectors),
doc/compression.md "Histograms" / "2D Delta Compression".

Buckets are *cumulative* (Prometheus-style: bucket b counts all observations
<= le[b]). On the wire each histogram's bucket array is delta-encoded (buckets
are non-decreasing) and NibblePacked; across time, consecutive histograms are
2D-delta encoded: the delta-of-deltas between histogram t and t-1 is usually
tiny for quiet series. This reproduces the reference's ~50x space win over the
one-series-per-bucket Prometheus data model (tested in test_hist.py).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

from . import nibblepack


@dataclass(frozen=True)
class GeometricBuckets:
    """le[i] = first * multiplier^i (ref: Histogram.scala GeometricBuckets)."""
    first: float
    multiplier: float
    num_buckets: int

    def les(self) -> np.ndarray:
        return self.first * self.multiplier ** np.arange(self.num_buckets)


@dataclass(frozen=True)
class CustomBuckets:
    """Explicit bucket tops, last is typically +Inf (ref: CustomBuckets)."""
    le: tuple

    def les(self) -> np.ndarray:
        return np.asarray(self.le, dtype=np.float64)


# ---- wire codec -------------------------------------------------------------

_HDR = struct.Struct("<HH")   # n_hists, n_buckets


def encode_hist_series_py(counts: np.ndarray) -> bytes:
    """counts [n, B] cumulative bucket counts (int64able) -> compressed bytes.

    Layout: header | per-histogram NibblePack'ed *increasing* delta arrays,
    where hist 0 packs its own bucket deltas and hist t>0 packs the 2D-delta
    (bucket-delta array minus previous histogram's bucket-delta array, zigzag).
    numpy spec implementation; the native twin (memory/native hist_encode)
    is bit-identical and handles the whole series in one call.
    """
    c = np.asarray(counts, dtype=np.int64)
    n, B = c.shape
    out = [_HDR.pack(n, B)]
    prev_deltas = None
    for i in range(n):
        deltas = np.diff(c[i], prepend=0)
        if prev_deltas is None:
            payload = nibblepack.pack_u64(deltas.astype(np.uint64))
        else:
            dd = deltas - prev_deltas
            payload = nibblepack.pack_u64(_zigzag(dd))
        out.append(payload)   # no per-hist framing: group count derives from B
        prev_deltas = deltas
    return b"".join(out)


def decode_hist_series_py(buf: bytes) -> np.ndarray:
    n, B = _HDR.unpack_from(buf, 0)
    off = _HDR.size
    out = np.zeros((n, B), np.int64)
    prev_deltas = None
    for i in range(n):
        words, used = nibblepack.unpack_u64_consumed(buf[off:], B); off += used
        if prev_deltas is None:
            deltas = words.astype(np.int64)
        else:
            deltas = prev_deltas + _unzigzag(words)
        out[i] = np.cumsum(deltas)
        prev_deltas = deltas
    return out


def _encode_native(counts: np.ndarray) -> bytes:
    from . import native
    c = np.asarray(counts, dtype=np.int64)
    n, B = c.shape
    return _HDR.pack(n, B) + native.hist_encode(c)


def _decode_native(buf) -> np.ndarray:
    from . import native
    n, B = _HDR.unpack_from(buf, 0)
    return native.hist_decode(buf[_HDR.size:], n, B)


def _bind():
    from . import native
    if native.available():
        return _encode_native, _decode_native
    return encode_hist_series_py, decode_hist_series_py


encode_hist_series, decode_hist_series = _bind()


def _zigzag(v: np.ndarray) -> np.ndarray:
    v = v.astype(np.int64)
    return ((v << 1) ^ (v >> 63)).astype(np.uint64)


def _unzigzag(u: np.ndarray) -> np.ndarray:
    u = u.astype(np.uint64)
    return ((u >> np.uint64(1)).astype(np.int64)) ^ -(u & np.uint64(1)).astype(np.int64)


# ---- quantile (host reference; the device kernel mirrors this) --------------

def histogram_quantile(q: float, les: np.ndarray, counts: np.ndarray) -> float:
    """Prometheus histogram_quantile on one cumulative histogram
    (ref: Histogram.scala quantile :288)."""
    total = counts[-1]
    if total == 0 or np.isnan(total):
        return np.nan
    if q < 0:
        return -np.inf
    if q > 1:
        return np.inf
    rank = q * total
    b = int(np.searchsorted(counts, rank, side="left"))
    b = min(b, len(les) - 1)
    if np.isinf(les[b]):
        # +Inf bucket: return the highest finite bound
        return les[b - 1] if b > 0 else np.nan
    lo_le = les[b - 1] if b > 0 else 0.0
    lo_cnt = counts[b - 1] if b > 0 else 0.0
    hi_cnt = counts[b]
    if hi_cnt == lo_cnt:
        return les[b]
    return lo_le + (les[b] - lo_le) * (rank - lo_cnt) / (hi_cnt - lo_cnt)
